"""Messaging fabric: topic/peer addressed, durable-queue semantics.

Reference: the `MessagingService` API (node/.../services/messaging/
Messaging.kt — send, addMessageHandler(topic), createMessage) backed in
production by an embedded Artemis broker with per-peer store-and-forward
queues and TLS bridges (ArtemisMessagingServer.kt:90,300-401), and in
Ring-3 tests by `InMemoryMessagingNetwork` with manually-pumped
deterministic delivery (test-utils/.../InMemoryMessagingNetwork.kt:47).

This module provides the API plus the in-memory fabric; the DCN (TCP)
fabric with durable queues lives in `corda_tpu.node.fabric`. Delivery
guarantees match Artemis semantics: per-(sender, target) FIFO, at-least-
once upstream with exactly-once to handlers via (sender, unique_id)
dedupe. Payloads are canonical-serialized bytes — even in-memory
delivery round-trips through the wire encoding so serialization gaps
surface in Ring-3 tests, not in production.
"""

from __future__ import annotations

import random
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

TOPIC_SESSION = "platform.session"
TOPIC_NETWORK_MAP = "platform.network_map"
TOPIC_RPC = "rpc.requests"
TOPIC_VERIFIER_REQ = "verifier.requests"
TOPIC_VERIFIER_RES = "verifier.responses"


@dataclass(frozen=True)
class Message:
    topic: str
    payload: bytes          # canonical-serialized body
    sender: str             # peer name of origin
    unique_id: int          # per-sender unique id (dedupe key)
    # OPTIONAL tracing header (utils/tracing.py): the sender's
    # (trace_id, span_id) SpanContext pair, so a receiver's spans join
    # the SAME trace — one connected tree per notarisation across the
    # fabric hop. None (the default) everywhere tracing is off; the
    # field is observability metadata, never consensus input.
    trace: Optional[tuple] = None
    # OPTIONAL deadline header (node/qos.py): absolute node-clock
    # microseconds after which the SENDER no longer wants an answer.
    # Consumers shed expired work at the cheapest point they notice it
    # (pre-decode at ingress, pre-stage at the notary flush) into a
    # typed `shed` response. QoS metadata, never consensus input — but
    # unlike `trace` it IS journaled across the TCP fabric: a frame
    # redelivered after a crash should still be shed if it expired.
    deadline: Optional[int] = None


Handler = Callable[[Message], None]


class MessagingService:
    """Send/handle interface every node component talks through."""

    def send(
        self,
        topic: str,
        payload: bytes,
        target: str,
        unique_id: Optional[int] = None,
        trace: Optional[tuple] = None,
        deadline: Optional[int] = None,
    ) -> None:
        """`trace`: optional tracing SpanContext header (see
        Message.trace); trace propagation is best-effort, delivery
        semantics are not. `deadline`: optional absolute-microsecond
        QoS header (Message.deadline) — both ride the fabric as
        headers, never as payload."""
        raise NotImplementedError

    def add_handler(self, topic: str, handler: Handler) -> None:
        raise NotImplementedError

    def add_ring(self, topic: str, ring, metrics=None) -> None:
        """OPTIONAL bulk-ingest seam (node/ingest.py): deliver `topic`
        messages into a bounded ring (`ring.offer(msg) -> bool`)
        instead of per-message handler dispatch, so a consumer can
        decode whole delivery rounds through the sharded ingest
        pipeline. A full ring parks the frame for redelivery
        (`retry_parked`) — backpressure without blocking the pump.
        Fabrics that don't implement it raise, and callers fall back
        to the per-message handler path.

        `metrics`: an optional MetricRegistry; implementations register
        ring-depth / high-water / parked-frame gauges for the topic so
        the backpressure is visible on /metrics BEFORE it stalls the
        pump (see register_ring_gauges)."""
        raise NotImplementedError(f"{type(self).__name__} has no ring seam")

    @property
    def my_address(self) -> str:
        raise NotImplementedError


def register_ring_gauges(metrics, topic: str, ring, parked_count=None) -> None:
    """Gauges over one topic's ingest ring: current depth, lifetime
    high-water mark, and (when the fabric exposes a counter) frames
    parked waiting for retry_parked. ONE naming scheme for every
    fabric, so dashboards don't fork per transport."""
    base = f"Ingest.{topic}.Ring"
    metrics.gauge(base + "Depth", lambda: len(ring))
    metrics.gauge(base + "HighWater", lambda: ring.high_water)
    if parked_count is not None:
        metrics.gauge(f"Ingest.{topic}.Parked", parked_count)


class InMemoryMessagingNetwork:
    """Shared fabric for Ring-3 tests: deterministic, manually pumped.

    One FIFO queue per (sender, target) pair — the in-memory analogue of
    Artemis per-peer bridges. `pump(1)` delivers exactly one message in
    global send order; `run(seed)` delivers until quiescent, with a seed
    interleaving *between* pair-queues (never reordering within one) to
    surface cross-peer races deterministically — the reference's
    pumpSend/pumpReceive + runNetwork loop.
    """

    def __init__(self):
        self._queues: dict[tuple[str, str], deque[Message]] = {}
        self._order: deque[tuple[str, str]] = deque()
        self._endpoints: dict[str, "InMemoryMessaging"] = {}
        self._dropped: list[Message] = []
        self.sent_count = 0

    def endpoint(self, name: str) -> "InMemoryMessaging":
        if name not in self._endpoints:
            self._endpoints[name] = InMemoryMessaging(self, name)
        return self._endpoints[name]

    def _enqueue(self, msg: Message, target: str) -> None:
        self.sent_count += 1
        pair = (msg.sender, target)
        self._queues.setdefault(pair, deque()).append(msg)
        self._order.append(pair)

    def pump(self, n: int = 1, rng: Optional[random.Random] = None) -> int:
        """Deliver up to n messages; returns how many were delivered."""
        delivered = 0
        while self._order and delivered < n:
            if rng is None:
                pair = self._order.popleft()
            else:
                live = [p for p, q in self._queues.items() if q]
                pair = live[rng.randrange(len(live))]
                self._order.remove(pair)   # earliest occurrence
            msg = self._queues[pair].popleft()
            ep = self._endpoints.get(pair[1])
            if ep is None or not ep.running:
                self._dropped.append(msg)
            else:
                ep._deliver(msg)
            delivered += 1
        return delivered

    def run(self, seed: Optional[int] = None) -> int:
        """Pump until quiescent. Returns total messages delivered."""
        rng = random.Random(seed) if seed is not None else None
        total = 0
        while self._order:
            total += self.pump(1, rng)
        return total

    @property
    def pending(self) -> int:
        return len(self._order)


class InMemoryMessaging(MessagingService):
    """One node's endpoint on the in-memory fabric."""

    def __init__(self, network: InMemoryMessagingNetwork, name: str):
        self._network = network
        self._name = name
        self._handlers: dict[str, list[Handler]] = {}
        self._rings: dict[str, object] = {}   # topic -> ingest ring
        self._next_id = 0
        self._seen: set[tuple[str, int]] = set()
        self._undelivered: deque[Message] = deque()
        self.running = True

    @property
    def my_address(self) -> str:
        return self._name

    def send(
        self,
        topic: str,
        payload: bytes,
        target: str,
        unique_id: Optional[int] = None,
        trace: Optional[tuple] = None,
        deadline: Optional[int] = None,
    ) -> None:
        """Explicit unique_id lets flows use deterministic ids so that
        replayed sends after checkpoint restore dedupe at the receiver
        (statemachine.py); counter ids stay below 2**63, hashed flow ids
        set the top bit, so the namespaces never collide."""
        if unique_id is None:
            unique_id = self._next_id
            self._next_id += 1
        msg = Message(topic, payload, self._name, unique_id, trace, deadline)
        self._network._enqueue(msg, target)

    def add_handler(self, topic: str, handler: Handler) -> None:
        self._handlers.setdefault(topic, []).append(handler)
        parked = [m for m in self._undelivered if m.topic == topic]
        for m in parked:
            self._undelivered.remove(m)
            self._deliver(m)

    def remove_handler(self, topic: str, handler: Handler) -> None:
        handlers = self._handlers.get(topic, [])
        if handler in handlers:
            handlers.remove(handler)

    def add_ring(self, topic: str, ring, metrics=None) -> None:
        """Route `topic` into a bounded ingest ring (wire-ingest fast
        path — see MessagingService.add_ring). Messages already parked
        for the topic flow into the ring immediately. With a
        MetricRegistry, the ring's depth/high-water and this endpoint's
        parked-frame count become gauges — PR 1's backpressure made
        visible before it stalls the pump."""
        self._rings[topic] = ring
        if metrics is not None:
            register_ring_gauges(
                metrics, topic, ring,
                parked_count=lambda t=topic: self.parked_count(t),
            )
        self.retry_parked(topic)

    def parked_count(self, topic: str) -> int:
        """Frames parked for `topic` because its ring was full (they
        re-enter via retry_parked)."""
        return sum(1 for m in self._undelivered if m.topic == topic)

    def retry_parked(self, topic: str) -> int:
        """Re-offer frames parked while the topic's ring was full
        (the consumer calls this after draining). Returns how many
        moved into the ring."""
        ring = self._rings.get(topic)
        if ring is None:
            return 0
        moved = 0
        parked = [m for m in self._undelivered if m.topic == topic]
        for m in parked:
            key = (m.sender, m.unique_id)
            if key in self._seen:
                # an at-least-once redelivery of this frame already
                # reached the ring while this copy sat parked — drop
                # the duplicate, exactly-once holds on the ring path
                # just like the handler path
                self._undelivered.remove(m)
                continue
            if not ring.offer(m):
                break   # still full: keep FIFO order, stop early
            self._undelivered.remove(m)
            self._seen.add(key)
            moved += 1
        return moved

    def _deliver(self, msg: Message) -> None:
        key = (msg.sender, msg.unique_id)
        if key in self._seen:
            return  # at-least-once upstream, exactly-once to handlers
        ring = self._rings.get(msg.topic)
        if ring is not None:
            # ring seam: enqueue the raw frame for the bulk decoder; a
            # full ring parks it (backpressure) for retry_parked
            if ring.offer(msg):
                self._seen.add(key)
            else:
                self._undelivered.append(msg)
            return
        handlers = self._handlers.get(msg.topic)
        if not handlers:
            self._undelivered.append(msg)
            return
        self._seen.add(key)
        for h in list(handlers):
            h(msg)
