"""Messaging fabric: topic/peer addressed, durable-queue semantics.

Reference: the `MessagingService` API (node/.../services/messaging/
Messaging.kt — send, addMessageHandler(topic), createMessage) backed in
production by an embedded Artemis broker with per-peer store-and-forward
queues and TLS bridges (ArtemisMessagingServer.kt:90,300-401), and in
Ring-3 tests by `InMemoryMessagingNetwork` with manually-pumped
deterministic delivery (test-utils/.../InMemoryMessagingNetwork.kt:47).

This module provides the API plus the in-memory fabric; the DCN (TCP)
fabric with durable queues lives in `corda_tpu.node.fabric`. Delivery
guarantees match Artemis semantics: per-(sender, target) FIFO, at-least-
once upstream with exactly-once to handlers via (sender, unique_id)
dedupe. Payloads are canonical-serialized bytes — even in-memory
delivery round-trips through the wire encoding so serialization gaps
surface in Ring-3 tests, not in production.
"""

from __future__ import annotations

import random
import threading
from ..utils import locks
from collections import deque
from dataclasses import dataclass
from typing import Callable, Optional

TOPIC_SESSION = "platform.session"
TOPIC_NETWORK_MAP = "platform.network_map"
TOPIC_RPC = "rpc.requests"
TOPIC_VERIFIER_REQ = "verifier.requests"
TOPIC_VERIFIER_RES = "verifier.responses"
# distributed sharded uniqueness (node/distributed_uniqueness.py): the
# cross-member two-phase reserve→commit protocol — ShardReserve /
# ShardReserveAck / ShardCommit / ShardCommitAck / ShardAbort plus the
# presumed-abort status queries — all ride this one topic
TOPIC_XSHARD = "notary.xshard"

# dedupe-table bound shared by BOTH fabrics: the newest DEDUPE_KEEP
# dispatched (sender, uid) keys are retained per sender; older ones
# prune away so a long soak's dedupe state stays bounded. Safe because
# senders stop re-offering a frame once it acks — only an explicit
# `unique_id=` replay could carry a key older than the watermark.
DEDUPE_KEEP = 8192


@dataclass(frozen=True)
class Message:
    topic: str
    payload: bytes          # canonical-serialized body
    sender: str             # peer name of origin
    unique_id: int          # per-sender unique id (dedupe key)
    # OPTIONAL tracing header (utils/tracing.py): the sender's
    # (trace_id, span_id) SpanContext pair, so a receiver's spans join
    # the SAME trace — one connected tree per notarisation across the
    # fabric hop. None (the default) everywhere tracing is off; the
    # field is observability metadata, never consensus input.
    trace: Optional[tuple] = None
    # OPTIONAL deadline header (node/qos.py): absolute node-clock
    # microseconds after which the SENDER no longer wants an answer.
    # Consumers shed expired work at the cheapest point they notice it
    # (pre-decode at ingress, pre-stage at the notary flush) into a
    # typed `shed` response. QoS metadata, never consensus input — but
    # unlike `trace` it IS journaled across the TCP fabric: a frame
    # redelivered after a crash should still be shed if it expired.
    deadline: Optional[int] = None


Handler = Callable[[Message], None]


class MessagingService:
    """Send/handle interface every node component talks through."""

    def send(
        self,
        topic: str,
        payload: bytes,
        target: str,
        unique_id: Optional[int] = None,
        trace: Optional[tuple] = None,
        deadline: Optional[int] = None,
    ) -> None:
        """`trace`: optional tracing SpanContext header (see
        Message.trace); trace propagation is best-effort, delivery
        semantics are not. `deadline`: optional absolute-microsecond
        QoS header (Message.deadline) — both ride the fabric as
        headers, never as payload."""
        raise NotImplementedError

    def add_handler(self, topic: str, handler: Handler) -> None:
        raise NotImplementedError

    def add_ring(self, topic: str, ring, metrics=None) -> None:
        """OPTIONAL bulk-ingest seam (node/ingest.py): deliver `topic`
        messages into a bounded ring (`ring.offer(msg) -> bool`)
        instead of per-message handler dispatch, so a consumer can
        decode whole delivery rounds through the sharded ingest
        pipeline. A full ring parks the frame for redelivery
        (`retry_parked`) — backpressure without blocking the pump.
        Fabrics that don't implement it raise, and callers fall back
        to the per-message handler path.

        `metrics`: an optional MetricRegistry; implementations register
        ring-depth / high-water / parked-frame gauges for the topic so
        the backpressure is visible on /metrics BEFORE it stalls the
        pump (see register_ring_gauges)."""
        raise NotImplementedError(f"{type(self).__name__} has no ring seam")

    @property
    def my_address(self) -> str:
        raise NotImplementedError


def register_ring_gauges(metrics, topic: str, ring, parked_count=None) -> None:
    """Gauges over one topic's ingest ring: current depth, lifetime
    high-water mark, and (when the fabric exposes a counter) frames
    parked waiting for retry_parked. ONE naming scheme for every
    fabric, so dashboards don't fork per transport."""
    metrics.gauge(f"Ingest.{topic}.RingDepth", lambda: len(ring))
    metrics.gauge(f"Ingest.{topic}.RingHighWater", lambda: ring.high_water)
    if parked_count is not None:
        metrics.gauge(f"Ingest.{topic}.Parked", parked_count)


class FabricFaults:
    """First-class fault-injection seam shared by BOTH fabrics.

    The chaos plane (testing/fleet.py) needs to break the network the
    way production breaks — partitions, dead nodes, slow links, frame
    drop/duplication — WITHOUT monkeypatching fabric internals. This
    object is the injection point: the in-memory fabric consults it at
    delivery time (simulated-time delays on the shared TestClock), the
    TCP fabric (node/fabric.py) consults it at bridge-connect, accept
    and per-frame ingest time (real-time delays). Both fabrics keep
    their delivery guarantees UNDER the faults — a blocked or delayed
    frame stays queued/journaled and redelivers on heal, a duplicated
    frame is absorbed by (sender, uid) dedupe — so chaos tests exercise
    the same code paths a real outage would.

    Every control-plane call appends to `log` with a fault-clock
    timestamp: the "injected reality" an invariant checker compares the
    health/cluster story against. Thread-safe: the TCP fabric reads
    from its loop thread while a test thread injects.
    """

    def __init__(self, clock=None, seed: int = 0):
        self._clock = clock
        self._rng = random.Random(seed)
        self._lock = locks.make_lock("FabricFaults._lock")
        self._groups: tuple[frozenset, ...] = ()   # partition groups
        self._down: set[str] = set()               # killed nodes
        self._delay: dict[tuple[str, str], int] = {}    # directional us
        self._drop: dict[tuple[str, str], float] = {}   # drop probability
        self._dup: dict[tuple[str, str], float] = {}    # dup probability
        self.log: list[dict] = []

    def now_micros(self) -> int:
        if self._clock is not None:
            return self._clock.now_micros()
        import time

        return time.time_ns() // 1_000

    def _record(self, action: str, **detail) -> None:
        self.log.append(
            {"at_micros": self.now_micros(), "action": action, **detail}
        )

    # -- control plane (the chaos side) --------------------------------------

    def partition(self, *groups) -> None:
        """Split the network: links BETWEEN groups are blocked (both
        directions), links within a group stay up. Nodes in no group
        are unreachable from every group — `partition({"A","B"})`
        isolates everyone else from A and B. Replaces any previous
        partition; `heal()` removes it."""
        with self._lock:
            self._groups = tuple(frozenset(g) for g in groups)
        self._record("partition", groups=[sorted(g) for g in groups])

    def heal(self) -> None:
        with self._lock:
            self._groups = ()
        self._record("heal")

    def kill(self, name: str) -> None:
        """Mark a node down: nothing reaches it, nothing leaves it.
        Frames addressed to it stay queued (in-memory) / journaled
        (TCP) and deliver after `revive` — the store-and-forward
        semantics a real crash exercises."""
        with self._lock:
            self._down.add(name)
        self._record("kill", node=name)

    def revive(self, name: str) -> None:
        with self._lock:
            self._down.discard(name)
        self._record("revive", node=name)

    def slow_link(
        self, a: str, b: str, delay_micros: int, symmetric: bool = True
    ) -> None:
        """Add per-frame latency on a link (0 clears it). The in-memory
        fabric holds frames until the TestClock passes send+delay; the
        TCP fabric sleeps the same interval before acking."""
        with self._lock:
            for pair in ((a, b), (b, a)) if symmetric else ((a, b),):
                if delay_micros > 0:
                    self._delay[pair] = int(delay_micros)
                else:
                    self._delay.pop(pair, None)
        self._record(
            "slow_link", a=a, b=b,
            delay_micros=int(delay_micros), symmetric=symmetric,
        )

    def slow_peer(self, name: str, delay_micros: int, peers=()) -> None:
        """Slow EVERY link touching `name` (both directions). With a
        known peer set, pass it; the wildcard key slows links to/from
        unknown peers too."""
        with self._lock:
            for key in (("*", name), (name, "*")):
                if delay_micros > 0:
                    self._delay[key] = int(delay_micros)
                else:
                    self._delay.pop(key, None)
        for p in peers:
            self.slow_link(name, p, delay_micros)
        if not peers:
            self._record(
                "slow_peer", node=name, delay_micros=int(delay_micros)
            )

    def drop_link(
        self, a: str, b: str, rate: float, symmetric: bool = True
    ) -> None:
        """Drop frames on a link with probability `rate` (0 clears).
        Safe only for traffic with an upstream retry (consensus
        heartbeats, the TCP fabric's journaled bridges) — the seeded
        RNG keeps runs deterministic."""
        with self._lock:
            for pair in ((a, b), (b, a)) if symmetric else ((a, b),):
                if rate > 0:
                    self._drop[pair] = float(rate)
                else:
                    self._drop.pop(pair, None)
        self._record("drop_link", a=a, b=b, rate=rate, symmetric=symmetric)

    def duplicate_link(
        self, a: str, b: str, rate: float, symmetric: bool = True
    ) -> None:
        """Deliver frames twice with probability `rate` (0 clears) —
        the receiver's (sender, uid) dedupe must absorb the copy."""
        with self._lock:
            for pair in ((a, b), (b, a)) if symmetric else ((a, b),):
                if rate > 0:
                    self._dup[pair] = float(rate)
                else:
                    self._dup.pop(pair, None)
        self._record(
            "duplicate_link", a=a, b=b, rate=rate, symmetric=symmetric
        )

    # -- query plane (the fabric side) ---------------------------------------

    def down(self, name: str) -> bool:
        with self._lock:
            return name in self._down

    def blocked(self, sender: str, target: str) -> bool:
        """True when no frame may move sender -> target right now:
        either end is down, or a partition separates them."""
        with self._lock:
            if sender in self._down or target in self._down:
                return True
            if not self._groups:
                return False
            ga = gb = None
            for g in self._groups:
                if sender in g:
                    ga = g
                if target in g:
                    gb = g
            return ga is not gb or ga is None

    def delay_micros(self, sender: str, target: str) -> int:
        with self._lock:
            return max(
                self._delay.get((sender, target), 0),
                self._delay.get(("*", target), 0),
                self._delay.get((sender, "*"), 0),
            )

    def should_drop(self, sender: str, target: str) -> bool:
        with self._lock:
            rate = self._drop.get((sender, target), 0.0)
            return rate > 0 and self._rng.random() < rate

    def should_duplicate(self, sender: str, target: str) -> bool:
        with self._lock:
            rate = self._dup.get((sender, target), 0.0)
            return rate > 0 and self._rng.random() < rate

    def snapshot(self) -> dict:
        """JSON-safe view of the ACTIVE faults (the log has history)."""
        with self._lock:
            return {
                "partition": [sorted(g) for g in self._groups],
                "down": sorted(self._down),
                "slow_links": {
                    f"{a}->{b}": d for (a, b), d in sorted(self._delay.items())
                },
                "drop_links": {
                    f"{a}->{b}": r for (a, b), r in sorted(self._drop.items())
                },
                "duplicate_links": {
                    f"{a}->{b}": r for (a, b), r in sorted(self._dup.items())
                },
            }


class InMemoryMessagingNetwork:
    """Shared fabric for Ring-3 tests: deterministic, manually pumped.

    One FIFO queue per (sender, target) pair — the in-memory analogue of
    Artemis per-peer bridges. `pump(1)` delivers exactly one message in
    global send order; `run(seed)` delivers until quiescent, with a seed
    interleaving *between* pair-queues (never reordering within one) to
    surface cross-peer races deterministically — the reference's
    pumpSend/pumpReceive + runNetwork loop.

    With a `FabricFaults` plane (and the clock it shares), delivery
    becomes fault-aware: frames across a partition or to a down node
    stay QUEUED (they deliver after heal/revive — store-and-forward,
    not loss), slow links hold frames until the TestClock passes
    send-time + delay, and drop/duplicate rates apply at delivery with
    the plane's seeded RNG. Per-pair FIFO order holds under every
    fault: only the HEAD of a pair queue is ever eligible.
    """

    def __init__(self, clock=None, faults: Optional[FabricFaults] = None):
        # queue entries are (msg, ready_at_micros)
        self._queues: dict[tuple[str, str], deque] = {}
        self._order: deque[tuple[str, str]] = deque()
        self._endpoints: dict[str, "InMemoryMessaging"] = {}
        self._dropped: list[Message] = []
        self.sent_count = 0
        self._clock = clock
        self.faults = faults

    def _now(self) -> int:
        if self._clock is not None:
            return self._clock.now_micros()
        if self.faults is not None:
            # no network clock: judge slow-link delays on the fault
            # plane's clock (its wall-clock fallback keeps delayed
            # frames DELIVERABLE eventually — a ready_at computed
            # against a clock pinned at 0 would strand them forever)
            return self.faults.now_micros()
        return 0

    def endpoint(self, name: str) -> "InMemoryMessaging":
        if name not in self._endpoints:
            self._endpoints[name] = InMemoryMessaging(self, name)
        return self._endpoints[name]

    def _enqueue(self, msg: Message, target: str) -> None:
        self.sent_count += 1
        pair = (msg.sender, target)
        ready_at = 0
        if self.faults is not None:
            delay = self.faults.delay_micros(msg.sender, target)
            if delay:
                ready_at = self._now() + delay
        self._queues.setdefault(pair, deque()).append((msg, ready_at))
        self._order.append(pair)

    def _deliverable_pairs(self) -> list[tuple[str, str]]:
        """Pairs whose HEAD frame may deliver now, in earliest-send
        order (faults mode only)."""
        now = self._now()
        faults = self.faults
        seen = set()
        out = []
        for pair in self._order:
            if pair in seen:
                continue
            seen.add(pair)
            q = self._queues.get(pair)
            if not q:
                continue
            _, ready_at = q[0]
            if ready_at > now:
                continue
            if faults.blocked(pair[0], pair[1]):
                continue
            ep = self._endpoints.get(pair[1])
            if ep is None or not ep.running:
                # a dead endpoint under chaos is a DOWN node: keep the
                # frame queued for redelivery after restart (the
                # durable fabric's store-and-forward analogue)
                continue
            out.append(pair)
        return out

    def pump(self, n: int = 1, rng: Optional[random.Random] = None) -> int:
        """Deliver up to n messages; returns how many were delivered.
        In faults mode only deliverable frames move — blocked/unready
        ones stay queued and pump returns short."""
        if self.faults is not None:
            return self._pump_faulty(n, rng)
        delivered = 0
        while self._order and delivered < n:
            if rng is None:
                pair = self._order.popleft()
            else:
                live = [p for p, q in self._queues.items() if q]
                pair = live[rng.randrange(len(live))]
                self._order.remove(pair)   # earliest occurrence
            msg, _ = self._queues[pair].popleft()
            ep = self._endpoints.get(pair[1])
            if ep is None or not ep.running:
                self._dropped.append(msg)
            else:
                ep._deliver(msg)
            delivered += 1
        return delivered

    def _pump_faulty(self, n: int, rng: Optional[random.Random]) -> int:
        faults = self.faults
        delivered = 0
        while delivered < n:
            live = self._deliverable_pairs()
            if not live:
                break
            pair = live[0] if rng is None else live[rng.randrange(len(live))]
            self._order.remove(pair)   # earliest occurrence
            msg, _ = self._queues[pair].popleft()
            if faults.should_drop(pair[0], pair[1]):
                self._dropped.append(msg)
            else:
                ep = self._endpoints[pair[1]]
                ep._deliver(msg)
                if faults.should_duplicate(pair[0], pair[1]):
                    ep._deliver(msg)   # (sender, uid) dedupe absorbs
            delivered += 1
        return delivered

    def run(self, seed: Optional[int] = None) -> int:
        """Pump until quiescent (nothing DELIVERABLE left — blocked or
        delayed frames stay queued). Returns total delivered."""
        rng = random.Random(seed) if seed is not None else None
        total = 0
        while True:
            got = self.pump(1, rng)
            if not got:
                return total
            total += got

    @property
    def pending(self) -> int:
        return len(self._order)

    @property
    def deliverable(self) -> int:
        """Pairs with a deliverable HEAD frame right now (a quiescence
        signal: nonzero iff pump(1) would move something) — `pending`
        without a fault plane; under faults, blocked/delayed frames
        don't count (quiescence must not wait on them). One scan of
        the order deque, no per-queue walk."""
        if self.faults is None:
            return len(self._order)
        return len(self._deliverable_pairs())


class InMemoryMessaging(MessagingService):
    """One node's endpoint on the in-memory fabric."""

    def __init__(self, network: InMemoryMessagingNetwork, name: str):
        self._network = network
        self._name = name
        self._handlers: dict[str, list[Handler]] = {}
        self._rings: dict[str, object] = {}   # topic -> ingest ring
        self._next_id = 0
        # insertion-ordered so the DEDUPE_KEEP bound evicts oldest-
        # first (the in-memory analogue of the TCP fabric's arrival-
        # watermark prune)
        self._seen: dict[tuple[str, int], None] = {}
        self._undelivered: deque[Message] = deque()
        self.running = True
        # wire-telemetry seam (utils.wire_telemetry.WireAccounting):
        # mutable like FabricEndpoint.telemetry — None costs one
        # attribute check per frame
        self.telemetry = None
        self.dedupe_keep = DEDUPE_KEEP

    @property
    def my_address(self) -> str:
        return self._name

    def send(
        self,
        topic: str,
        payload: bytes,
        target: str,
        unique_id: Optional[int] = None,
        trace: Optional[tuple] = None,
        deadline: Optional[int] = None,
    ) -> None:
        """Explicit unique_id lets flows use deterministic ids so that
        replayed sends after checkpoint restore dedupe at the receiver
        (statemachine.py); counter ids stay below 2**63, hashed flow ids
        set the top bit, so the namespaces never collide."""
        if unique_id is None:
            unique_id = self._next_id
            self._next_id += 1
        msg = Message(topic, payload, self._name, unique_id, trace, deadline)
        tel = self.telemetry
        if tel is not None:
            tel.record_frame("out", target, topic, len(payload))
        self._network._enqueue(msg, target)

    def add_handler(self, topic: str, handler: Handler) -> None:
        self._handlers.setdefault(topic, []).append(handler)
        parked = [m for m in self._undelivered if m.topic == topic]
        for m in parked:
            self._undelivered.remove(m)
            self._deliver(m)

    def remove_handler(self, topic: str, handler: Handler) -> None:
        handlers = self._handlers.get(topic, [])
        if handler in handlers:
            handlers.remove(handler)

    def add_ring(self, topic: str, ring, metrics=None) -> None:
        """Route `topic` into a bounded ingest ring (wire-ingest fast
        path — see MessagingService.add_ring). Messages already parked
        for the topic flow into the ring immediately. With a
        MetricRegistry, the ring's depth/high-water and this endpoint's
        parked-frame count become gauges — PR 1's backpressure made
        visible before it stalls the pump."""
        self._rings[topic] = ring
        if metrics is not None:
            register_ring_gauges(
                metrics, topic, ring,
                parked_count=lambda t=topic: self.parked_count(t),
            )
        self.retry_parked(topic)

    def parked_count(self, topic: str) -> int:
        """Frames parked for `topic` because its ring was full (they
        re-enter via retry_parked)."""
        return sum(1 for m in self._undelivered if m.topic == topic)

    def retry_parked(self, topic: str) -> int:
        """Re-offer frames parked while the topic's ring was full
        (the consumer calls this after draining). Returns how many
        moved into the ring."""
        ring = self._rings.get(topic)
        if ring is None:
            return 0
        moved = 0
        parked = [m for m in self._undelivered if m.topic == topic]
        for m in parked:
            key = (m.sender, m.unique_id)
            if key in self._seen:
                # an at-least-once redelivery of this frame already
                # reached the ring while this copy sat parked — drop
                # the duplicate, exactly-once holds on the ring path
                # just like the handler path
                self._undelivered.remove(m)
                continue
            if not ring.offer(m):
                break   # still full: keep FIFO order, stop early
            self._undelivered.remove(m)
            self._remember(key, m)
            moved += 1
        return moved

    def _remember(self, key: tuple[str, int], msg: Message) -> None:
        """Mark a frame delivered (dedupe) + record the inbound link —
        ONE seam for all three delivery paths, so the telemetry and
        the DEDUPE_KEEP eviction can never disagree."""
        tel = self.telemetry
        if tel is not None:
            tel.record_frame(
                "in", msg.sender, msg.topic, len(msg.payload)
            )
        self._seen[key] = None
        if len(self._seen) > self.dedupe_keep:
            self._seen.pop(next(iter(self._seen)))

    def wire_depths(self) -> dict:
        """The WirePlane's per-tick depth pull (the TCP fabric's
        `wire_depths` shape): undelivered frames queued toward each
        peer stand in for the unacked journal backlog."""
        backlog = {
            target: len(q)
            for (sender, target), q in self._network._queues.items()
            if sender == self._name and q
        }
        return {
            "journal_depth": sum(backlog.values()),
            "dedupe_depth": len(self._seen),
            "backlog": backlog,
        }

    def _deliver(self, msg: Message) -> None:
        key = (msg.sender, msg.unique_id)
        if key in self._seen:
            # at-least-once upstream, exactly-once to handlers
            tel = self.telemetry
            if tel is not None:
                tel.record_dedupe_hit(msg.sender)
            return
        ring = self._rings.get(msg.topic)
        if ring is not None:
            # ring seam: enqueue the raw frame for the bulk decoder; a
            # full ring parks it (backpressure) for retry_parked
            if ring.offer(msg):
                self._remember(key, msg)
            else:
                self._undelivered.append(msg)
            return
        handlers = self._handlers.get(msg.topic)
        if not handlers:
            self._undelivered.append(msg)
            return
        self._remember(key, msg)
        for h in list(handlers):
            h(msg)
