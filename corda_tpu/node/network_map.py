"""Network map service: the node directory protocol.

Reference: node/.../services/network/NetworkMapService.kt:62 — a
register/fetch/subscribe/push protocol over messaging topics
(FETCH_TOPIC/QUERY_TOPIC/REGISTER_TOPIC/SUBSCRIPTION_TOPIC/PUSH_TOPIC/
PUSH_ACK_TOPIC, `:64-75`), with signed `NodeRegistration`s carrying a
monotonically-increasing serial and an expiry, an in-memory
(InMemoryNetworkMapService) and a persistent (PersistentNetworkMapService)
implementation, and subscriber eviction after too many unacknowledged
pushes.

Design notes vs the reference:
- Registrations are signed over the canonical (CTS) encoding of the
  registration record and verified with the registering party's identity
  key — same trust model as the reference's `WireNodeRegistration`
  (NodeRegistration.toWire / verified in processRegistrationChangeRequest).
- The map service is just another topic handler on the fabric; any node
  can host it (the reference advertises it as `corda.network_map`).
- Clients keep their `NetworkMapCache` + `IdentityService` in sync from
  fetch responses and pushes (AbstractNode.registerWithNetworkMap:593).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from ..core import serialization as ser
from ..core.identity import Party
from ..crypto import schemes
from .messaging import Message, MessagingService
from .services import NetworkMapCache, NodeInfo, SERVICE_NETWORK_MAP

TOPIC_NM_REGISTER = "platform.network_map.register"
TOPIC_NM_FETCH = "platform.network_map.fetch"
TOPIC_NM_SUBSCRIBE = "platform.network_map.subscribe"
TOPIC_NM_PUSH = "platform.network_map.push"
TOPIC_NM_PUSH_ACK = "platform.network_map.push_ack"
TOPIC_NM_REPLY = "platform.network_map.reply"

ADD = "add"
REMOVE = "remove"

# Subscribers that fall this many un-acked pushes behind are dropped
# (reference: NetworkMapService maxUnacknowledgedUpdates = 10).
MAX_UNACKED_UPDATES = 10


@dataclass(frozen=True)
class NodeRegistration:
    """A signed-over change request: add/remove one node (reference:
    NetworkMapService.kt NodeRegistration — serial guards replay,
    expires bounds validity)."""

    info: NodeInfo
    serial: int
    op: str                 # ADD | REMOVE
    expires_micros: int


@dataclass(frozen=True)
class WireNodeRegistration:
    """Canonical bytes of a NodeRegistration + identity-key signature."""

    raw: bytes
    signature: bytes

    def verified(self) -> NodeRegistration:
        reg = ser.decode(self.raw)
        if not isinstance(reg, NodeRegistration):
            raise ValueError("registration payload is not a NodeRegistration")
        key = reg.info.legal_identity.owning_key
        if not schemes.verify_one(key, self.signature, self.raw):
            raise ValueError(f"bad registration signature for {reg.info.legal_identity}")
        return reg


def sign_registration(reg: NodeRegistration, priv: schemes.PrivateKey) -> WireNodeRegistration:
    raw = ser.encode(reg)
    return WireNodeRegistration(raw, priv.sign(raw))


@dataclass(frozen=True)
class RegistrationRequest:
    wire: WireNodeRegistration
    req_id: int


@dataclass(frozen=True)
class RegistrationResponse:
    req_id: int
    error: Optional[str]


@dataclass(frozen=True)
class FetchMapRequest:
    req_id: int
    subscribe: bool
    if_changed_since: Optional[int]    # map version, None = always send


@dataclass(frozen=True)
class FetchMapResponse:
    req_id: int
    version: int
    registrations: Optional[tuple]     # of WireNodeRegistration; None if unchanged


@dataclass(frozen=True)
class MapUpdate:
    wire: WireNodeRegistration
    version: int


@dataclass(frozen=True)
class MapUpdateAck:
    version: int


for _cls in (
    NodeRegistration,
    WireNodeRegistration,
    RegistrationRequest,
    RegistrationResponse,
    FetchMapRequest,
    FetchMapResponse,
    MapUpdate,
    MapUpdateAck,
):
    ser.serializable(_cls)


class NetworkMapService:
    """The directory server side (InMemory/PersistentNetworkMapService).

    Pass a NodeDatabase to persist registrations across restarts — they
    are reloaded (and re-verified) at construction, mirroring
    PersistentNetworkMapService's JDBC-backed registration map.
    """

    def __init__(self, messaging: MessagingService, clock, db=None, services=None):
        """`services`: the hosting node's ServiceHub — accepted
        registrations mirror into its own NetworkMapCache/IdentityService
        so the host can route back to registrants (the reference's map
        node shares the node's cache the same way)."""
        self._messaging = messaging
        self._clock = clock
        self._services = services
        self._registry: dict[str, WireNodeRegistration] = {}
        # Replay + hijack protection. The latest registration per name is
        # persisted even for REMOVE (a tombstone), so neither the serial
        # high-water mark nor the name->key binding resets on restart:
        self._serials: dict[str, int] = {}
        self._bindings: dict[str, bytes] = {}   # name -> key fingerprint
        self._version = 0
        # subscriber address -> un-acked push count
        self._subscribers: dict[str, int] = {}
        self._store = self._meta = None
        if db is not None:
            from .persistence import PersistentKVStore

            self._store = PersistentKVStore(db, "network_map")
            self._meta = PersistentKVStore(db, "network_map_meta")
            for key, blob in self._store.items():
                try:
                    wire = ser.decode(blob)
                    reg = wire.verified()
                except (ValueError, ser.SerializationError):
                    continue
                name = reg.info.legal_identity.name
                self._serials[name] = reg.serial
                self._bindings[name] = (
                    reg.info.legal_identity.owning_key.fingerprint()
                )
                if reg.op == ADD:
                    self._registry[name] = wire
                    self._mirror(reg)
            stored_version = self._meta.get(b"version")
            if stored_version is not None:
                self._version = ser.decode(stored_version)
        messaging.add_handler(TOPIC_NM_REGISTER, self._on_register)
        messaging.add_handler(TOPIC_NM_FETCH, self._on_fetch)
        messaging.add_handler(TOPIC_NM_SUBSCRIBE, self._on_subscribe)
        messaging.add_handler(TOPIC_NM_PUSH_ACK, self._on_push_ack)

    # -- request processing --------------------------------------------------

    @staticmethod
    def _decoded(msg: Message, expected: type):
        """Decode a request, dropping malformed payloads instead of
        letting them crash the message pump (an unauthenticated peer
        must not be able to DoS the directory with garbage bytes)."""
        try:
            req = ser.decode(msg.payload)
        except ser.SerializationError:
            return None
        return req if isinstance(req, expected) else None

    def _on_register(self, msg: Message) -> None:
        req = self._decoded(msg, RegistrationRequest)
        if req is None:
            return
        error = None
        try:
            self._process_registration(req.wire)
        except (ValueError, ser.SerializationError) as e:
            error = str(e)
        self._reply(msg.sender, RegistrationResponse(req.req_id, error))

    def _process_registration(self, wire: WireNodeRegistration) -> None:
        reg = wire.verified()
        name = reg.info.legal_identity.name
        if reg.op not in (ADD, REMOVE):
            raise ValueError(f"unknown registration op {reg.op!r}")
        if reg.expires_micros <= self._clock.now_micros():
            raise ValueError("registration has expired")
        # Key continuity: the first registration binds name -> key; later
        # changes must be signed by that same key (verified() has already
        # checked the signature against the in-payload key, so equality of
        # fingerprints makes it a check against the bound key). Without
        # this, anyone could re-register a peer's name under their own key
        # and hijack its address + identity at every subscriber.
        fp = reg.info.legal_identity.owning_key.fingerprint()
        bound = self._bindings.get(name)
        if bound is not None and fp != bound:
            raise ValueError(f"identity key mismatch for {name!r}")
        prev = self._serials.get(name)
        if prev is not None and reg.serial <= prev:
            raise ValueError(
                f"serial {reg.serial} is not newer than {prev} (replay?)"
            )
        self._serials[name] = reg.serial
        self._bindings[name] = fp
        if reg.op == ADD:
            self._registry[name] = wire
        else:
            self._registry.pop(name, None)
        if self._store is not None:
            # REMOVE persists as a tombstone: it carries the serial and
            # binding forward across restarts so the old ADD can't be
            # replayed to resurrect a deregistered node.
            self._store.put(name.encode(), ser.encode(wire))
        self._version += 1
        if self._meta is not None:
            self._meta.put(b"version", ser.encode(self._version))
        self._mirror(reg)
        self._push(wire)

    def _mirror(self, reg: NodeRegistration) -> None:
        """Reflect an accepted registration into the host's own cache."""
        if self._services is None:
            return
        if reg.op == ADD:
            self._services.network_map_cache.add_node(reg.info)
            self._services.identity.register(reg.info.legal_identity)
        else:
            self._services.network_map_cache.remove_node(reg.info)

    def _push(self, wire: WireNodeRegistration) -> None:
        update = ser.encode(MapUpdate(wire, self._version))
        for address in list(self._subscribers):
            self._subscribers[address] += 1
            if self._subscribers[address] > MAX_UNACKED_UPDATES:
                # slow consumer: drop; it will re-fetch on reconnect
                del self._subscribers[address]
                continue
            self._messaging.send(TOPIC_NM_PUSH, update, address)

    def _on_fetch(self, msg: Message) -> None:
        req = self._decoded(msg, FetchMapRequest)
        if req is None:
            return
        if req.subscribe:
            self._subscribers[msg.sender] = 0
        unchanged = (
            req.if_changed_since is not None
            and req.if_changed_since == self._version
        )
        regs = None if unchanged else tuple(self._registry.values())
        self._reply(msg.sender, FetchMapResponse(req.req_id, self._version, regs))

    def _on_subscribe(self, msg: Message) -> None:
        self._subscribers[msg.sender] = 0

    def _on_push_ack(self, msg: Message) -> None:
        if msg.sender in self._subscribers:
            self._subscribers[msg.sender] = 0

    def _reply(self, address: str, response) -> None:
        self._messaging.send(TOPIC_NM_REPLY, ser.encode(response), address)

    # -- introspection -------------------------------------------------------

    @property
    def version(self) -> int:
        return self._version

    def registered_names(self) -> list[str]:
        return sorted(self._registry)

    def subscriber_count(self) -> int:
        return len(self._subscribers)


class NetworkMapClient:
    """Client side: registers this node, mirrors the map into the local
    NetworkMapCache/IdentityService (AbstractNode.registerWithNetworkMap).
    """

    DEFAULT_TTL_MICROS = 365 * 24 * 3600 * 1_000_000   # 1 year, like the ref
    # periodic re-registration: the map's last-seen stamp is the
    # explorer network view's liveness signal, and without renewal it
    # would freeze at boot time (round-5 review). Re-ADDs are tiny
    # signed deltas; subscribers re-stamp on the push.
    RENEW_MICROS = 60 * 1_000_000

    def __init__(
        self,
        services,
        messaging: MessagingService,
        map_address: str,
        identity_priv: schemes.PrivateKey,
    ):
        self._services = services
        self._messaging = messaging
        self._map_address = map_address
        self._priv = identity_priv
        self._next_req = 0
        self._pending: dict[int, Callable] = {}
        self.registration_error: Optional[str] = None
        # mirror of the service's replay/continuity guards, so a stale or
        # forged push can't roll this client's view backwards:
        self._serials: dict[str, int] = {}
        self._bindings: dict[str, bytes] = {}
        self._known: set[str] = set()   # names this client learned from the map
        self.registered = False
        self.map_version: Optional[int] = None
        self._last_renewal = 0
        messaging.add_handler(TOPIC_NM_REPLY, self._on_reply)
        messaging.add_handler(TOPIC_NM_PUSH, self._on_push)

    # -- outbound ------------------------------------------------------------

    def register(
        self,
        op: str = ADD,
        on_done: Optional[Callable] = None,
        on_error: Optional[Callable[[str], None]] = None,
    ) -> None:
        """Publish our own NodeInfo (serial = clock micros: monotone
        across restarts, the reference uses Instant serials). Rejection
        is reported via `on_error`/`registration_error`, never raised —
        the reply handler runs inside the message pump, and a throw
        there would abort delivery of unrelated traffic."""
        reg = NodeRegistration(
            info=self._services.my_info,
            serial=self._services.clock.now_micros(),
            op=op,
            expires_micros=self._services.clock.now_micros() + self.DEFAULT_TTL_MICROS,
        )
        self._last_renewal = self._services.clock.now_micros()
        wire = sign_registration(reg, self._priv)
        req_id = self._fresh_req_id()

        def handle(resp: RegistrationResponse):
            if resp.error is not None:
                self.registration_error = resp.error
                if on_error is not None:
                    on_error(resp.error)
                else:
                    import logging

                    logging.getLogger("corda_tpu.network_map").warning(
                        "network map rejected registration: %s", resp.error
                    )
                return
            self.registration_error = None
            self.registered = True
            if on_done is not None:
                on_done(resp)

        self._pending[req_id] = handle
        self._messaging.send(
            TOPIC_NM_REGISTER,
            ser.encode(RegistrationRequest(wire, req_id)),
            self._map_address,
        )

    def fetch(self, subscribe: bool = True) -> None:
        """Pull the whole map (and subscribe to future deltas)."""
        req_id = self._fresh_req_id()
        self._pending[req_id] = self._apply_fetch
        self._messaging.send(
            TOPIC_NM_FETCH,
            ser.encode(FetchMapRequest(req_id, subscribe, self.map_version)),
            self._map_address,
        )

    def deregister(self, on_done: Optional[Callable] = None) -> None:
        self.register(op=REMOVE, on_done=on_done)

    def tick(self, now: Optional[int] = None) -> None:
        """Heartbeat renewal (called from the node pump): re-register
        every RENEW_MICROS so the map's last-seen stays a liveness
        signal — a node that stops ticking ages visibly in every
        peer's network view."""
        if not self.registered:
            return
        now = now if now is not None else self._services.clock.now_micros()
        if now - self._last_renewal >= self.RENEW_MICROS:
            self.register()

    # -- inbound -------------------------------------------------------------

    def _on_reply(self, msg: Message) -> None:
        if msg.sender != self._map_address:
            return   # replies are only trusted from our map service
        try:
            resp = ser.decode(msg.payload)
        except ser.SerializationError:
            return
        handler = self._pending.pop(resp.req_id, None)
        if handler is not None:
            handler(resp)

    def _apply_fetch(self, resp: FetchMapResponse) -> None:
        self.map_version = resp.version
        if resp.registrations is None:
            return
        live: set[str] = set()
        for wire in resp.registrations:
            applied = self._apply_wire(wire)
            if applied is not None:
                live.add(applied)
        # A full fetch is authoritative: any node we previously learned
        # from the map that is absent now has deregistered — drop it, or
        # its stale address would be routed to forever.
        cache: NetworkMapCache = self._services.network_map_cache
        for name in self._known - live:
            info = cache.node_by_name(name)
            if info is not None:
                cache.remove_node(info)
        self._known = live

    def _on_push(self, msg: Message) -> None:
        if msg.sender != self._map_address:
            return   # only the map service may push to us
        try:
            update = ser.decode(msg.payload)
        except ser.SerializationError:
            return
        self._apply_wire(update.wire)
        self.map_version = update.version
        self._messaging.send(
            TOPIC_NM_PUSH_ACK,
            ser.encode(MapUpdateAck(update.version)),
            self._map_address,
        )

    def _apply_wire(self, wire: WireNodeRegistration) -> Optional[str]:
        """Apply one registration; returns the node name if it is (still)
        live after this wire, None if rejected or removed."""
        try:
            reg = wire.verified()
        except ValueError:
            return None   # a bad registration from the service is ignored
        name = reg.info.legal_identity.name
        fp = reg.info.legal_identity.owning_key.fingerprint()
        bound = self._bindings.get(name)
        if bound is not None and fp != bound:
            return None   # name hijack attempt: key changed mid-stream
        prev = self._serials.get(name)
        if prev is not None and reg.serial < prev:
            return None   # stale replayed registration
        self._serials[name] = reg.serial
        self._bindings[name] = fp
        cache: NetworkMapCache = self._services.network_map_cache
        if reg.op == ADD:
            cache.add_node(reg.info)
            self._services.identity.register(reg.info.legal_identity)
            self._known.add(name)
            return name
        cache.remove_node(reg.info)
        self._known.discard(name)
        return None

    def _fresh_req_id(self) -> int:
        self._next_req += 1
        return self._next_req


def advertise_network_map(info: NodeInfo) -> NodeInfo:
    """Return a copy of `info` advertising the network-map service."""
    return NodeInfo(
        info.address,
        info.legal_identity,
        info.advertised_services + (SERVICE_NETWORK_MAP,),
    )
