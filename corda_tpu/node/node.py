"""Node assembly & lifecycle: the real node over the DCN fabric.

Reference: `AbstractNode.start()` boot ordering (node/.../internal/
AbstractNode.kt:163-222 — database, services, messaging, notary, SMM,
scheduler, network-map registration) and `Node` (Node.kt:125-344 —
embedded broker, RPC server start, the message pump `run()` loop);
CLI entry `NodeStartup` (NodeStartup.kt:44-99).

TPU-first differences: the "broker" is the node's own durable fabric
endpoint (fabric.py) — there is no separate broker process; signature
verification drains into the TPU batch SPI (in-process or via the
out-of-process verifier pool, NodeConfiguration.verifierType); the pump
loop is the single server thread every service runs on
(AffinityExecutor.kt role).
"""

from __future__ import annotations

import os
import random
from typing import Optional

from ..crypto import schemes
from ..crypto.batch_verifier import BatchSignatureVerifier
from ..flows.statemachine import StateMachineManager
from . import network_map as nm
from . import rpc as rpclib
from .config import NodeConfig
from .fabric import FabricEndpoint, PeerAddress, TlsIdentity
from .notary import (
    InMemoryUniquenessProvider,
    BatchingNotaryService,
    SimpleNotaryService,
    ValidatingNotaryService,
)
from .persistence import (
    NodeDatabase,
    PersistentKVStore,
    PersistentServiceHub,
    PersistentUniquenessProvider,
)
from .scheduler import NodeSchedulerService
from .services import (
    Clock,
    IdentityService,
    NodeInfo,
    SERVICE_NETWORK_MAP,
    SERVICE_NOTARY,
    SERVICE_NOTARY_VALIDATING,
)


class Node:
    """One production node process (reference: Node.kt).

    Lifecycle: `Node(config).start()` boots everything and registers
    with the network map; `run()` enters the pump loop (blocks);
    `stop()` shuts down. `rpc_client(...)` builds a loopback client for
    embedded use (tests, the shell).
    """

    def __init__(
        self,
        config: NodeConfig,
        clock: Optional[Clock] = None,
        batch_verifier: Optional[BatchSignatureVerifier] = None,
    ):
        self.config = config
        # CorDapps first: their import registers states/commands with
        # the canonical codec (decoding a peer's transaction needs the
        # classes) and @initiated_by responders with the flow registry
        # (reference: CorDapp scan before SMM start, AbstractNode.kt:427)
        import importlib

        for module in config.cordapps:
            importlib.import_module(module)
        os.makedirs(config.base_dir, exist_ok=True)
        self.db = NodeDatabase(os.path.join(config.base_dir, "node.db"))

        # persistent boot counter: per-boot RNG streams (flow/session
        # ids, fresh confidential keys) must NEVER repeat across
        # restarts — a restarted dev node that re-seeded identically
        # would mint the exact session ids of its previous life, and
        # peers silently route them into old, ended sessions (found by
        # the notary kill-restart soak: the post-restart notarisation
        # hung forever with no error anywhere)
        from .persistence import PersistentKVStore

        _meta = PersistentKVStore(self.db, "node_meta")
        _prev = _meta.get(b"boot_count")
        self.boot_count = (
            int.from_bytes(_prev, "big") if _prev else 0
        ) + 1
        _meta.put(b"boot_count", self.boot_count.to_bytes(8, "big"))

        # -- identity (persisted across restarts; AbstractNode obtains
        # it from the node CA keystore, KeyStoreUtilities.kt) ---------
        self.keypair = self._load_or_create_identity()
        from ..core.identity import Party

        self.party = Party(config.name, self.keypair.public)

        # -- TLS channel identity (self-signed; pinned via network map)
        self.tls = self._load_or_create_tls() if config.use_tls else None

        advertised: tuple[str, ...] = ()
        if config.notary in ("simple", "raft", "bft"):
            # BFT is non-validating, like the reference's
            # BFTNonValidatingNotaryService (its only BFT flavour)
            advertised = (SERVICE_NOTARY,)
        elif config.notary in ("validating", "batching", "raft-validating"):
            advertised = (SERVICE_NOTARY_VALIDATING,)
        if config.is_network_map_host:
            advertised = advertised + (SERVICE_NETWORK_MAP,)

        # distributed notary members share one service identity derived
        # from (cluster_name, cluster_key_seed); the key installs into
        # key management so any member can sign for the cluster
        self._cluster_identity = None
        self._cluster_keypair = None
        if config.notary in ("raft", "raft-validating", "bft"):
            from .config import ConfigError

            if config.name not in config.cluster_peers:
                raise ConfigError(
                    f"{config.notary} notary needs cluster_peers including "
                    f"this node"
                )
        if config.notary in ("raft", "raft-validating") or (
            config.notary == "batching" and config.notary_cluster_shards > 0
        ):
            # the distributed-uniqueness batching cluster shares one
            # service identity exactly like the raft cluster: every
            # member answers (and signs) for the cluster party
            from ..core.identity import Party as _Party

            self._cluster_keypair = self._derive_keypair(
                f"{config.cluster_name}:{config.cluster_key_seed}"
            )
            self._cluster_identity = _Party(
                config.cluster_name, self._cluster_keypair.public
            )
        elif config.notary == "bft":
            # BFT: composite f+1 identity over per-member keys, all
            # derivable from the shared (cluster_name, cluster_key_seed)
            # config — dev-mode key provisioning, like the raft shared
            # key (production distributes real key material out of band)
            from ..core.identity import Party as _Party
            from ..crypto.composite import CompositeKey

            member_kps = {
                peer: self._bft_member_keypair(peer)
                for peer in config.cluster_peers
            }
            self._cluster_keypair = member_kps[config.name]
            f = (len(config.cluster_peers) - 1) // 3
            composite = CompositeKey.build(
                [member_kps[p].public for p in config.cluster_peers],
                threshold=f + 1,
            )
            self._cluster_identity = _Party(config.cluster_name, composite)

        self.info = NodeInfo(
            address=config.name,
            legal_identity=self.party,
            advertised_services=advertised,
            host=config.p2p_host,
            port=0,   # patched after the fabric binds (ephemeral ports)
            tls_fingerprint=self.tls.fingerprint if self.tls else None,
            cluster_identity=self._cluster_identity,
        )

        if batch_verifier is None and config.verifier_backend == "cpu":
            from ..crypto.batch_verifier import CpuBatchVerifier

            batch_verifier = CpuBatchVerifier()

        # -- services over one shared database -------------------------
        self.services = PersistentServiceHub.open(
            "",   # path unused: db is shared
            self.info,
            IdentityService(self.party),
            self.keypair,
            clock=clock,
            batch_verifier=batch_verifier,
            rng=random.Random(self._dev_seed("kms", per_boot=True)),
            db=self.db,
        )

        # -- fabric endpoint -------------------------------------------
        self.messaging = FabricEndpoint(
            config.name,
            self.keypair,
            self.db,
            resolve=self._resolve_peer,
            host=config.p2p_host,
            port=config.p2p_port,
            tls=self.tls,
        )
        # inbound connections claiming a map-registered name must prove
        # they hold that identity's key (fabric.py _auth_server); without
        # this, any peer could claim "Bob" and inject session messages
        self.messaging.expected_identity_key = self._expected_identity_key

        # -- network map (host or client) ------------------------------
        self.network_map_service: Optional[nm.NetworkMapService] = None
        self.network_map_client: Optional[nm.NetworkMapClient] = None
        if config.is_network_map_host:
            self.network_map_service = nm.NetworkMapService(
                self.messaging,
                self.services.clock,
                db=self.db,
                services=self.services,
            )
        else:
            self.network_map_client = nm.NetworkMapClient(
                self.services,
                self.messaging,
                config.network_map_peer,
                self.keypair.private,
            )

        # -- metrics + tracing (MonitoringService's MetricRegistry;
        # serve with node.webserver() -> GET /metrics in prometheus
        # format, the JMX/Jolokia role of Node.kt:306-308, and the
        # hot-path flight recorder at GET /traces). Created BEFORE the
        # notary so its batching counters/phase timers land on this
        # node's scrape surface. The tracer is the process default:
        # disabled unless CORDA_TPU_TRACE=1 (utils/tracing.py).
        from ..utils import tracing
        from ..utils.health import ClusterHealth, HealthMonitor
        from ..utils.metrics import MetricRegistry
        from ..utils.perf import PerfPlane, PerfPolicy

        self.metrics = MetricRegistry()
        self.tracer = tracing.get_tracer()
        # performance-attribution plane (utils/perf.py): kernel
        # compile-vs-execute accounting (installed as the process
        # default, so every TpuBatchVerifier this node constructs
        # records into it), per-shard skew telemetry, the in-process
        # bench history + baseline diff, and the sampling profiler —
        # served at GET /perf + /profile. Created BEFORE the notary so
        # attach_perf can wire the flush feeds.
        self.perf = None
        if config.perf_enabled:
            self.perf = PerfPlane(
                clock=self.services.clock,
                metrics=self.metrics,
                tracer=self.tracer,
                policy=PerfPolicy(
                    profile_hz=config.perf_profile_hz or 19.0
                ),
                baseline_path=config.perf_baseline or None,
            )
        # QoS plane (node/qos.py): installed with the batching notary
        # when config.qos_enabled; None keeps every hot path unchanged
        self.qos = None
        # health plane (utils/health.py): watchdog over every long-
        # lived loop, SLO/shed/ring alert rules, the canary probe and
        # the JSON-lines event log — served at GET /healthz + /health,
        # rolled up fleet-wide at GET /cluster. Created BEFORE the
        # notary so the flush loop can register its heartbeat.
        self.health = HealthMonitor(
            clock=self.services.clock,
            metrics=self.metrics,
            tracer=self.tracer,
            event_log_path=os.path.join(
                config.base_dir, "health_events.jsonl"
            ),
        )
        self._hb_pump = self.health.heartbeat("messaging.pump")
        self._hb_raft = self._hb_bft = None
        self._canary_fn = None
        self.cluster_health = ClusterHealth(
            config.name,
            lambda: self.health.snapshot(summary=True),
            self._health_peer_urls,
            clock_fn=self.services.clock.now_micros,
        )
        # cross-node trace assembly (utils/tracing.ClusterTraces):
        # GET /cluster/trace/<id> pulls matching span sets from every
        # peer's flight recorder over the same advertised web_port the
        # health rollup rides, merges them clock-offset-adjusted
        self.cluster_traces = tracing.ClusterTraces(
            config.name,
            self.tracer,
            self._peer_web_urls,
        )
        # incident forensics (utils/health.IncidentRecorder): every
        # firing alert snapshots a durable bundle — alert + slowest
        # matching traces WITH their remote halves + metrics snapshot
        # + event tail — to base_dir/incidents, served at /incidents
        from ..utils.health import IncidentRecorder

        self.incidents = IncidentRecorder(
            os.path.join(config.base_dir, "incidents"),
            clock_fn=self.services.clock.now_micros,
            assemble=self.cluster_traces.assemble,
        )
        self.health.attach_incidents(
            self.incidents, node=config.name, background=True
        )
        # transaction provenance plane (utils/txstory.py): the per-tx
        # lifecycle ledger every serving-path seam emits into, served
        # at GET /tx/<id> (cluster-assembled) + GET /tx/slowest with
        # Tx.Stage.* histograms on /metrics. Created BEFORE the notary
        # so every flavour can attach; `services.txstory` is the seam
        # the flavour-shared commit_and_sign path reads.
        self.txstory = None
        self.cluster_tx = None
        if config.txstory_enabled:
            from ..utils.txstory import ClusterTxStory, TxStory

            index = None
            if config.txstory_index:
                from .persistence import TxStoryIndex

                index = TxStoryIndex(self.db)
            self.txstory = TxStory(
                metrics=self.metrics,
                clock=self.services.clock,
                tracer=self.tracer,
                index=index,
            )
            self.services.txstory = self.txstory
            self.cluster_tx = ClusterTxStory(
                config.name,
                self.txstory,
                self._peer_web_urls,
                tracer=self.tracer,
            )
            if config.txstory_stage_slo_micros > 0:
                t = config.txstory_stage_slo_micros
                self.health.watch_txstory(
                    self.txstory,
                    {"queue": t, "verify": t, "commit": t},
                )

        # -- flows, notary, scheduler ----------------------------------
        # @corda_service instances from the imported cordapps, before
        # any flow can run (installCordaServices, AbstractNode.kt:226)
        from .cordapp import install_cordapp_services

        install_cordapp_services(self.services, config.cordapps)
        self.smm = StateMachineManager(
            self.services, self.messaging,
            rng=random.Random(self._dev_seed("smm", per_boot=True)),
        )
        self._install_notary()
        # device telemetry & capacity attribution (utils/
        # device_telemetry.py): per-device HBM/busy/queue/transfer
        # sampling over jax.local_devices() fed by the process device
        # accounting every TpuBatchVerifier records into, plus the
        # roofline capacity model naming the binding constraint —
        # served at GET /device + /capacity. Built AFTER the notary so
        # attach_device can map shard queues onto pinned devices and
        # bridge the degraded-mode flag.
        self.device_plane = None
        if config.device_telemetry_enabled:
            from ..utils.device_telemetry import DevicePlane

            self.device_plane = DevicePlane(
                clock=self.services.clock,
                metrics=self.metrics,
                perf=self.perf,
            )
            notary = getattr(self.services, "notary_service", None)
            if isinstance(notary, BatchingNotaryService):
                notary.attach_device(self.device_plane)
            self.health.watch_device(self.device_plane)
        # wire & gateway telemetry (utils/wire_telemetry.py): per-link
        # fabric frame/byte accounting pushed by the messaging seams,
        # codec cost attribution (native cts_hash vs pure-Python CTS),
        # journal append/fsync latency, redelivery/dedupe/backlog
        # depths pulled per tick, plus per-endpoint gateway request
        # accounting recorded by the webserver dispatch wrapper —
        # served at GET /wire and joined into GET /capacity as the
        # "wire" resource via the device plane's wire feed.
        self.wire_plane = None
        if config.wire_telemetry_enabled:
            from ..utils.wire_telemetry import WirePlane

            self.wire_plane = WirePlane(
                clock=self.services.clock,
                metrics=self.metrics,
            )
            self.wire_plane.attach_fabric(self.messaging)
            self.health.watch_wire(self.wire_plane)
            if self.device_plane is not None:
                self.device_plane.set_wire_feed(
                    self.wire_plane.wire_host_seconds)
        self.scheduler = NodeSchedulerService(self.services, self.smm.start_flow)

        # -- verifier offload ------------------------------------------
        self.verifier_service = None
        if config.verifier_type == "out_of_process":
            from .verifier import (
                OutOfProcessTransactionVerifierService,
                RedispatchPolicy,
            )

            self.verifier_service = OutOfProcessTransactionVerifierService(
                self.messaging,
                metrics=self.metrics,
                register_peer=self._register_worker_peer,
                clock=self.services.clock,
                policy=RedispatchPolicy(
                    lease_micros=config.verifier_lease_micros,
                    backoff_base_micros=config.verifier_redispatch_backoff,
                ),
            )
            self.services.transaction_verifier = self.verifier_service
            # pool-degraded alerting: a lost worker (or a starved
            # pool) pages before client timeouts do
            self.verifier_service.watch_health(self.health)
            # per-attempt verify history on the lifecycle ledger
            self.verifier_service.txstory = self.txstory

        # -- RPC --------------------------------------------------------
        users = [
            rpclib.RpcUser(u.username, u.password, tuple(u.permissions))
            for u in config.rpc_users
        ]
        self.rpc_ops = rpclib.CordaRPCOpsImpl(self.services, self.smm)
        self.rpc_server = rpclib.RPCServer(
            self.rpc_ops,
            self.messaging,
            rpclib.RPCUserService(*users),
            client_backlog=self._peer_backlog,
        )

        self._worker_peers: dict[str, PeerAddress] = {}
        self.running = False

    def _derive_keypair(self, material: str) -> schemes.KeyPair:
        """Dev-mode key derivation from shared config material (cluster
        service keys; production distributes real keys out of band)."""
        import hashlib

        return schemes.generate_keypair(
            self.config.scheme_id,
            seed=int.from_bytes(
                hashlib.sha256(material.encode()).digest()[:16], "big"
            ),
        )

    def _bft_member_keypair(self, member: str) -> schemes.KeyPair:
        cfg = self.config
        return self._derive_keypair(
            f"{cfg.cluster_name}:{cfg.cluster_key_seed}:{member}"
        )

    def _dev_seed(self, purpose: str, per_boot: bool = False):
        """Deterministic per-(node, purpose) RNG seed in dev mode, None
        (OS entropy) otherwise. The node name is mixed in: two dev nodes
        must never share a fresh-key stream, or each would hold the
        other's 'anonymous' private keys.

        per_boot additionally mixes the persistent boot counter: id/key
        streams that must not repeat across restarts (session ids, flow
        ids, fresh confidential keys) get a new stream every boot while
        staying reproducible for a given (node, boot) pair. Identity and
        cluster keys stay boot-independent — they must re-derive the
        SAME key after a restart."""
        if not self.config.dev_mode:
            return None
        import hashlib

        material = f"{self.config.name}:{self.config.key_seed}:{purpose}"
        if per_boot:
            material += f":boot{self.boot_count}"
        return int.from_bytes(
            hashlib.sha256(material.encode()).digest()[:8], "big"
        )

    # -- identity persistence ------------------------------------------------

    def _load_or_create_identity(self) -> schemes.KeyPair:
        store = PersistentKVStore(self.db, "node_identity")
        blob = store.get(b"private")
        if blob is not None:
            scheme_id = int.from_bytes(blob[:4], "big")
            return schemes.keypair_from_private(scheme_id, blob[4:])
        cfg = self.config
        seed = self._dev_seed("identity") if cfg.key_seed else None
        kp = schemes.generate_keypair(cfg.scheme_id, seed=seed)
        store.put(
            b"private",
            kp.private.scheme_id.to_bytes(4, "big") + kp.private.data,
        )
        return kp

    def _load_or_create_tls(self) -> TlsIdentity:
        # registered material first: --initial-registration stored a
        # doorman-certified TLS key+chain under certificates/tls.pem
        # (registration.py NetworkRegistrationHelper); fall back to the
        # dev-mode self-signed identity persisted in the node DB
        import os

        tls_pem = os.path.join(
            self.config.base_dir, "certificates", "tls.pem"
        )
        if os.path.exists(tls_pem):
            with open(tls_pem, "rb") as f:
                blob = f.read()
            # file layout: key PEM, then leaf cert, then the CA chain;
            # the fabric serves (and peers pin) the leaf only
            marker = b"-----BEGIN CERTIFICATE-----"
            leaf_start = blob.find(marker)
            if leaf_start == -1:
                raise RuntimeError(
                    f"{tls_pem} contains no CERTIFICATE block — the "
                    "file is corrupt or truncated; restore it or "
                    "delete it and re-run --initial-registration"
                )
            leaf_end = blob.index(marker, leaf_start + 1) \
                if blob.count(marker) > 1 else len(blob)
            return TlsIdentity(
                blob[leaf_start:leaf_end], blob[:leaf_start]
            )
        store = PersistentKVStore(self.db, "node_tls")
        cert, key = store.get(b"cert"), store.get(b"key")
        if cert is not None and key is not None:
            return TlsIdentity(bytes(cert), bytes(key))
        tls = TlsIdentity.generate(self.config.name)
        store.put(b"cert", tls.cert_pem)
        store.put(b"key", tls.key_pem)
        return tls

    # -- peer resolution -----------------------------------------------------

    def _resolve_peer(self, peer: str) -> Optional[PeerAddress]:
        """Fabric bridge target lookup: network map first (host, port,
        pinned fingerprint travel in NodeInfo), then ad-hoc worker
        registrations, then the statically-configured map host."""
        info = self.services.network_map_cache.node_by_name(peer)
        if info is not None and info.host is not None and info.port:
            return PeerAddress(info.host, info.port, info.tls_fingerprint)
        if peer in self._worker_peers:
            return self._worker_peers[peer]
        cfg = self.config
        if peer == cfg.network_map_peer and cfg.network_map_host:
            return PeerAddress(
                cfg.network_map_host,
                cfg.network_map_port,
                cfg.network_map_fingerprint,
            )
        return None

    def _register_worker_peer(self, name: str, host: str, port: int) -> None:
        self._worker_peers[name] = PeerAddress(host, port)

    def _expected_identity_key(self, peer: str):
        info = self.services.network_map_cache.node_by_name(peer)
        return None if info is None else info.legal_identity.owning_key

    def _peer_backlog(self, peer: str) -> int:
        """Outbound journal depth for one peer — the RPC server's
        dead-client detector."""
        rows = self.db.query(
            "SELECT COUNT(*) FROM fabric_out WHERE peer=?", (peer,)
        )
        return rows[0][0]

    # -- health plane ---------------------------------------------------------

    def _peer_web_urls(self) -> dict:
        """Base gateway URL per network-map peer that advertises a web
        port — the one peer list both the health rollup and the
        cross-node trace assembler ride."""
        out: dict[str, str] = {}
        for info in self.services.network_map_cache.all_nodes():
            name = info.legal_identity.name
            if name == self.config.name:
                continue
            if info.host and info.web_port:
                out[name] = f"http://{info.host}:{info.web_port}"
        return out

    def _health_peer_urls(self) -> dict:
        """The cluster rollup's peer list: every network-map node that
        advertises a web gateway (NodeInfo.web_port) answers
        GET /health?summary=1 there."""
        return {
            name: f"{base}/health?summary=1"
            for name, base in self._peer_web_urls().items()
        }

    def _launch_canary(self, complete) -> None:
        """One canary notarisation through the REAL flush path
        (utils/health.py notary_canary_fn does the work; this indirection
        exists so the probe always sees the CURRENT notary service)."""
        from ..utils.health import notary_canary_fn

        if self._canary_fn is None:
            self._canary_fn = notary_canary_fn(
                self.services, self.party, tracer=self.tracer
            )
        self._canary_fn(complete)

    # -- notary ---------------------------------------------------------------

    def _build_qos(self) -> None:
        """SLO plane for the serving path: deadline shedding, priority
        lanes, admission gating and the adaptive batching controller,
        on the node's registry so /metrics carries Qos.* and the web
        gateway serves the JSON mirror at GET /qos. An operator-
        configured batching window is the controller's CEILING (it
        tunes inside the fence, never past the configured bound);
        unset (0) falls back to the policy default ceiling."""
        from .qos import NotaryQos, QosPolicy

        self.qos = NotaryQos(
            QosPolicy(
                target_p99_micros=self.config.qos_target_p99_micros,
                max_wait_micros=(
                    self.config.notary_batch_wait_micros
                    or QosPolicy.max_wait_micros
                ),
                admission_rate_per_sec=(
                    self.config.qos_admission_rate_per_sec
                ),
                admission_burst=self.config.qos_admission_burst,
            ),
            clock=self.services.clock,
            metrics=self.metrics,
        )
        # shed/admit events land on the lifecycle ledger with the tx
        # id attached (qos.admit_tx / shed_tx)
        self.qos.txstory = self.txstory

    def _install_distributed_uniqueness(self) -> None:
        """Round-12 horizontal scale-out: the batching notary over a
        DistributedUniquenessProvider — the state-ref space
        partitioned across the cluster members named in cluster_peers
        (ShardMap; GET /shards serves the ownership map), cross-member
        transactions taking the fabric two-phase reserve→commit with
        the presumed-abort WAL on this node's database. The member
        signs with the cluster service identity, exactly like a raft
        member."""
        from .distributed_uniqueness import (
            DistributedUniquenessProvider,
            XShardPolicy,
        )
        from .persistence import (
            NotaryIntentJournal,
            ShardedPersistentUniquenessProvider,
            XShardCoordinatorJournal,
            XShardReservationJournal,
        )

        cfg = self.config
        self.services.key_management.register_keypair(self._cluster_keypair)
        if cfg.qos_enabled:
            self._build_qos()
        if cfg.notary_state_store == "commitlog":
            store = self._build_state_store(cfg.notary_cluster_shards)
        else:
            store = ShardedPersistentUniquenessProvider(
                self.db, cfg.notary_cluster_shards
            )
        self._gauge_committed_states(store)
        provider = DistributedUniquenessProvider(
            cfg.name,
            list(cfg.cluster_peers),
            self.messaging,
            self.services.clock,
            n_partitions=cfg.notary_cluster_shards,
            store=store,
            journal=XShardCoordinatorJournal(self.db),
            reservations=XShardReservationJournal(self.db),
            metrics=self.metrics,
            tracer=self.tracer,
            qos=self.qos,
            policy=XShardPolicy(
                timeout_micros=cfg.notary_xshard_timeout_micros,
                backoff_base_micros=cfg.notary_xshard_backoff,
                backoff_cap_micros=20 * cfg.notary_xshard_backoff,
            ),
            seed=self._dev_seed("xshard") or 0,
        )
        provider.txstory = self.txstory
        # boot recovery BEFORE serving: commit-marked WAL intents
        # re-drive, unmarked ones presumed-abort, journaled
        # reservations reload as immediate orphans
        provider.recover()
        self.xshard = provider
        intent_journal = None
        if cfg.notary_intent_wal:
            intent_journal = NotaryIntentJournal(self.db)
        self.services.notary_service = BatchingNotaryService(
            self.services,
            provider,
            service_identity=self._cluster_identity,
            max_wait_micros=cfg.notary_batch_wait_micros,
            metrics=self.metrics,
            qos=self.qos,
            degraded_fallback=cfg.notary_degraded_fallback,
            intent_journal=intent_journal,
        )
        self.services.notary_service.attach_txstory(self.txstory)
        if intent_journal is not None:
            self.services.notary_service.replay_intents()
        self.services.notary_service.attach_health(self.health)
        provider.attach_health(self.health)
        if self.qos is not None:
            self.health.watch_qos(self.qos)
        self.health.attach_canary(self._launch_canary)
        if self.perf is not None:
            self.services.notary_service.attach_perf(self.perf)
            self.health.watch_perf(self.perf)

    def _build_state_store(self, n_shards: int):
        """Mount the billion-state committed-state registry (round 19,
        node/statestore.py) under <base_dir>/statestore, drain the
        sqlite tables into it (ONE-WAY boot migration — commit-log
        appends are idempotent, and the sqlite clear runs last, so a
        crash mid-migration simply re-migrates on next boot), and
        export the Statestore.* gauges the GET /statestore plane
        reads alongside."""
        from .statestore import (
            ShardedCommitLogUniquenessProvider,
            migrate_sqlite_state,
        )

        store = ShardedCommitLogUniquenessProvider(
            os.path.join(self.config.base_dir, "statestore"), n_shards
        )
        migrate_sqlite_state(self.db, store)
        self.statestore = store

        def stat(key):
            return lambda s=store, k=key: s.stats()[k]

        self.metrics.gauge(
            "Statestore.CommittedStates", stat("committed_states")
        )
        self.metrics.gauge("Statestore.Segments", stat("segments"))
        self.metrics.gauge(
            "Statestore.SnapshotStates", stat("snapshot_states")
        )
        self.metrics.gauge(
            "Statestore.MemtableStates", stat("memtable_states")
        )
        self.metrics.gauge("Statestore.Compactions", stat("compactions"))
        return store

    def _gauge_committed_states(self, uniqueness) -> None:
        # set-growth without a scan: every backend maintains the count
        # O(1), so health/capacity can watch it for free
        self.metrics.gauge(
            "Notary.CommittedStates",
            lambda u=uniqueness: u.committed_count,
        )

    def _install_notary(self) -> None:
        kind = self.config.notary
        self.raft = None
        self.bft = None
        self.xshard = None
        self.statestore = None
        if kind == "":
            return
        if kind == "batching" and self.config.notary_cluster_shards > 0:
            self._install_distributed_uniqueness()
            return
        if kind in ("simple", "validating", "batching"):
            # sharded commit plane (round 6): >1 shard — or a node
            # whose DB already migrated to partition tables (the
            # layout is STICKY: once rows live in notary_commits_s<k>,
            # EVERY notary kind must read the partitions — a revert to
            # the legacy provider would consult the emptied legacy
            # table and silently accept double-spends of already
            # consumed states)
            from .persistence import ShardedPersistentUniquenessProvider

            shards = self.config.notary_shards
            stored = PersistentKVStore(
                self.db, ShardedPersistentUniquenessProvider._META_SPACE
            ).get(b"shards")
            if kind == "batching" and shards > 1:
                pass                           # explicit sharded plane
            elif stored is not None:
                if kind == "batching" and shards >= 1:
                    # an explicit count on a partitioned DB is a retune
                    # — 1 included, which migrates the rows back DOWN
                    # into a single partition
                    shards = max(shards, 1)
                else:
                    # unset (0) or a non-batching kind: keep the stored
                    # partition count — re-partitioning every boot
                    # would churn the rows for nothing, and reading the
                    # emptied legacy table instead would silently
                    # accept double-spends
                    shards = max(int.from_bytes(stored, "big"), 1)
            else:
                shards = 0                     # classic legacy layout
            if self.config.notary_state_store == "commitlog":
                # billion-state plane (round 19): the segmented commit
                # log + mmap hash index replaces the sqlite tables; a
                # one-way boot migration drains whichever layout they
                # held (legacy or partitioned)
                shards = max(self.config.notary_shards, 1)
                uniqueness = self._build_state_store(shards)
            elif shards:
                uniqueness = ShardedPersistentUniquenessProvider(
                    self.db, shards
                )
            else:
                uniqueness = PersistentUniquenessProvider(self.db)
            self._gauge_committed_states(uniqueness)
            if kind == "batching":
                shard_verifiers = None
                if (
                    shards > 1
                    and self.config.verifier_backend != "cpu"
                ):
                    # per-device verify dispatch — only worth building
                    # when this process actually sees several devices
                    # (N unpinned copies on one chip would just pay N
                    # jit caches for the same dispatch queue)
                    try:
                        import jax

                        from ..crypto.batch_verifier import (
                            per_shard_verifiers,
                        )

                        devices = jax.devices()
                        if len(devices) > 1:
                            shard_verifiers = per_shard_verifiers(
                                shards, devices=devices
                            )
                    except Exception:
                        shard_verifiers = None   # shared SPI verifier
                if self.config.qos_enabled:
                    self._build_qos()
                intent_journal = None
                if self.config.notary_intent_wal:
                    # durable intake (round 9): intents share the node
                    # database (same file, same WAL-mode fsync
                    # discipline as the fabric journals)
                    from .persistence import NotaryIntentJournal

                    intent_journal = NotaryIntentJournal(self.db)
                self.services.notary_service = BatchingNotaryService(
                    self.services,
                    uniqueness,
                    max_wait_micros=self.config.notary_batch_wait_micros,
                    metrics=self.metrics,
                    qos=self.qos,
                    shards=max(shards, 1),
                    shard_workers=self.config.notary_shard_workers,
                    shard_verifiers=shard_verifiers,
                    degraded_fallback=self.config.notary_degraded_fallback,
                    intent_journal=intent_journal,
                )
                self.services.notary_service.attach_txstory(self.txstory)
                if intent_journal is not None:
                    # boot replay: requests admitted-but-in-flight at
                    # the last crash re-enter the normal flush path;
                    # uniqueness dedupe absorbs already-committed ones
                    self.services.notary_service.replay_intents()
                # health plane over the serving path: the flush loop's
                # heartbeat, the SLO burn-rate + shed-ratio rules (when
                # QoS is on), and the canary probe riding real flushes
                self.services.notary_service.attach_health(self.health)
                if self.qos is not None:
                    self.health.watch_qos(self.qos)
                self.health.attach_canary(self._launch_canary)
                # perf plane over the same path: flush phase marks feed
                # the skew/overlap telemetry, the served-request counter
                # becomes the in-process notarisations/s history, and
                # the retrace + skew alerts land on the health monitor
                if self.perf is not None:
                    self.services.notary_service.attach_perf(self.perf)
                    self.health.watch_perf(self.perf)
                return
            cls = {
                "simple": SimpleNotaryService,
                "validating": ValidatingNotaryService,
            }[kind]
            self.services.notary_service = cls(self.services, uniqueness)
            return
        if kind in ("raft", "raft-validating"):
            from .raft import RaftNode, RaftUniquenessProvider

            self.services.key_management.register_keypair(
                self._cluster_keypair
            )

            def factory(apply_fn, **raft_kw):
                return RaftNode(
                    self.config.name,
                    list(self.config.cluster_peers),
                    self.messaging,
                    apply_fn,
                    self.services.clock,
                    cluster=self.config.cluster_name,
                    db=self.db,
                    rng=random.Random(self._dev_seed("raft")),
                    # consensus observability: Raft.Phase.* timers +
                    # lag gauges on this node's scrape surface, phase
                    # spans joined to propagated client traces, applied
                    # commits stamped onto the lifecycle ledger
                    metrics=self.metrics,
                    tracer=self.tracer,
                    txstory=self.txstory,
                    **raft_kw,
                )

            provider = RaftUniquenessProvider(factory)
            self.raft = provider.raft
            cls = (
                SimpleNotaryService if kind == "raft"
                else ValidatingNotaryService
            )
            self.services.notary_service = cls(
                self.services,
                provider,
                service_identity=self._cluster_identity,
            )
            return
        if kind == "bft":
            from .bft import BftReplica, BFTNotaryService

            # sign replies with the derived member key, not the node key
            self.services.key_management.register_keypair(
                self._cluster_keypair
            )
            replica = BftReplica(
                self.config.name,
                list(self.config.cluster_peers),
                self.messaging,
                lambda cmd, ts: (None, None),
                self.services.clock,
                cluster=self.config.cluster_name,
                rng=random.Random(self._dev_seed("bft")),
                metrics=self.metrics,
                tracer=self.tracer,
                txstory=self.txstory,
            )
            self.bft = replica
            self.services.notary_service = BFTNotaryService(
                self.services,
                replica,
                self._cluster_identity,
                member_key=self._cluster_keypair.public,
                member_keys={
                    peer: self._bft_member_keypair(peer).public
                    for peer in self.config.cluster_peers
                },
            )
            # config-path invariant: production clusters always run in
            # signed-certificate mode — the hook-less fallback of
            # _valid_prepared_entry is reachable only from unit rigs
            # that wire a bare BftReplica (round-4 verdict Weak #5)
            if replica.sign_prepare_fn is None or (
                replica.verify_prepare_fn is None
            ):
                raise AssertionError(
                    "BFT notary booted without prepare-signature hooks"
                )
            return
        raise NotImplementedError(f"unknown notary kind {kind!r}")

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "Node":
        import dataclasses

        self.messaging.start()
        # web gateway bound BEFORE the NodeInfo freezes (its port is
        # advertised through the network map so peers can pull
        # GET /health for the /cluster rollup) but not yet SERVING:
        # answering /healthz during a slow boot (checkpoint restore,
        # map registration) would feed an orchestrator 503s and
        # restart-loop exactly the slow-starting nodes. A bind failure
        # (port taken) must not strand a half-started node.
        self.web = None
        if self.config.web_port >= 0:
            u = self.config.rpc_users[0]
            try:
                self.web = self._build_webserver(
                    u.username, u.password, port=self.config.web_port
                )
            except Exception:
                self.stop()
                raise
        # the fabric bound its listen port; advertise the real one
        self.info = dataclasses.replace(
            self.info,
            port=self.messaging.listen_port,
            web_port=self.web.port if self.web is not None else None,
        )
        self.services.my_info = self.info
        self.services.network_map_cache.add_node(self.info)
        self.services.identity.register(self.party)
        if self.network_map_client is not None:
            self.network_map_client.register()
            self.network_map_client.fetch(subscribe=True)
        if self.network_map_service is not None:
            # the map host publishes its own NodeInfo so clients learn
            # its identity (and, when it doubles as a notary, that too)
            reg = nm.NodeRegistration(
                info=self.info,
                serial=self.services.clock.now_micros(),
                op=nm.ADD,
                expires_micros=self.services.clock.now_micros()
                + nm.NetworkMapClient.DEFAULT_TTL_MICROS,
            )
            try:
                self.network_map_service._process_registration(
                    nm.sign_registration(reg, self.keypair.private)
                )
            except ValueError:
                pass   # restart within one clock microsecond: already registered
        restored = self.smm.restore_checkpoints()
        if restored:
            import logging

            logging.getLogger("corda_tpu.node").info(
                "restored %d checkpointed flows", restored
            )
        self.running = True
        if self.web is not None:
            self.web.start()
        if self.perf is not None and self.config.perf_profile_hz > 0:
            # continuous profiling over this node's long-lived threads
            # (everything but the sampler itself); started only after
            # boot so warmup compiles don't dominate the first capture
            self.perf.profiler.start()
        # boot work (map registration, checkpoint restore) may exceed
        # the watchdog deadline: the pump loop starts NOW, so its
        # heartbeat clock does too
        self._hb_pump.beat()
        return self

    def _tick_services(self) -> None:
        self.scheduler.tick()
        self.smm.tick()
        notary = getattr(self.services, "notary_service", None)
        if isinstance(notary, BatchingNotaryService):
            # the pump interval is the batch deadline: everything that
            # queued since the last pump shares one SPI dispatch
            notary.tick()
        if self.verifier_service is not None:
            # pool self-healing: lease expiry, redispatch backoff and
            # hedging all walk on the pump cadence
            self.verifier_service.tick()
        if self.xshard is not None:
            # distributed uniqueness: resend schedules, reserve-phase
            # timeouts, commit re-drives and orphan queries all walk
            # on the pump cadence too
            self.xshard.tick()
        if getattr(self, "statestore", None) is not None:
            # commit-log compaction walks on the pump cadence:
            # fold piled-up sealed segments into the next snapshot
            # generation off the serving path
            self.statestore.maintain()
        if self.raft is not None:
            if self._hb_raft is None:
                self._hb_raft = self.health.heartbeat("raft.driver")
            self.raft.tick()
            self._hb_raft.beat()
        if self.bft is not None:
            if self._hb_bft is None:
                self._hb_bft = self.health.heartbeat("bft.driver")
            self.bft.tick()
            self._hb_bft.beat()
        if self.network_map_client is not None:
            # liveness heartbeat: periodic map re-registration keeps
            # the explorer's last-seen column meaningful
            self.network_map_client.tick()
        if self.txstory is not None:
            # lifecycle ledger: group-commit the sqlite index buffer
            self.txstory.tick()
        # health plane last: the watchdog judges the beats this tick
        # just made, the canary launches, alert rules walk their states
        self.health.tick()
        if self.perf is not None:
            # history sampling rides the same cadence (self-throttled
            # to the perf policy's sample gap)
            self.perf.tick()
        if self.device_plane is not None:
            # device telemetry sampling too (self-throttled alike) —
            # after health.tick so rules judge last-sample state and
            # this tick's sample serves the NEXT walk
            self.device_plane.tick()
        if self.wire_plane is not None:
            # wire telemetry pulls fabric depths (journal/dedupe/
            # backlog) on the same self-throttled cadence
            self.wire_plane.tick()

    def run(self) -> None:
        """The pump loop — the single server thread (Node.kt:344)."""
        import threading

        self._run_thread = threading.current_thread()
        try:
            while self.running:
                n = self.messaging.pump(block=True, timeout=0.2)
                self._hb_pump.beat(progress=n)
                self._tick_services()
        finally:
            self._run_thread = None

    def pump(self, timeout: float = 0.0) -> int:
        """One pump step (embedded/driver use)."""
        n = self.messaging.pump(block=timeout > 0, timeout=timeout)
        self._hb_pump.beat(progress=n)
        self._tick_services()
        return n

    def stop(self) -> None:
        import threading

        # idempotence keys on its own flag, NOT on `running`: the CLI
        # signal handler clears `running` to break the pump loop, and
        # the finally-block stop() after it must still tear down (web
        # gateway, fabric, db) instead of early-returning
        if getattr(self, "_stopped", False):
            return
        self._stopped = True
        self.running = False
        web = getattr(self, "web", None)
        if web is not None:
            web.stop()
        perf = getattr(self, "perf", None)
        if perf is not None:
            perf.profiler.stop()
        # an embedded run() thread must drain its current pump before
        # the database closes under it
        run_thread = getattr(self, "_run_thread", None)
        if (
            run_thread is not None
            and run_thread is not threading.current_thread()
        ):
            run_thread.join(timeout=5)
        self.scheduler.stop()
        self.smm.stop()
        notary = getattr(self.services, "notary_service", None)
        if isinstance(notary, BatchingNotaryService):
            notary.stop()   # shard worker threads, when running
        if getattr(self, "xshard", None) is not None:
            self.xshard.stop()
        if self.raft is not None:
            self.raft.stop()
        if self.bft is not None:
            self.bft.stop()
        self.messaging.stop()
        self.db.close()

    # -- conveniences ---------------------------------------------------------

    @property
    def vault(self):
        return self.services.vault

    def rpc_client(self, username: str, password: str) -> rpclib.RPCClient:
        """Loopback RPC client on this node's own endpoint (the shell's
        connection — InteractiveShell talks to the node the same way a
        remote client does)."""
        return rpclib.RPCClient(
            self.messaging, self.config.name, username, password
        )

    def webserver(self, username: str, password: str, port: int = 0):
        """Embedded web gateway over the node's own RPC surface, with
        this node's MetricRegistry at /metrics, the flight recorder at
        /traces, the QoS plane (when enabled) at /qos, the health
        plane at /healthz + /health, the fleet rollup at /cluster,
        the perf-attribution plane at /perf (+ folded profiler stacks
        at /profile), the device-telemetry plane at /device + the
        capacity model at /capacity, plus the ledger explorer UI at
        /web/explorer/, and the wire & gateway telemetry plane at
        /wire. The node's pump
        loop (run()) drives message delivery, so the gateway itself
        only polls futures (pass a real pump when embedding without
        run())."""
        return self._build_webserver(username, password, port).start()

    def _build_webserver(self, username: str, password: str, port: int = 0):
        """Bind the gateway without serving yet — start() begins the
        accept loop once the node is fully booted (the bound port is
        what NodeInfo.web_port advertises)."""
        import corda_tpu.tools.web_explorer  # noqa: F401 - /api/explorer

        from ..client.webserver import NodeWebServer

        return NodeWebServer(
            self.rpc_client(username, password),
            pump=lambda: None,
            port=port,
            metrics=self.metrics,
            tracer=self.tracer,
            qos=self.qos,
            health=self.health,
            cluster=self.cluster_health,
            perf=self.perf,
            cluster_traces=self.cluster_traces,
            incidents=self.incidents,
            shards=getattr(self, "xshard", None),
            txstory=self.txstory,
            cluster_tx=self.cluster_tx,
            device=self.device_plane,
            wire=self.wire_plane,
            statestore=getattr(self, "statestore", None),
            slow_request_micros=self.config.web_slow_request_micros,
        )


def banner(config: NodeConfig) -> str:
    return (
        "\n   ______               __         ______ ___  __  __\n"
        "  / ____/___  _________/ /___ _   /_  __// _ \\/ / / /\n"
        " / /   / __ \\/ ___/ __  / __ `/    / /  / ___/ /_/ /\n"
        "/ /___/ /_/ / /  / /_/ / /_/ /    / /  / /  / __  /\n"
        "\\____/\\____/_/   \\__,_/\\__,_/    /_/  /_/  /_/ /_/\n\n"
        f"  node: {config.name}   notary: {config.notary or 'none'}   "
        f"map: {'host' if config.is_network_map_host else config.network_map_peer}\n"
    )
