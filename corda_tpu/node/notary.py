"""Notary services: uniqueness (double-spend prevention) + signing.

Reference: node/.../services/transactions/ (SURVEY §2.7) —
SimpleNotaryService / ValidatingNotaryService over a
PersistentUniquenessProvider (locked stateRef->consumingTx map,
PersistentUniquenessProvider.kt:20, commit :63+), TimeWindowChecker
(core/.../node/services/TimeWindowChecker.kt), and the NotaryFlow
service side (core/.../flows/NotaryFlow.kt:107-130).

TPU-first: the notary is the batch seam. `BatchingNotaryService`
accumulates concurrent notarisation requests in a queue and, on each
pump tick (or when `max_batch` fills), drains EVERY pending
transaction's signature checks through ONE BatchSignatureVerifier
dispatch — a single padded XLA program across transactions — then
commits inputs and scatters signed replies back to the waiting service
flows. This is the serving path the reference approximates with
horizontally-scaled verifier processes (SURVEY §2.5,
OutOfProcessTransactionVerifierService.kt:19-73).
"""

from __future__ import annotations

import gc
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core import serialization as ser
from ..core.contracts import StateRef, TimeWindow
from ..core.identity import Party
from ..core.transactions import (
    FilteredTransaction,
    SignedTransaction,
    TransactionVerificationError,
)
from ..crypto.hashes import SecureHash
from ..crypto.tx_signature import TransactionSignature
from ..utils import tracing
from ..utils.metrics import MetricRegistry
from .services import ServiceHub

# -- errors (wire-serializable: sent back to the requesting flow) ------------


@ser.serializable
@dataclass(frozen=True)
class NotaryError:
    """Base marker for notarisation failures (reference:
    core/.../flows/NotaryError.kt)."""

    kind: str
    message: str
    conflict: Any = None    # {state_ref: consuming_tx_id} for conflicts


class NotaryException(Exception):
    def __init__(self, error: NotaryError):
        self.error = error
        super().__init__(f"notarisation failed: {error.kind}: {error.message}")


class UniquenessConflict(Exception):
    def __init__(self, conflict: dict):
        self.conflict = conflict   # StateRef -> consuming tx id
        super().__init__(f"{len(conflict)} input(s) already consumed")


# journaled flow-future outcomes must round-trip the codec so a restored
# notary flow replays the same conflict
ser.register_custom(
    UniquenessConflict,
    "UniquenessConflict",
    lambda e: e.conflict,
    lambda v: UniquenessConflict(dict(v)),
)


# -- uniqueness providers ----------------------------------------------------


def snapshot_uniqueness_map(committed: dict) -> list:
    """Canonical (sorted, ser-encodable) dump of a stateRef->tx map.

    ONE implementation shared by the Raft snapshot and the BFT
    checkpoint paths: the encoding is consensus-critical (BFT
    checkpoint digests are computed over it), so two drifting copies
    would break cross-replica state-transfer agreement."""
    return sorted(
        [ser.encode(ref), h.bytes_] for ref, h in committed.items()
    )


def restore_uniqueness_map(state) -> dict:
    return {
        ser.decode(bytes(r)): SecureHash(bytes(h)) for r, h in state
    }


class UniquenessProvider:
    """stateRef -> consuming-tx registry; the core consensus primitive."""

    # True on providers whose commit completes inline on this host
    # (in-memory, sqlite): the batching notary then drains a whole
    # flush through ONE commit_many call instead of a future +
    # callback per transaction. Distributed providers (Raft, BFT)
    # stay False — their commits resolve on cluster consensus.
    batch_synchronous = False

    def commit(
        self, states: list[StateRef], tx_id: SecureHash, requester: Party
    ) -> None:
        raise NotImplementedError

    def commit_async(
        self, states: list[StateRef], tx_id: SecureHash, requester: Party
    ):
        """Future-shaped commit (what notary flows actually await):
        local providers resolve immediately; distributed ones (Raft,
        BFT) resolve when the cluster reaches consensus."""
        from ..flows.api import FlowFuture

        fut = FlowFuture()
        try:
            self.commit(states, tx_id, requester)
            fut.set_result(None)
        except Exception as e:
            fut.set_exception(e)
        return fut

    def commit_many(self, entries) -> list:
        """Batched commit: `entries` is [(states, tx_id, requester)];
        returns one outcome per entry, in order — None on success or
        the exception (UniquenessConflict etc.) that entry raised.
        Semantics are EXACTLY sequential commit in list order: an
        earlier entry's refs are committed before a later conflicting
        entry is checked, so intra-batch double spends resolve
        first-wins like they would one call at a time."""
        out = []
        for states, tx_id, requester in entries:
            try:
                self.commit(states, tx_id, requester)
                out.append(None)
            except Exception as e:   # noqa: BLE001 - per-entry outcome
                out.append(e)
        return out


class InMemoryUniquenessProvider(UniquenessProvider):
    """Single-node map (reference: PersistentUniquenessProvider
    semantics, minus the JDBC persistence — see persistence.py for the
    sqlite-backed version). Commit is all-or-nothing: on any conflict
    nothing is recorded and the full conflict set is reported."""

    batch_synchronous = True

    def __init__(self):
        self.committed: dict[StateRef, SecureHash] = {}

    def commit(self, states, tx_id, requester) -> None:
        conflict = {
            ref: self.committed[ref]
            for ref in states
            if ref in self.committed and self.committed[ref] != tx_id
        }
        if conflict:
            raise UniquenessConflict(conflict)
        for ref in states:
            self.committed[ref] = tx_id


# -- time window -------------------------------------------------------------


class TimeWindowChecker:
    """Clock-tolerance validation (TimeWindowChecker.kt): the notary
    accepts a window iff `now` (± tolerance) intersects it."""

    def __init__(self, clock, tolerance_micros: int = 30_000_000):
        self.clock = clock
        self.tolerance = tolerance_micros

    def is_valid(self, tw: Optional[TimeWindow], now: Optional[int] = None) -> bool:
        """`now` override: distributed notaries validate against the
        consensus-ordered timestamp so every replica gets one answer."""
        if tw is None:
            return True
        if now is None:
            now = self.clock.now_micros()
        if tw.until_time is not None and now - self.tolerance >= tw.until_time:
            return False
        if tw.from_time is not None and now + self.tolerance < tw.from_time:
            return False
        return True


# -- the services ------------------------------------------------------------


class NotaryService:
    """Common commit-and-sign core shared by every notary flavour."""

    validating = False

    def __init__(
        self,
        services: ServiceHub,
        uniqueness: Optional[UniquenessProvider] = None,
        tolerance_micros: int = 30_000_000,
        service_identity: Optional[Party] = None,
    ):
        """`service_identity`: the cluster-shared notary Party for
        distributed notaries (each member holds the shared key and
        answers for it); None = this node's own identity."""
        self.services = services
        self.uniqueness = uniqueness or InMemoryUniquenessProvider()
        self.time_window_checker = TimeWindowChecker(
            services.clock, tolerance_micros
        )
        self.service_identity = service_identity

    @property
    def identity(self) -> Party:
        if self.service_identity is not None:
            return self.service_identity
        return self.services.my_info.notary_identity

    def commit_and_sign(
        self,
        tx_id: SecureHash,
        inputs: list[StateRef],
        time_window: Optional[TimeWindow],
        requester: Party,
    ):
        """validate time window -> commit inputs -> sign tx id
        (NotaryFlow.Service.call, NotaryFlow.kt:110-130). A generator
        (`yield from` it inside a flow): the commit awaits the
        uniqueness provider's future, which suspends the service flow
        while a distributed provider reaches consensus. Returns a
        TransactionSignature or a NotaryError."""
        from ..flows.api import wait_future

        if not self.time_window_checker.is_valid(time_window):
            return NotaryError(
                "time-window-invalid",
                f"window {time_window} outside notary clock tolerance",
            )
        try:
            yield from wait_future(
                self.uniqueness.commit_async(inputs, tx_id, requester)
            )
        except UniquenessConflict as e:
            return NotaryError(
                "conflict",
                str(e),
                conflict={str(r): h for r, h in e.conflict.items()},
            )
        except Exception as e:
            return NotaryError("commit-unavailable", str(e))
        sig = self.services.key_management.sign(
            tx_id, self.identity.owning_key
        )
        return sig


class SimpleNotaryService(NotaryService):
    """Non-validating: sees only a Merkle tear-off of (inputs, notary,
    time window) — privacy-preserving, trusts the requester for contract
    validity (SimpleNotaryService.kt)."""

    def process(
        self,
        ftx: FilteredTransaction,
        requester: Party,
        deadline: Optional[int] = None,
    ):
        # `deadline` (node/qos.py) is accepted on every notary flavour
        # so the service flow passes it uniformly; only the batching
        # notary currently sheds on it (this flavour serves per-request
        # — by the time it runs, answering costs less than shedding)
        del deadline
        try:
            ftx.verify()
        except TransactionVerificationError as e:
            return NotaryError("invalid-proof", str(e))
        # completeness: a tear-off hiding an input (or the time window /
        # notary) would let the requester double-spend the hidden state
        from ..core.transactions import G_INPUTS, G_NOTARY, G_TIMEWINDOW

        for g, what in (
            (G_INPUTS, "inputs"),
            (G_NOTARY, "notary"),
            (G_TIMEWINDOW, "time window"),
        ):
            if not ftx.all_revealed(g):
                return NotaryError(
                    "incomplete-tearoff",
                    f"tear-off hides {what} components",
                )
        if ftx.notary != self.identity:
            return NotaryError(
                "wrong-notary", f"tx names notary {ftx.notary}, I am "
                f"{self.identity}"
            )
        return (
            yield from self.commit_and_sign(
                ftx.id, list(ftx.inputs), ftx.time_window, requester
            )
        )


@dataclass
class _PendingNotarisation:
    stx: SignedTransaction
    requester: Party
    future: Any   # FlowFuture resolved with TransactionSignature | NotaryError
    # tracing: the frame's live root span (utils/tracing.py), opened at
    # wire-frame ingest. The flush attributes its phase intervals to it
    # and ENDS it when this request is answered. None when tracing is
    # off — the disabled path costs one falsy check per request.
    span: Any = None
    # QoS (node/qos.py): the request's propagated absolute-microsecond
    # deadline and its arrival time on the node clock. A request whose
    # deadline passed while it queued is shed pre-stage (the flush
    # answers a typed `shed` NotaryError without spending verify work);
    # arrival feeds the admitted-latency histogram the adaptive
    # batching controller steers by. Both None when QoS is off.
    deadline: Optional[int] = None
    arrival_micros: Optional[int] = None


class BatchingNotaryService(NotaryService):
    """Batch-committing validating notary — the north-star serving path
    (SURVEY §7 Phase 4).

    `process` enqueues the request and suspends the service flow on a
    future; `flush` (driven by the node pump tick, or immediately when
    `max_batch` requests are queued) drains the queue:

      queue -> ONE BatchSignatureVerifier dispatch over every pending
      transaction's signatures (the SPI pads/buckets into fixed XLA
      shapes) -> per-tx required-signer/contract/time-window checks ->
      uniqueness commit in arrival order -> scatter signed replies.

    Under the pump model the batch window is one delivery round: every
    request that arrived since the last quiescent point shares a single
    TPU dispatch, which is exactly the queue->pad/bucket->dispatch->
    scatter loop the reference approximates with horizontally-scaled
    verifier processes (NotaryFlow.kt:107-130 per-request service,
    OutOfProcessTransactionVerifierService.kt:19-73 offload seam).
    """

    validating = True

    def __init__(
        self,
        services: ServiceHub,
        uniqueness: Optional[UniquenessProvider] = None,
        tolerance_micros: int = 30_000_000,
        service_identity: Optional[Party] = None,
        max_batch: int = 512,
        max_wait_micros: int = 0,
        metrics: Optional[MetricRegistry] = None,
        qos=None,
    ):
        """`max_wait_micros` is the batching DEADLINE (SURVEY §7 hard
        part 4 — latency vs throughput): 0 (default) flushes every pump
        tick; positive, the tick HOLDS arrivals until the oldest one
        has waited that long (or `max_batch` fills), so a lightly
        loaded notary still forms deep batches — throughput rides the
        flush depth (BASELINE.md round-3 sweep), at a bounded latency
        cost the operator chooses.

        `metrics`: the node's MetricRegistry — pass it and the batching
        counters, ratio gauge, flush-phase timers and ingest-ring
        gauges all land on the node's /metrics surface; None keeps a
        private registry (embedded/test rigs).

        `qos`: an optional node/qos.NotaryQos. With one attached,
        max_batch/max_wait_micros become the STARTING point of its
        adaptive batching controller (which retunes both each flush to
        hold the configured p99 target), expired requests are shed
        pre-stage into typed `shed` errors, and every answered request
        feeds the admitted-latency histogram the controller steers by.
        None keeps the static knobs and a zero-cost hot path."""
        super().__init__(
            services, uniqueness, tolerance_micros, service_identity
        )
        self.max_batch = max_batch
        self.max_wait_micros = max_wait_micros
        self.qos = qos
        self._pending: list[_PendingNotarisation] = []
        self._ingest_ring = None   # attach_ingest: pre-decoded arrivals
        self._oldest_arrival: Optional[int] = None
        self._health_heartbeat = None   # attach_health: flush-loop liveness
        # registry-backed metrics (scrapeable at /metrics, unlike the
        # bare ints they replace): dispatches vs requests IS the
        # batching ratio, exported as its own gauge
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self._batches_counter = self.metrics.counter(
            "Notary.BatchesDispatched"
        )
        self._requests_counter = self.metrics.counter(
            "Notary.RequestsBatched"
        )
        self.metrics.gauge(
            "Notary.BatchingRatio",
            lambda: (
                self._requests_counter.count / self._batches_counter.count
                if self._batches_counter.count
                else 0.0
            ),
        )
        # per-phase flush timers: always on (a handful of updates per
        # FLUSH, not per tx), so /metrics carries the stage breakdown
        # continuously — the registry-backed replacement for the old
        # env-gated phase_seconds dict
        self._phase_timers: dict[str, Any] = {}
        # CORDA_TPU_NOTARY_PROFILE=1: additionally accumulate per-phase
        # wall seconds across flushes into a plain dict (BASELINE.md
        # serving-profile methodology; bench.py prints it). The
        # phase_seconds property is the back-compat view.
        self._phase_profile: Optional[dict] = (
            {} if os.environ.get("CORDA_TPU_NOTARY_PROFILE") else None
        )

    # -- back-compat views over the registry-backed metrics ----------------

    @property
    def batches_dispatched(self) -> int:
        return self._batches_counter.count

    @property
    def requests_batched(self) -> int:
        return self._requests_counter.count

    @property
    def phase_seconds(self) -> Optional[dict]:
        """The CORDA_TPU_NOTARY_PROFILE accumulation dict (None when
        profiling is off) — the live object, so callers may clear() it
        between warm-up and timed reps as before."""
        return self._phase_profile

    @property
    def effective_max_batch(self) -> int:
        """The live flush-depth knob: the adaptive controller's when
        QoS is attached, the static config otherwise."""
        qos = self.qos
        return qos.controller.batch if qos is not None else self.max_batch

    @property
    def effective_wait_micros(self) -> int:
        """The live batching-window knob (see effective_max_batch)."""
        qos = self.qos
        return (
            qos.controller.wait_micros if qos is not None
            else self.max_wait_micros
        )

    def process(
        self,
        stx: SignedTransaction,
        requester: Party,
        deadline: Optional[int] = None,
    ):
        from ..flows.api import FlowFuture, wait_future

        if stx.wtx.notary != self.identity:
            return NotaryError(
                "wrong-notary",
                f"tx names notary {stx.wtx.notary}, I am {self.identity}",
            )
        qos = self.qos
        arrival = None
        if qos is not None:
            from . import qos as qoslib

            arrival = self.services.clock.now_micros()
            if qoslib.expired(deadline, arrival):
                # dead on arrival: answer without queuing — the flow
                # entry's pre-decode-equivalent cheapest point
                qos.count_shed(qoslib.SHED_EXPIRED_INGRESS)
                return NotaryError(
                    qoslib.SHED_KIND,
                    f"deadline {deadline} already expired at arrival",
                )
            # per-client admission gate on the REQUEST path (the same
            # token bucket the lane router applies at ring-seam
            # fabrics): one flooding requester is rate-shaped here,
            # before any queue slot or verify work is spent on it
            if not qos.admission.admit(requester.name, arrival):
                qos.count_shed(qoslib.SHED_ADMISSION)
                return NotaryError(
                    qoslib.SHED_KIND,
                    f"admission rate exceeded for {requester.name}",
                )
            # brownout on the request path: at level 2 deadline-less
            # traffic sheds here too — with no SLO to serve it by, it
            # is the first load the degraded notary stops carrying
            if qos.brownout_level >= 2 and deadline is None:
                qos.count_shed(qoslib.SHED_BROWNOUT_NO_DEADLINE)
                return NotaryError(
                    qoslib.SHED_KIND,
                    "brownout: deadline-less requests are being shed",
                )
            qos.admitted.inc()
        fut = FlowFuture()
        if not self._pending:
            self._oldest_arrival = self.services.clock.now_micros()
        # flow-driven requests trace too: a root span per notarisation
        # (the wire-ingest path arrives with its span already attached
        # via attach_ingest; this is the fabric-less service entry)
        tracer = tracing.get_tracer()
        span = None
        if tracer.enabled:
            span = tracer.start_trace(
                "notarise.request", tx_id=str(stx.id), requester=requester.name
            )
        self._pending.append(
            _PendingNotarisation(
                stx, requester, fut, span=span,
                deadline=deadline, arrival_micros=arrival,
            )
        )
        if len(self._pending) >= self.effective_max_batch:
            self.flush()
        result = yield from wait_future(fut)
        return result

    def attach_ingest(self, ring) -> None:
        """Wire the pipelined wire-ingest seam (node/ingest.py): the
        ring carries batches of _PendingNotarisation whose stx was
        decoded, Merkle-id'd and signature-staged by the ingest
        pipeline — the flush drains them directly, and its stage phase
        reuses the memoised staging instead of re-staging. The ring is
        BOUNDED: when this notary falls behind, the producer's `put`
        blocks, which is the backpressure that keeps the decode pool
        from running unboundedly ahead of the TPU dispatch."""
        self._ingest_ring = ring
        # backpressure visibility: depth + high-water gauges on this
        # notary's registry, so the ring filling up shows on /metrics
        # BEFORE it stalls the producer
        from .messaging import register_ring_gauges

        register_ring_gauges(self.metrics, "notary", ring)

    def attach_health(self, monitor) -> None:
        """Register this notary's flush loop on the health plane
        (utils/health.py): a `notary.flush` heartbeat beaten every
        tick, carrying requests answered as progress and the live
        queue depth (pending + ingest ring) for livelock detection —
        a flush loop that ticks forever while its queue sits full and
        nothing resolves is wedged in a way the stall detector can't
        see. Pass None to detach (bench A/B rigs)."""
        if monitor is None:
            self._health_heartbeat = None
            return
        self._health_heartbeat = monitor.heartbeat(
            "notary.flush",
            queue_depth=lambda: len(self._pending)
            + (
                len(self._ingest_ring)
                if self._ingest_ring is not None
                else 0
            ),
        )

    def _drain_ingest(self) -> None:
        ring = self._ingest_ring
        if ring is not None:
            for batch in ring.drain():
                self._pending.extend(batch)
            if self._pending and self._oldest_arrival is None:
                self._oldest_arrival = self.services.clock.now_micros()

    def tick(self) -> int:
        """Pump hook (MockNetwork `node.ticks` / Node._tick_services):
        flush whatever accumulated during the last delivery round —
        unless a batching deadline is set and neither it nor max_batch
        has been reached yet. Returns requests answered (0 = held or
        quiescent)."""
        self._drain_ingest()
        hb = self._health_heartbeat
        n = len(self._pending)
        if not n:
            if hb is not None:
                hb.beat()
            return 0
        if self.effective_wait_micros and n < self.effective_max_batch:
            age = (
                self.services.clock.now_micros()
                - (self._oldest_arrival or 0)
            )
            if age < self.effective_wait_micros:
                # held, not wedged: the loop is alive (beat), it just
                # chose to wait — zero progress, which is exactly what
                # livelock detection should see while a batch forms
                if hb is not None:
                    hb.beat()
                return 0
        self.flush()
        if hb is not None:
            hb.beat(progress=n)
        return n

    def _mark(
        self, phase: str, t_prev: float, marks: Optional[list] = None
    ) -> float:
        """Phase boundary: charge now - t_prev to `phase` on the
        registry timer (always), the profile dict (when
        CORDA_TPU_NOTARY_PROFILE is set), and `marks` (the per-flush
        interval list trace-span emission consumes). Always returns
        now so call sites stay one-liners."""
        now = time.perf_counter()
        dt = now - t_prev
        timer = self._phase_timers.get(phase)
        if timer is None:
            timer = self._phase_timers[phase] = self.metrics.timer(
                "Notary.FlushPhase." + phase
            )
        timer.update(dt)
        if self._phase_profile is not None:
            self._phase_profile[phase] = (
                self._phase_profile.get(phase, 0.0) + dt
            )
        if marks is not None:
            marks.append((phase, t_prev, now))
        return now

    def flush(self) -> None:
        # A flush allocates O(batch) objects (futures, ladder requests,
        # resolved ltxs) that stay reachable until the scatter at the
        # end — a generational collection mid-flush walks the whole
        # staged heap for nothing, and at 16k-deep flushes those gen-2
        # sweeps were 68% of the serving wall (BASELINE.md round-3
        # profile). Suspend automatic GC for the bounded flush body;
        # collection resumes (and catches up) between pump ticks.
        self._drain_ingest()   # pre-ingested arrivals join this flush
        gc_was_enabled = gc.isenabled()
        if gc_was_enabled:
            gc.disable()
        try:
            self._flush_inner()
        finally:
            if gc_was_enabled:
                gc.enable()

    def _flush_inner(self) -> None:
        pending, self._pending = self._pending, []
        self._oldest_arrival = None
        if not pending:
            return
        if self.qos is not None:
            pending = self._qos_admit(pending)
            if not pending:
                self.qos.observe_flush(0, len(self._pending))
                return
        # `marks` collects this flush's phase intervals; the finally
        # attributes them to every member frame's trace and ENDS the
        # per-frame root spans — on every exit path (normal, streamed,
        # dispatch failure), so upstream traces always complete
        marks: list[tuple[str, float, float]] = []
        try:
            self._flush_body(pending, marks)
        finally:
            self._emit_flush_trace(pending, marks)
            if self.qos is not None:
                self._qos_feedback(pending)

    def _qos_admit(
        self, pending: list[_PendingNotarisation]
    ) -> list[_PendingNotarisation]:
        """Pre-stage QoS pass over one flush's intake: shed requests
        whose deadline passed while they queued (a typed `shed` answer
        — the client gave up; verifying it would burn a TPU batch lane
        on a dead request), then cap the served depth at the adaptive
        controller's batch so one flush cannot blow the latency budget;
        the overflow re-queues AHEAD of newer arrivals (FIFO holds)."""
        from . import qos as qoslib

        qos = self.qos
        now = self.services.clock.now_micros()
        live: list[_PendingNotarisation] = []
        for p in pending:
            if qoslib.expired(p.deadline, now):
                qos.count_shed(qoslib.SHED_EXPIRED_FLUSH)
                if p.span:
                    # shed events are span events: the trace shows WHY
                    # this notarisation never reached the dispatch
                    p.span.add_event(
                        "qos.shed", reason=qoslib.SHED_EXPIRED_FLUSH
                    )
                    p.span.set_attribute("shed", qoslib.SHED_EXPIRED_FLUSH)
                    p.span.end()
                p.future.set_result(
                    NotaryError(
                        qoslib.SHED_KIND,
                        f"deadline {p.deadline} expired while queued "
                        f"(now {now})",
                    )
                )
            else:
                live.append(p)
        cap = qos.controller.batch
        if len(live) > cap:
            overflow = live[cap:]
            live = live[:cap]
            self._pending = overflow + self._pending
            self._oldest_arrival = (
                overflow[0].arrival_micros
                if overflow[0].arrival_micros is not None
                else now
            )
        return live

    def _qos_feedback(self, served: list[_PendingNotarisation]) -> None:
        """Post-flush QoS pass: admitted-request completion latency
        (node-clock micros, arrival -> answer) into the histogram the
        adaptive controller reads, then one controller/brownout
        observation with the depth served and the backlog left.
        Futures still open here (distributed-commit consensus resolves
        them later) record at RESOLUTION via a done callback — slow
        consensus commits must reach the p99 the controller steers by,
        or it would stretch the window while the real SLO breaches."""
        qos = self.qos
        now = self.services.clock.now_micros()
        for p in served:
            if p.arrival_micros is None:
                continue
            fut = p.future
            if getattr(fut, "done", False):
                qos.record_admitted(now - p.arrival_micros)
            elif hasattr(fut, "add_done_callback"):
                fut.add_done_callback(
                    lambda f, arr=p.arrival_micros, q=qos: q.record_admitted(
                        q.now_micros() - arr
                    )
                )
        qos.observe_flush(len(served), len(self._pending))

    def _emit_flush_trace(self, pending, marks) -> None:
        """Per-frame trace assembly: the flush phases ran batched, so
        each interval is shared across the batch and stamped into every
        traced member's tree (batch size as an attribute). Spans are
        emitted on the tracer that OWNS the frame's root span, so mixed
        tracer setups still assemble whole traces."""
        n = len(pending)
        for p in pending:
            span = p.span
            if not span or span.ended:
                # an already-ended root means ITS owner closed the
                # trace at ingest (pipeline feed path): attaching phase
                # spans now would re-open the assembled trace as orphan
                # fragments — the flush only annotates roots it OWNS
                continue
            tracer = getattr(span, "_tracer", None)
            if tracer is not None:
                for phase, t0, t1 in marks:
                    tracer.span_at("notary." + phase, span, t0, t1, batch=n)
            # the root ends when the request is ANSWERED: on the
            # synchronous paths every future resolved inside the flush
            # body, but a distributed provider's commit_async resolves
            # on cluster consensus AFTER this finally — deferring the
            # end there keeps the consensus-commit latency inside the
            # trace (the slow-commit regression the recorder hunts)
            fut = p.future
            if getattr(fut, "done", True) or not hasattr(
                fut, "add_done_callback"
            ):
                span.end()
            else:
                fut.add_done_callback(lambda f, s=span: s.end())

    def _flush_body(self, pending, marks) -> None:
        t = time.perf_counter()
        # phase 1 — ONE SPI dispatch across all pending transactions.
        # Staging is per-tx-protected: one malformed transaction (bad
        # scheme in signature_requests) must answer ITS future with an
        # error and leave the rest of the batch alive — aborting here
        # after self._pending was swapped out would strand every
        # requester's FlowFuture forever.
        reqs: list = []
        spans: list[tuple[int, int]] = []
        live: list[_PendingNotarisation] = []
        for p in pending:
            try:
                rs = p.stx.signature_requests()
            except Exception as e:
                p.future.set_result(
                    NotaryError("invalid-transaction", str(e))
                )
                continue
            spans.append((len(reqs), len(rs)))
            reqs.extend(rs)
            live.append(p)
        pending = live
        if not pending:
            return
        t = self._mark("stage", t, marks)
        verifier = self.services.batch_verifier
        try:
            collector: Optional[threading.Thread] = None
            box: dict = {}
            handle = None
            # TraceAnnotation (when jax provides it): the dispatch span
            # becomes a named region in an XLA profiler capture, so
            # host-side traces line up with the device timeline
            with tracing.annotate("corda_tpu.notary.batch_verify_dispatch"):
                if hasattr(verifier, "verify_batch_async"):
                    handle = verifier.verify_batch_async(reqs)
                else:
                    results = verifier.verify_batch(reqs)
            # STREAMING tail (round-5): when the handle's per-chunk
            # transfers were queued at dispatch and the uniqueness
            # provider commits synchronously, chunk k's transactions
            # validate + commit while the device still runs chunk k+1 —
            # the residual link_wait the join path pays disappears into
            # downstream host work. Commit order stays exactly arrival
            # order (the chunk consumer advances a monotonic pointer),
            # so intra-batch first-wins semantics are unchanged.
            stream_ok = (
                handle is not None
                and getattr(handle, "streamed", False)
                and getattr(self.uniqueness, "batch_synchronous", False)
            )
            if handle is not None and not stream_ok:
                # collect on a worker thread: on a remote-attached
                # device the d2h result fetch is GIL-releasing link IO
                # (~100 ms), which this overlaps with the contract loop
                # below instead of serialising after it
                def _collect() -> None:
                    try:
                        box["results"] = handle.result()
                    except Exception as e:   # noqa: BLE001 - rethrown below
                        box["error"] = e

                collector = threading.Thread(target=_collect, daemon=True)
                collector.start()
            t = self._mark("dispatch", t, marks)
            # overlap: contract execution (host Python) runs while the
            # device computes the signature batch and the collector
            # thread drains the result transfer. Contracts run through
            # the SPI's BATCH entry point: one grouped-by-contract pass
            # for the in-memory service (asset contracts verify the
            # whole flush in a specialized sweep, core/batch_verify.py),
            # ONLY registered (operator-installed) contracts run
            # speculatively here — attachment-carried sandboxed code is
            # peer-supplied, so it DEFERS until the transaction's
            # signatures are known-good (phase 2 below), matching the
            # verifier worker's gate. The SPI seam is honoured only for
            # SYNCHRONOUS verifier services: an async (out-of-process)
            # pool resolves its futures via the message pump this flush
            # is running ON, so blocking on it here would deadlock —
            # the batching notary then verifies in-process instead.
            tv = self.services.transaction_verifier
            tv_sync = getattr(tv, "synchronous", False)
            # ONE batched resolve+verify pass (services.py
            # resolve_verify_batch): asset-shaped transactions take the
            # object-less fast sweep, the rest build LedgerTransactions
            # and honour the SPI seam / attachment-code deferral as
            # before. Async (out-of-process) pools resolve their
            # futures via the pump this flush runs ON, so the SPI is
            # honoured only when synchronous — the in-process grouped
            # sweep covers the rest.
            contract_errs, deferred_ltx = self.services.resolve_verify_batch(
                [p.stx for p in pending],
                spi=tv if tv_sync else None,
            )
            t = self._mark("resolve_verify", t, marks)
            if stream_ok:
                self._stream_tail(
                    pending, spans, contract_errs, deferred_ltx,
                    handle, tv, tv_sync, t, marks,
                )
                return
            if collector is not None:
                collector.join()
                if "error" in box:
                    raise box["error"]
                results = box["results"]
            t = self._mark("link_wait", t, marks)
        except Exception as e:
            # a failed dispatch (unsupported scheme in the batch, device
            # unavailable) must answer every waiting requester, not
            # strand them and crash the pump tick
            for p in pending:
                p.future.set_result(
                    NotaryError("verification-unavailable", str(e))
                )
            return
        self._batches_counter.inc()
        self._requests_counter.inc(len(pending))
        # phase 2 — per-tx validation in arrival order
        eligible: list[_PendingNotarisation] = []
        for i, (p, (off, n), cerr) in enumerate(
            zip(pending, spans, contract_errs)
        ):
            if not self._validate_one(p, results[off : off + n], cerr):
                continue
            dltx = deferred_ltx.get(i)
            if dltx is not None:
                # signatures just validated: NOW the peer-supplied
                # attachment code may run (sandboxed) — through the SPI
                # when it resolves inline, in-process otherwise (an
                # async pool cannot complete inside this pump tick)
                try:
                    if tv_sync:
                        tv.verify(dltx).result()
                    else:
                        dltx.verify()
                except Exception as e:
                    p.future.set_result(
                        NotaryError("invalid-transaction", str(e))
                    )
                    continue
            eligible.append(p)
        t = self._mark("validate", t, marks)
        if not eligible:
            return
        conflict_error = self._conflict_error
        finalize = self._finalize_sign

        # phase 3 — uniqueness commit. A synchronous provider takes the
        # WHOLE flush through one commit_many (one lock/DB transaction,
        # no future+callback per tx); a distributed provider keeps the
        # per-tx future path since each commit resolves on consensus.
        if getattr(self.uniqueness, "batch_synchronous", False):
            try:
                outcomes = self.uniqueness.commit_many(
                    [
                        (list(p.stx.wtx.inputs), p.stx.id, p.requester)
                        for p in eligible
                    ]
                )
            except Exception as e:
                # a failed batch write (db locked, disk error) must
                # answer every waiting requester, not strand them and
                # crash the pump tick — same contract as the phase-1
                # dispatch failure path above
                for p in eligible:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(e))
                    )
                return
            committed: dict[int, _PendingNotarisation] = {}
            for i, (p, err) in enumerate(zip(eligible, outcomes)):
                if err is None:
                    committed[i] = p
                elif isinstance(err, UniquenessConflict):
                    p.future.set_result(conflict_error(err))
                else:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(err))
                    )
            t = self._mark("commit", t, marks)
            finalize(committed)
            self._mark("sign_scatter", t, marks)
            return

        committed_async: dict[int, _PendingNotarisation] = {}
        remaining = [len(eligible)]

        def on_commit(f, i: int, p: _PendingNotarisation) -> None:
            try:
                f.result()
            except UniquenessConflict as e:
                p.future.set_result(conflict_error(e))
            except Exception as e:
                p.future.set_result(NotaryError("commit-unavailable", str(e)))
            else:
                committed_async[i] = p
            remaining[0] -= 1
            if remaining[0] == 0:
                finalize(committed_async)

        for i, p in enumerate(eligible):
            fut = self.uniqueness.commit_async(
                list(p.stx.wtx.inputs), p.stx.id, p.requester
            )
            fut.add_done_callback(lambda f, i=i, p=p: on_commit(f, i, p))
        self._mark("sign_scatter", t, marks)

    def _conflict_error(self, e: UniquenessConflict) -> NotaryError:
        return NotaryError(
            "conflict",
            str(e),
            conflict={str(r): h for r, h in e.conflict.items()},
        )

    def _finalize_sign(
        self, committed: dict[int, _PendingNotarisation]
    ) -> None:
        # ONE Merkle-batch notary signature over all committed ids,
        # scattered with per-tx inclusion proofs (host signing is
        # ~70 µs/signature — per-tx signing alone would cap the
        # serving rate near 14k tx/s)
        if not committed:
            return
        order = sorted(committed)
        try:
            sigs = self.services.key_management.sign_batch(
                [committed[i].stx.id for i in order],
                self.identity.owning_key,
            )
        except Exception as e:
            for i in order:
                committed[i].future.set_result(
                    NotaryError("commit-unavailable", str(e))
                )
            return
        for i, sig in zip(order, sigs):
            committed[i].future.set_result(sig)

    def _stream_tail(
        self, pending, spans, contract_errs, deferred_ltx,
        handle, tv, tv_sync, t, marks=None,
    ) -> None:
        """Streaming validate+commit (round-5): consume the SPI's
        per-chunk results as each chunk's device compute completes,
        validating and committing chunk k's transactions while the
        device still runs chunk k+1. The pointer over `pending` is
        monotonic and a transaction only passes it when EVERY one of
        its signature rows is resolved, so validation and commit
        happen in exact arrival order — intra-batch first-wins
        double-spend semantics are identical to the join path's one
        commit_many over the whole flush."""
        results = handle.skeleton()
        committed: dict[int, _PendingNotarisation] = {}
        state = {"ptr": 0}
        n_pend = len(pending)
        # counted at dispatch like the join path (line above phase 2):
        # a batch that later fails mid-stream was still dispatched
        self._batches_counter.inc()
        self._requests_counter.inc(n_pend)

        def drain() -> bool:
            """Advance over fully-resolved transactions: validate,
            then commit the ready group. False = batch write failed
            (every requester answered)."""
            ready: list[tuple[int, _PendingNotarisation]] = []
            ptr = state["ptr"]
            while ptr < n_pend:
                off, n = spans[ptr]
                row = results[off : off + n]
                if any(r is None for r in row):
                    break
                i, p = ptr, pending[ptr]
                ptr += 1
                if not self._validate_one(p, row, contract_errs[i]):
                    continue
                dltx = deferred_ltx.get(i)
                if dltx is not None:
                    # signatures just validated: NOW peer-supplied
                    # attachment code may run (sandboxed)
                    try:
                        if tv_sync:
                            tv.verify(dltx).result()
                        else:
                            dltx.verify()
                    except Exception as e:   # noqa: BLE001 - per tx
                        p.future.set_result(
                            NotaryError("invalid-transaction", str(e))
                        )
                        continue
                ready.append((i, p))
            state["ptr"] = ptr
            if not ready:
                return True
            try:
                outcomes = self.uniqueness.commit_many(
                    [
                        (list(p.stx.wtx.inputs), p.stx.id, p.requester)
                        for _, p in ready
                    ]
                )
            except Exception as e:   # noqa: BLE001 - answer all
                # failed batch write: answer every unanswered
                # requester (already-committed ones re-commit
                # idempotently on client retry)
                for p in pending:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(e))
                    )
                return False
            for (i, p), err in zip(ready, outcomes):
                if err is None:
                    committed[i] = p
                elif isinstance(err, UniquenessConflict):
                    p.future.set_result(self._conflict_error(err))
                else:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(err))
                    )
            return True

        try:
            for idxs, vals in handle.chunks():
                for j, ok in zip(idxs, vals):
                    results[j] = ok
                if not drain():
                    return
            # all-CPU batches have no device chunks: drain once more
            if state["ptr"] < n_pend and not drain():
                return
        except Exception as e:   # noqa: BLE001 - device/link failure
            # a failed chunk fetch must answer every waiting requester,
            # not strand them and crash the pump tick (set_result on an
            # already-answered future is a no-op)
            for p in pending:
                p.future.set_result(
                    NotaryError("verification-unavailable", str(e))
                )
            return
        t = self._mark("stream_commit", t, marks)
        self._finalize_sign(committed)
        self._mark("sign_scatter", t, marks)

    def _validate_one(
        self,
        p: _PendingNotarisation,
        sig_results: list[bool],
        contract_err: Optional[Exception] = None,
    ) -> bool:
        """Pre-commit checks; answers the future and returns False on
        failure, True when the tx may proceed to uniqueness commit."""
        stx = p.stx
        try:
            # signature errors take precedence over the (overlapped)
            # contract result, matching the reference's check order
            # (SignedTransaction.kt:143-149)
            stx.raise_on_invalid(sig_results)
            except_keys = self.__dict__.get("_except_keys")
            if except_keys is None:
                except_keys = frozenset((self.identity.owning_key,))
                self._except_keys = except_keys
            stx.verify_required_signatures(except_keys)
            if contract_err is not None:
                raise contract_err
        except Exception as e:
            p.future.set_result(NotaryError("invalid-transaction", str(e)))
            return False
        if not self.time_window_checker.is_valid(stx.wtx.time_window):
            p.future.set_result(
                NotaryError(
                    "time-window-invalid",
                    f"window {stx.wtx.time_window} outside notary clock "
                    "tolerance",
                )
            )
            return False
        return True


class ValidatingNotaryService(NotaryService):
    """Validating: fully resolves and verifies the transaction —
    signatures through the TPU batch SPI, then contracts — before
    committing (ValidatingNotaryFlow.kt:17-46). Backchain resolution
    happens in the service *flow* (it needs sessions); this class does
    the post-resolution work."""

    validating = True

    def process(
        self,
        stx: SignedTransaction,
        requester: Party,
        deadline: Optional[int] = None,
    ):
        del deadline   # see SimpleNotaryService.process
        if stx.wtx.notary != self.identity:
            return NotaryError(
                "wrong-notary", f"tx names notary {stx.wtx.notary}, I am "
                f"{self.identity}"
            )
        try:
            stx.verify(
                self.services,
                check_sufficient_signatures=False,   # ours is still missing
                verifier=self.services.batch_verifier,
            )
        except Exception as e:
            return NotaryError("invalid-transaction", str(e))
        return (
            yield from self.commit_and_sign(
                stx.id, list(stx.wtx.inputs), stx.wtx.time_window, requester
            )
        )
