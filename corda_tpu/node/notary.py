"""Notary services: uniqueness (double-spend prevention) + signing.

Reference: node/.../services/transactions/ (SURVEY §2.7) —
SimpleNotaryService / ValidatingNotaryService over a
PersistentUniquenessProvider (locked stateRef->consumingTx map,
PersistentUniquenessProvider.kt:20, commit :63+), TimeWindowChecker
(core/.../node/services/TimeWindowChecker.kt), and the NotaryFlow
service side (core/.../flows/NotaryFlow.kt:107-130).

TPU-first: the notary is the batch seam. `BatchingNotaryService`
accumulates concurrent notarisation requests in a queue and, on each
pump tick (or when `max_batch` fills), drains EVERY pending
transaction's signature checks through ONE BatchSignatureVerifier
dispatch — a single padded XLA program across transactions — then
commits inputs and scatters signed replies back to the waiting service
flows. This is the serving path the reference approximates with
horizontally-scaled verifier processes (SURVEY §2.5,
OutOfProcessTransactionVerifierService.kt:19-73).
"""

from __future__ import annotations

import gc
import os
import threading
from ..utils import locks
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core import serialization as ser
from ..core.contracts import StateRef, TimeWindow
from ..core.identity import Party
from ..core.transactions import (
    FilteredTransaction,
    SignedTransaction,
    TransactionVerificationError,
)
from ..crypto.hashes import SecureHash
from ..crypto.tx_signature import TransactionSignature
from ..utils import tracing
from ..utils.metrics import MetricRegistry
from .services import ServiceHub

# -- errors (wire-serializable: sent back to the requesting flow) ------------


@ser.serializable
@dataclass(frozen=True)
class NotaryError:
    """Base marker for notarisation failures (reference:
    core/.../flows/NotaryError.kt)."""

    kind: str
    message: str
    conflict: Any = None    # {state_ref: consuming_tx_id} for conflicts


class NotaryException(Exception):
    def __init__(self, error: NotaryError):
        self.error = error
        super().__init__(f"notarisation failed: {error.kind}: {error.message}")


class UniquenessConflict(Exception):
    def __init__(self, conflict: dict):
        self.conflict = conflict   # StateRef -> consuming tx id
        super().__init__(f"{len(conflict)} input(s) already consumed")


# journaled flow-future outcomes must round-trip the codec so a restored
# notary flow replays the same conflict
ser.register_custom(
    UniquenessConflict,
    "UniquenessConflict",
    lambda e: e.conflict,
    lambda v: UniquenessConflict(dict(v)),
)


class ShardUnavailableError(Exception):
    """A distributed cross-shard commit could not reach a partition
    owner (partitioned away, dead past its phase timeout). Typed so the
    serving paths answer a `shard-unavailable` NotaryError — a degraded
    answer, never a hang and never a silent double-spend window: the
    request neither reserved nor committed anything that outlives it."""

    def __init__(self, owner: str, partitions, elapsed_micros: int = 0):
        self.owner = owner
        self.partitions = tuple(partitions)
        self.elapsed_micros = elapsed_micros
        super().__init__(
            f"shard owner {owner} unreachable for partitions "
            f"{sorted(self.partitions)} after {elapsed_micros} us"
        )


# -- uniqueness providers ----------------------------------------------------


def snapshot_uniqueness_map(committed: dict) -> list:
    """Canonical (sorted, ser-encodable) dump of a stateRef->tx map.

    ONE implementation shared by the Raft snapshot and the BFT
    checkpoint paths: the encoding is consensus-critical (BFT
    checkpoint digests are computed over it), so two drifting copies
    would break cross-replica state-transfer agreement."""
    return sorted(
        [ser.encode(ref), h.bytes_] for ref, h in committed.items()
    )


def restore_uniqueness_map(state) -> dict:
    return {
        ser.decode(bytes(r)): SecureHash(bytes(h)) for r, h in state
    }


class UniquenessProvider:
    """stateRef -> consuming-tx registry; the core consensus primitive."""

    # True on providers whose commit completes inline on this host
    # (in-memory, sqlite): the batching notary then drains a whole
    # flush through ONE commit_many call instead of a future +
    # callback per transaction. Distributed providers (Raft, BFT)
    # stay False — their commits resolve on cluster consensus.
    batch_synchronous = False

    def commit(
        self, states: list[StateRef], tx_id: SecureHash, requester: Party
    ) -> None:
        raise NotImplementedError

    def commit_async(
        self,
        states: list[StateRef],
        tx_id: SecureHash,
        requester: Party,
        trace=None,
    ):
        """Future-shaped commit (what notary flows actually await):
        local providers resolve immediately; distributed ones (Raft,
        BFT) resolve when the cluster reaches consensus. `trace` is an
        optional trace context: distributed providers thread it
        through their protocol messages so every cluster member stamps
        consensus-phase spans into the requester's trace; local
        providers (commit resolves inline, nothing to attribute)
        ignore it."""
        del trace
        from ..flows.api import FlowFuture

        fut = FlowFuture()
        try:
            self.commit(states, tx_id, requester)
            fut.set_result(None)
        except Exception as e:
            fut.set_exception(e)
        return fut

    def commit_many(self, entries) -> list:
        """Batched commit: `entries` is [(states, tx_id, requester)];
        returns one outcome per entry, in order — None on success or
        the exception (UniquenessConflict etc.) that entry raised.
        Semantics are EXACTLY sequential commit in list order: an
        earlier entry's refs are committed before a later conflicting
        entry is checked, so intra-batch double spends resolve
        first-wins like they would one call at a time."""
        out = []
        for states, tx_id, requester in entries:
            try:
                self.commit(states, tx_id, requester)
                out.append(None)
            except Exception as e:   # noqa: BLE001 - per-entry outcome
                out.append(e)
        return out


class InMemoryUniquenessProvider(UniquenessProvider):
    """Single-node map (reference: PersistentUniquenessProvider
    semantics, minus the JDBC persistence — see persistence.py for the
    sqlite-backed version). Commit is all-or-nothing: on any conflict
    nothing is recorded and the full conflict set is reported."""

    batch_synchronous = True

    def __init__(self):
        self.committed: dict[StateRef, SecureHash] = {}

    def commit(self, states, tx_id, requester) -> None:
        conflict = {
            ref: self.committed[ref]
            for ref in states
            if ref in self.committed and self.committed[ref] != tx_id
        }
        if conflict:
            raise UniquenessConflict(conflict)
        for ref in states:
            self.committed[ref] = tx_id


# -- sharded uniqueness ------------------------------------------------------


def shard_of_ref(ref: StateRef, n_shards: int) -> int:
    """Deterministic state-ref -> shard routing: the first two bytes of
    the producing transaction's id, mod the shard count. A pure
    function of the ref bytes — the same ref lands on the same shard
    across restarts, processes and hosts, which is what makes the
    partitioned uniqueness namespace sound (a ref checked on the wrong
    partition would miss the committed row that conflicts it). Sibling
    outputs of one transaction share a prefix, so the common
    spend-what-one-tx-issued shape stays single-shard."""
    if n_shards <= 1:
        return 0
    return int.from_bytes(ref.txhash.bytes_[:2], "big") % n_shards


def shard_of_tx(stx, n_shards: int) -> int:
    """Home shard of one transaction: its first input's owning shard
    (input-less issues route by their own id — they touch no uniqueness
    namespace, any shard can serve them)."""
    if n_shards <= 1:
        return 0
    inputs = stx.wtx.inputs
    if inputs:
        return shard_of_ref(inputs[0], n_shards)
    return int.from_bytes(stx.id.bytes_[:2], "big") % n_shards


class _UniquenessPartition:
    """One shard's slice of the committed-state registry: the committed
    map, in-flight cross-shard reservations, and the condition that
    serialises both."""

    __slots__ = ("committed", "reserved", "cond")

    def __init__(self):
        self.committed: dict[StateRef, SecureHash] = {}
        # ref -> reserving tx id: marked by the reserve phase of a
        # cross-shard commit; holders resolve (commit or abort) within
        # one flush, so waiters never park long
        self.reserved: dict[StateRef, SecureHash] = {}
        self.cond = locks.make_condition("_UniquenessPartition.cond")


class ShardReservation:
    """A held cross-shard reservation (phase one of reserve→commit).

    Every involved partition holds `reserved[ref] = tx_id` rows for
    this transaction; `commit()` flips them to committed rows,
    `abort()` releases them — per partition atomically (under its
    condition), waking any committer parked on the reservation. A
    reservation resolves exactly once."""

    def __init__(self, provider, tx_id, requester, by_shard):
        self._provider = provider
        self._tx_id = tx_id
        self._requester = requester
        self._by_shard = by_shard      # shard id -> [StateRef], ascending
        self._resolved = False

    @property
    def shards(self) -> list[int]:
        return sorted(self._by_shard)

    def commit(self) -> None:
        self._resolve(commit=True)

    def abort(self) -> None:
        self._resolve(commit=False)

    def _resolve(self, commit: bool) -> None:
        if self._resolved:
            return
        self._resolved = True
        self._provider._resolve_reservation(
            self._by_shard, self._tx_id, self._requester, commit
        )


class ShardedUniquenessProvider(UniquenessProvider):
    """Partitioned committed-state registry: the uniqueness namespace
    split into `n_shards` slices by state-ref prefix (`shard_of_ref`),
    each with its own lock, so N shard flush pipelines commit
    concurrently instead of serialising on one map.

    Cross-shard transactions (inputs owned by more than one partition)
    take a deterministic two-phase reserve→commit: partitions are
    visited in ascending shard order (no lock-order cycles), each marks
    the refs reserved; any conflict aborts the whole reservation —
    releasing every partition's rows atomically — and reports the full
    conflict set, exactly as the single-map provider would. A committer
    that finds a ref reserved by ANOTHER transaction waits for that
    reservation to resolve (they resolve within one flush), so a
    rejected request always lost to a transaction that really
    committed — never to a reservation that later aborted. That is
    what keeps accept/reject decisions bit-exact against a serial
    single-shard replay.

    `record_decisions=True` keeps an append-only decision log
    [(tx_id, conflict-or-None)] in the exact serialisation order the
    partitions decided — the replay order the shard-correctness tests
    pin against a serial reference."""

    batch_synchronous = True

    def __init__(self, n_shards: int = 1, record_decisions: bool = False):
        self.n_shards = max(1, int(n_shards))
        self._parts = [_UniquenessPartition() for _ in range(self.n_shards)]
        self._decision_lock = locks.make_lock(
            "ShardedUniquenessProvider._decision_lock"
        )
        self.decisions: Optional[list] = [] if record_decisions else None

    # -- routing -----------------------------------------------------------

    def shard_of(self, ref: StateRef) -> int:
        return shard_of_ref(ref, self.n_shards)

    def _by_shard(self, states) -> dict[int, list[StateRef]]:
        out: dict[int, list[StateRef]] = {}
        for ref in states:
            out.setdefault(self.shard_of(ref), []).append(ref)
        return out

    # -- views -------------------------------------------------------------

    @property
    def committed(self) -> dict:
        """Merged read-only view across partitions (tests, snapshots)."""
        merged: dict[StateRef, SecureHash] = {}
        for part in self._parts:
            with part.cond:
                merged.update(part.committed)
        return merged

    def partition_depth(self, shard: int) -> int:
        part = self._parts[shard]
        with part.cond:
            return len(part.committed)

    # -- storage backend (overridden by the persistent subclass) ----------

    def _prior_consumer(self, shard: int, ref: StateRef):
        """The committed consumer of `ref` on `shard`, or None. Called
        under the partition condition."""
        return self._parts[shard].committed.get(ref)

    def _prior_consumers_many(self, shard: int, refs) -> dict:
        """Batched membership probe: {ref: committed consumer} for the
        subset of `refs` already committed on `shard` (absent = free).
        Called under the partition condition. The default is per-ref
        point probes; backends with a real batched sweep (the commit-
        log store's sorted mmap-index walk, the sqlite layer's one
        `IN (...)` query) override this — commit_many issues exactly
        ONE of these per flush run."""
        out = {}
        for ref in refs:
            prior = self._prior_consumer(shard, ref)
            if prior is not None:
                out[ref] = prior
        return out

    def _write_shard(self, shard: int, refs, tx_id, requester) -> None:
        """Durably commit `refs` -> tx_id on `shard`. Called under the
        partition condition."""
        committed = self._parts[shard].committed
        for ref in refs:
            committed[ref] = tx_id

    def _write_rows(self, shard: int, rows) -> None:
        """Durably commit a run of (ref, tx_id, requester) rows on one
        shard — commit_many's batched write. Called under the partition
        condition."""
        committed = self._parts[shard].committed
        for ref, tx_id, _requester in rows:
            committed[ref] = tx_id

    # -- partition primitives (the distributed provider's store seam) ------

    def prior_consumer(self, partition: int, ref: StateRef):
        """Committed consumer of `ref` on `partition` (None = free),
        under the partition condition — the check half of the
        distributed provider's participant role (node/
        distributed_uniqueness.py), which keeps its own reservation
        table and only needs the committed registry from here."""
        part = self._parts[partition]
        with part.cond:
            return self._prior_consumer(partition, ref)

    def write_partition(self, partition: int, refs, tx_id, requester) -> None:
        """Durably commit `refs` -> tx_id on one partition, under its
        condition — the write half of the distributed store seam.
        Idempotent (the backing writes are INSERT OR IGNORE / dict
        assignment), so a re-driven cross-member commit replays
        safely."""
        part = self._parts[partition]
        with part.cond:
            self._write_shard(partition, refs, tx_id, requester)
            part.cond.notify_all()

    # -- the two-phase core ------------------------------------------------

    def reserve(self, states, tx_id, requester) -> ShardReservation:
        """Phase one: mark every ref reserved across its owning
        partitions (ascending shard order). Raises UniquenessConflict
        with the FULL conflict set — after releasing any rows already
        reserved — when any ref is already committed to a different
        transaction. Blocks (briefly) on other transactions' in-flight
        reservations rather than failing against them: a reservation is
        not a commit until it resolves."""
        by_shard = self._by_shard(states)
        reserved: dict[int, list[StateRef]] = {}
        conflict: dict[StateRef, SecureHash] = {}
        try:
            for shard in sorted(by_shard):
                part = self._parts[shard]
                refs = by_shard[shard]
                with part.cond:
                    # wait out other transactions' reservations on our
                    # refs — but not once a conflict already doomed the
                    # request: the remaining shards are only visited to
                    # complete the conflict REPORT, and parking a dead
                    # request behind unrelated reservations would add
                    # latency exactly under contention
                    if not conflict:
                        part.cond.wait_for(
                            lambda: all(
                                part.reserved.get(r) in (None, tx_id)
                                for r in refs
                            )
                        )
                    for ref in refs:
                        prior = self._prior_consumer(shard, ref)
                        if prior is not None and prior != tx_id:
                            conflict[ref] = prior
                    if conflict:
                        # keep scanning remaining shards for the
                        # complete conflict report, but reserve nothing
                        # further
                        continue
                    for ref in refs:
                        part.reserved[ref] = tx_id
                    reserved[shard] = refs
        except BaseException:
            # a storage-backend error mid-reserve (e.g. the persistent
            # subclass's _prior_consumer hitting a locked database) must
            # not LEAK the partitions already reserved — a leaked row is
            # waited on forever by every later committer of those refs
            self._resolve_reservation(reserved, tx_id, requester, False)
            raise
        if conflict:
            self._resolve_reservation(reserved, tx_id, requester, False)
            self._record(tx_id, conflict)
            raise UniquenessConflict(conflict)
        return ShardReservation(self, tx_id, requester, reserved)

    def _resolve_reservation(self, by_shard, tx_id, requester, commit) -> None:
        if commit:
            # record the accept BEFORE any partition flips: a loser can
            # only observe (and record its conflict against) this
            # transaction after its rows became visible, so the decision
            # log stays in true serialisation order — the property the
            # serial-replay tests ride on
            self._record(tx_id, None)
        for shard in sorted(by_shard):
            part = self._parts[shard]
            refs = by_shard[shard]
            with part.cond:
                for ref in refs:
                    if part.reserved.get(ref) == tx_id:
                        del part.reserved[ref]
                if commit:
                    self._write_shard(shard, refs, tx_id, requester)
                part.cond.notify_all()

    def _record(self, tx_id, conflict) -> None:
        if self.decisions is not None:
            with self._decision_lock:
                self.decisions.append((tx_id, conflict))

    # -- UniquenessProvider SPI -------------------------------------------

    def commit_many(self, entries) -> list:
        """Batched commit with EXACTLY sequential first-wins semantics
        (the UniquenessProvider contract), tuned for the shard flush's
        shape: consecutive entries fully owned by ONE partition — the
        overwhelming majority, since the flush that calls this already
        routed by home shard — process as a run under a single
        condition hold (one acquire + one backing write per run, like
        the unsharded provider's one-lock commit_many), with a staged
        view so intra-run conflicts resolve first-wins. Cross-shard
        entries fall back to the per-entry two-phase commit in place,
        preserving order."""
        out: list = [None] * len(entries)
        n = len(entries)
        shard_of = self.shard_of
        i = 0
        while i < n:
            home = None
            for ref in entries[i][0]:
                s = shard_of(ref)
                if home is None:
                    home = s
                elif s != home:
                    home = -1
                    break
            if home == -1:
                # cross-shard: the two-phase reserve→commit, in order
                try:
                    self.commit(*entries[i])
                except Exception as e:   # noqa: BLE001 - per-entry outcome
                    out[i] = e
                i += 1
                continue
            home = home or 0
            # extend the single-shard run
            j = i + 1
            while j < n:
                states_j = entries[j][0]
                if any(shard_of(r) != home for r in states_j):
                    break
                j += 1
            part = self._parts[home]
            rows: list = []
            staged: dict = {}
            done = i
            with part.cond:
                # the condition is held for the WHOLE run — never
                # released mid-run, or the staged-but-unwritten rows
                # would be invisible to a concurrent cross-shard
                # reserve on this partition, which could then accept a
                # second consumer for a staged ref. An entry whose refs
                # carry someone ELSE's in-flight reservation therefore
                # TRUNCATES the run (we must not wait while holding
                # staged state); it re-enters below via the per-entry
                # two-phase path, which parks on the reservation
                # correctly.
                # ONE batched membership probe for the whole run: the
                # backing store never changes under the held condition
                # (the run's own rows write at the end), so the
                # persisted view is fixed — only the staged view
                # evolves entry to entry
                run_refs: list = []
                seen: set = set()
                for k in range(i, j):
                    for ref in entries[k][0]:
                        if ref not in seen:
                            seen.add(ref)
                            run_refs.append(ref)
                persisted = self._prior_consumers_many(home, run_refs)
                for k in range(i, j):
                    states_k, tx_k, req_k = entries[k]
                    if any(
                        part.reserved.get(r) not in (None, tx_k)
                        for r in states_k
                    ):
                        break
                    conflict = {}
                    for ref in states_k:
                        prior = staged.get(ref)
                        if prior is None:
                            prior = persisted.get(ref)
                        if prior is not None and prior != tx_k:
                            conflict[ref] = prior
                    if conflict:
                        out[k] = UniquenessConflict(conflict)
                        self._record(tx_k, conflict)
                    else:
                        for ref in states_k:
                            staged[ref] = tx_k
                            rows.append((ref, tx_k, req_k))
                        self._record(tx_k, None)
                    done = k + 1
                if rows:
                    self._write_rows(home, rows)
            if done == i:
                # first entry of the run is blocked on a foreign
                # reservation: the per-entry commit path waits it out
                try:
                    self.commit(*entries[i])
                except Exception as e:   # noqa: BLE001 - per-entry outcome
                    out[i] = e
                done = i + 1
            i = done
        return out

    def commit(self, states, tx_id, requester) -> None:
        by_shard = self._by_shard(states)
        if len(by_shard) <= 1:
            # single-partition fast path: check + write under ONE
            # condition hold — no reservation round trip
            shard = next(iter(by_shard), 0)
            part = self._parts[shard]
            refs = by_shard.get(shard, [])
            with part.cond:
                part.cond.wait_for(
                    lambda: all(
                        part.reserved.get(r) in (None, tx_id) for r in refs
                    )
                )
                conflict = {}
                for ref in refs:
                    prior = self._prior_consumer(shard, ref)
                    if prior is not None and prior != tx_id:
                        conflict[ref] = prior
                if conflict:
                    self._record(tx_id, conflict)
                    raise UniquenessConflict(conflict)
                # record inside the hold: the accept must serialise
                # into the decision log before any later conflict
                # against these rows can be recorded
                self._record(tx_id, None)
                self._write_shard(shard, refs, tx_id, requester)
            return
        self.reserve(states, tx_id, requester).commit()


# -- time window -------------------------------------------------------------


class TimeWindowChecker:
    """Clock-tolerance validation (TimeWindowChecker.kt): the notary
    accepts a window iff `now` (± tolerance) intersects it."""

    def __init__(self, clock, tolerance_micros: int = 30_000_000):
        self.clock = clock
        self.tolerance = tolerance_micros

    def is_valid(self, tw: Optional[TimeWindow], now: Optional[int] = None) -> bool:
        """`now` override: distributed notaries validate against the
        consensus-ordered timestamp so every replica gets one answer."""
        if tw is None:
            return True
        if now is None:
            now = self.clock.now_micros()
        if tw.until_time is not None and now - self.tolerance >= tw.until_time:
            return False
        if tw.from_time is not None and now + self.tolerance < tw.from_time:
            return False
        return True


# -- the services ------------------------------------------------------------


class NotaryService:
    """Common commit-and-sign core shared by every notary flavour."""

    validating = False

    def __init__(
        self,
        services: ServiceHub,
        uniqueness: Optional[UniquenessProvider] = None,
        tolerance_micros: int = 30_000_000,
        service_identity: Optional[Party] = None,
    ):
        """`service_identity`: the cluster-shared notary Party for
        distributed notaries (each member holds the shared key and
        answers for it); None = this node's own identity."""
        self.services = services
        self.uniqueness = uniqueness or InMemoryUniquenessProvider()
        self.time_window_checker = TimeWindowChecker(
            services.clock, tolerance_micros
        )
        self.service_identity = service_identity

    @property
    def identity(self) -> Party:
        if self.service_identity is not None:
            return self.service_identity
        return self.services.my_info.notary_identity

    def commit_and_sign(
        self,
        tx_id: SecureHash,
        inputs: list[StateRef],
        time_window: Optional[TimeWindow],
        requester: Party,
        trace=None,
    ):
        """validate time window -> commit inputs -> sign tx id
        (NotaryFlow.Service.call, NotaryFlow.kt:110-130). A generator
        (`yield from` it inside a flow): the commit awaits the
        uniqueness provider's future, which suspends the service flow
        while a distributed provider reaches consensus. Returns a
        TransactionSignature or a NotaryError. `trace`: optional trace
        context handed to the provider so a distributed commit's
        consensus-phase spans join the requester's trace."""
        from ..flows.api import wait_future

        # lifecycle ledger (utils/txstory.py): the non-batching
        # flavours (simple/validating, raft-backed included) admit and
        # terminal here — commit_and_sign IS their serving path. The
        # batching notary never reaches this method (enqueue_pending
        # owns its intake), so no double-admit.
        story = getattr(self.services, "txstory", None)
        if story is not None:
            story.admit(
                str(tx_id),
                requester=getattr(requester, "name", None),
            )
        if not self.time_window_checker.is_valid(time_window):
            err = NotaryError(
                "time-window-invalid",
                f"window {time_window} outside notary clock tolerance",
            )
            if story is not None:
                story.terminal_from(str(tx_id), err)
            return err
        try:
            yield from wait_future(
                self.uniqueness.commit_async(
                    inputs, tx_id, requester, trace=trace
                )
            )
        except UniquenessConflict as e:
            err = NotaryError(
                "conflict",
                str(e),
                conflict={str(r): h for r, h in e.conflict.items()},
            )
            if story is not None:
                story.terminal_from(str(tx_id), err)
            return err
        except ShardUnavailableError as e:
            # a partition owner is unreachable: a typed degraded answer
            # the client can retry against a healed cluster — distinct
            # from commit-unavailable so operators (and the fleet
            # checker) can tell a partitioned shard from a broken store
            err = NotaryError("shard-unavailable", str(e))
            if story is not None:
                story.terminal_from(str(tx_id), err)
            return err
        except Exception as e:
            err = NotaryError("commit-unavailable", str(e))
            if story is not None:
                story.terminal_from(str(tx_id), err)
            return err
        sig = self.services.key_management.sign(
            tx_id, self.identity.owning_key
        )
        if story is not None:
            story.close(str(tx_id), "committed")
        return sig


class SimpleNotaryService(NotaryService):
    """Non-validating: sees only a Merkle tear-off of (inputs, notary,
    time window) — privacy-preserving, trusts the requester for contract
    validity (SimpleNotaryService.kt)."""

    def process(
        self,
        ftx: FilteredTransaction,
        requester: Party,
        deadline: Optional[int] = None,
        trace=None,
    ):
        # `deadline` (node/qos.py) is accepted on every notary flavour
        # so the service flow passes it uniformly; only the batching
        # notary currently sheds on it (this flavour serves per-request
        # — by the time it runs, answering costs less than shedding).
        # `trace` likewise: an optional trace context threaded to the
        # uniqueness provider, where a distributed (Raft) commit stamps
        # per-member consensus-phase spans into it.
        del deadline
        try:
            ftx.verify()
        except TransactionVerificationError as e:
            return NotaryError("invalid-proof", str(e))
        # completeness: a tear-off hiding an input (or the time window /
        # notary) would let the requester double-spend the hidden state
        from ..core.transactions import G_INPUTS, G_NOTARY, G_TIMEWINDOW

        for g, what in (
            (G_INPUTS, "inputs"),
            (G_NOTARY, "notary"),
            (G_TIMEWINDOW, "time window"),
        ):
            if not ftx.all_revealed(g):
                return NotaryError(
                    "incomplete-tearoff",
                    f"tear-off hides {what} components",
                )
        if ftx.notary != self.identity:
            return NotaryError(
                "wrong-notary", f"tx names notary {ftx.notary}, I am "
                f"{self.identity}"
            )
        return (
            yield from self.commit_and_sign(
                ftx.id, list(ftx.inputs), ftx.time_window, requester,
                trace=trace,
            )
        )


@dataclass
class _PendingNotarisation:
    stx: SignedTransaction
    requester: Party
    future: Any   # FlowFuture resolved with TransactionSignature | NotaryError
    # tracing: the frame's live root span (utils/tracing.py), opened at
    # wire-frame ingest. The flush attributes its phase intervals to it
    # and ENDS it when this request is answered. None when tracing is
    # off — the disabled path costs one falsy check per request.
    span: Any = None
    # QoS (node/qos.py): the request's propagated absolute-microsecond
    # deadline and its arrival time on the node clock. A request whose
    # deadline passed while it queued is shed pre-stage (the flush
    # answers a typed `shed` NotaryError without spending verify work);
    # arrival feeds the admitted-latency histogram the adaptive
    # batching controller steers by. Both None when QoS is off.
    deadline: Optional[int] = None
    arrival_micros: Optional[int] = None
    # durable intake (round 9): this request's row id in the intent
    # WAL. Set by enqueue_pending when a journal is attached (or by
    # replay_intents re-enqueueing an unresolved intent — which must
    # NOT append a second row); the resolution callback deletes the
    # row when the future answers. None when the WAL is off. The
    # sentinel -1 means "synthetic, never journal" (the health canary).
    intent_seq: Optional[int] = None


class _ShardAnswer:
    """Future proxy used by threaded shard workers: `set_result` lands
    the outcome on the notary's completion queue instead of resolving
    the real FlowFuture from a worker thread — the pump thread drains
    the queue and resolves, so flow resumption stays single-threaded
    (FlowFuture's contract). Duck-types the subset of the future
    surface the flush paths touch."""

    __slots__ = ("future", "_queue", "done")

    def __init__(self, future, queue):
        self.future = future
        self._queue = queue
        self.done = False

    def set_result(self, value) -> None:
        if self.done:
            return
        self.done = True
        self._queue.append((self.future, value))

    def add_done_callback(self, cb) -> None:
        # callbacks belong on the REAL future: they fire on the pump
        # thread when the completion drains, which is where qos/trace
        # observers expect to run
        self.future.add_done_callback(cb)


class _NotaryShard:
    """One slice of the sharded commit plane: a bounded pending queue,
    its own flush state, a (possibly device-pinned) verifier handle and
    per-shard liveness/metric hooks. The BatchingNotaryService routes
    requests here by state-ref prefix (shard_of_tx) and either flushes
    shards inline from the pump tick or hands each one to a dedicated
    worker thread."""

    __slots__ = (
        "id", "pending", "oldest_arrival", "cond", "verifier",
        "heartbeat", "queue_bound", "flushes", "requests", "answered",
        "wake", "busy",
    )

    def __init__(self, sid: int, verifier, queue_bound: int, metrics):
        self.id = sid
        self.pending: list[_PendingNotarisation] = []
        self.oldest_arrival: Optional[int] = None
        self.cond = locks.make_condition("_NotaryShard.cond")
        self.verifier = verifier       # None = the hub's shared verifier
        self.heartbeat = None          # attach_health wires one per shard
        self.queue_bound = queue_bound
        self.flushes = metrics.counter(f"Notary.Shard{sid}.Flushes")
        self.requests = metrics.counter(f"Notary.Shard{sid}.Requests")
        self.answered = metrics.counter(f"Notary.Shard{sid}.Answered")
        metrics.gauge(f"Notary.Shard{sid}.Depth", lambda: len(self.pending))
        self.wake = False              # worker flush requested
        self.busy = False              # a flush of this shard is running

    def depth(self) -> int:
        return len(self.pending)


class BatchingNotaryService(NotaryService):
    """Batch-committing validating notary — the north-star serving path
    (SURVEY §7 Phase 4).

    `process` enqueues the request and suspends the service flow on a
    future; `flush` (driven by the node pump tick, or immediately when
    `max_batch` requests are queued) drains the queue:

      queue -> ONE BatchSignatureVerifier dispatch over every pending
      transaction's signatures (the SPI pads/buckets into fixed XLA
      shapes) -> per-tx required-signer/contract/time-window checks ->
      uniqueness commit in arrival order -> scatter signed replies.

    Under the pump model the batch window is one delivery round: every
    request that arrived since the last quiescent point shares a single
    TPU dispatch, which is exactly the queue->pad/bucket->dispatch->
    scatter loop the reference approximates with horizontally-scaled
    verifier processes (NotaryFlow.kt:107-130 per-request service,
    OutOfProcessTransactionVerifierService.kt:19-73 offload seam).
    """

    validating = True

    def __init__(
        self,
        services: ServiceHub,
        uniqueness: Optional[UniquenessProvider] = None,
        tolerance_micros: int = 30_000_000,
        service_identity: Optional[Party] = None,
        max_batch: int = 512,
        max_wait_micros: int = 0,
        metrics: Optional[MetricRegistry] = None,
        qos=None,
        shards: int = 1,
        shard_workers: bool = False,
        shard_verifiers: Optional[list] = None,
        shard_queue_depth: int = 0,
        degraded_fallback: bool = True,
        intent_journal=None,
    ):
        """`max_wait_micros` is the batching DEADLINE (SURVEY §7 hard
        part 4 — latency vs throughput): 0 (default) flushes every pump
        tick; positive, the tick HOLDS arrivals until the oldest one
        has waited that long (or `max_batch` fills), so a lightly
        loaded notary still forms deep batches — throughput rides the
        flush depth (BASELINE.md round-3 sweep), at a bounded latency
        cost the operator chooses.

        `metrics`: the node's MetricRegistry — pass it and the batching
        counters, ratio gauge, flush-phase timers and ingest-ring
        gauges all land on the node's /metrics surface; None keeps a
        private registry (embedded/test rigs).

        `qos`: an optional node/qos.NotaryQos. With one attached,
        max_batch/max_wait_micros become the STARTING point of its
        adaptive batching controller (which retunes both each flush to
        hold the configured p99 target), expired requests are shed
        pre-stage into typed `shed` errors, and every answered request
        feeds the admitted-latency histogram the controller steers by.
        None keeps the static knobs and a zero-cost hot path.

        `shards` > 1 partitions the COMMIT PLANE (round-6 tentpole):
        requests route by state-ref prefix (shard_of_tx) onto N
        independent shards, each with its own bounded pending queue,
        flush pipeline, uniqueness partition (pass a
        ShardedUniquenessProvider — any provider works, but only a
        partitioned one commits concurrently) and, when
        `shard_verifiers` is given (crypto/batch_verifier.py
        per_shard_verifiers: one device-pinned TpuBatchVerifier per
        mesh device, cycled over the shards), its own per-device
        verify dispatch so each shard's batch lands on its own chip.
        Cross-shard
        transactions take the provider's two-phase reserve→commit.
        `shard_workers=True` additionally gives every shard a dedicated
        flush thread (the pump tick then only routes + drains answers);
        False flushes shards from the tick in a dispatch-all-then-
        consume wave, which still overlaps device compute across
        shards. `shard_queue_depth` bounds each shard's pending queue
        (0 = 4x max_batch); a full queue triggers that shard's flush.
        shards == 1 keeps the original single-queue hot path
        bit-for-bit.

        `degraded_fallback` (round-9 fault plane): a device/kernel
        exception at the verify dispatch seam retries once on the
        device, then serves THAT flush through the CPU reference
        verifier (bit-exact semantics — CpuBatchVerifier is the
        correctness anchor the kernels are pinned against), counting
        Notary.DegradedFlushes and firing the `notary.degraded_mode`
        alert; every later flush's device attempt doubles as the
        recovery probe that re-arms the device path and auto-resolves
        the alert. A batch that fails DETERMINISTICALLY (CPU fallback
        raises too) is bisected to isolate the poison transaction(s),
        which are quarantined with a typed answer while the rest of
        the batch commits normally. False restores the old behaviour
        (one dispatch failure fails the whole flush).

        `intent_journal` (round-9 durable intake): a
        persistence.NotaryIntentJournal — every admitted request is
        appended BEFORE it enters the pending queue and deleted when
        its future resolves; `replay_intents()` re-enqueues unresolved
        intents on boot through the normal flush path (uniqueness
        dedupe absorbs already-committed replays), taking
        in-flight-at-kill loss to zero."""
        super().__init__(
            services, uniqueness, tolerance_micros, service_identity
        )
        self.max_batch = max_batch
        self.max_wait_micros = max_wait_micros
        self.qos = qos
        self._pending: list[_PendingNotarisation] = []
        self._ingest_ring = None   # attach_ingest: pre-decoded arrivals
        self._oldest_arrival: Optional[int] = None
        self._health_heartbeat = None   # attach_health: flush-loop liveness
        self._perf = None               # attach_perf: attribution plane
        self.txstory = None             # attach_txstory: lifecycle ledger
        # registry-backed metrics (scrapeable at /metrics, unlike the
        # bare ints they replace): dispatches vs requests IS the
        # batching ratio, exported as its own gauge
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self._batches_counter = self.metrics.counter(
            "Notary.BatchesDispatched"
        )
        self._requests_counter = self.metrics.counter(
            "Notary.RequestsBatched"
        )
        self.metrics.gauge(
            "Notary.BatchingRatio",
            lambda: (
                self._requests_counter.count / self._batches_counter.count
                if self._batches_counter.count
                else 0.0
            ),
        )
        # per-phase flush timers: always on (a handful of updates per
        # FLUSH, not per tx), so /metrics carries the stage breakdown
        # continuously — the registry-backed replacement for the old
        # env-gated phase_seconds dict
        self._phase_timers: dict[str, Any] = {}
        # CORDA_TPU_NOTARY_PROFILE=1: additionally accumulate per-phase
        # wall seconds across flushes into a plain dict (BASELINE.md
        # serving-profile methodology; bench.py prints it). The
        # phase_seconds property is the back-compat view.
        self._phase_profile: Optional[dict] = (
            {} if os.environ.get("CORDA_TPU_NOTARY_PROFILE") else None
        )
        # -- fault-tolerance plane (round 9) ----------------------------
        self.degraded_fallback = degraded_fallback
        self.intent_journal = intent_journal
        self._degraded = False         # device path currently distrusted
        self._degraded_last: dict = {}     # evidence: error, at_micros
        self._cpu_reference = None         # lazy CpuBatchVerifier
        self._degraded_counter = self.metrics.counter(
            "Notary.DegradedFlushes"
        )
        self._quarantined_counter = self.metrics.counter(
            "Notary.Quarantined"
        )
        self.quarantined: list = []        # poison tx ids, boot-scoped
        self.metrics.gauge(
            "Notary.DegradedMode", lambda: 1 if self._degraded else 0
        )
        if intent_journal is not None:
            self.metrics.gauge(
                "Notary.IntentUnresolved",
                lambda: intent_journal.unresolved_count,
            )
        # -- sharded commit plane (round 6) ----------------------------
        self.n_shards = max(1, int(shards))
        self._shards: Optional[list[_NotaryShard]] = None
        self._completions = None       # worker mode: (future, outcome)
        self._workers: list[threading.Thread] = []
        self._stop_workers = False
        self._gc_lock = locks.make_lock("BatchingNotaryService._gc_lock")
        self._gc_depth = 0
        self._gc_reenable = False
        if self.n_shards > 1:
            if not getattr(self.uniqueness, "batch_synchronous", False):
                raise ValueError(
                    "sharded commit plane requires a batch_synchronous "
                    "uniqueness provider (distributed providers resolve "
                    "on consensus, not on the shard flush)"
                )
            bound = shard_queue_depth or 4 * max_batch
            self._shards = [
                _NotaryShard(
                    k,
                    (
                        shard_verifiers[k % len(shard_verifiers)]
                        if shard_verifiers else None
                    ),
                    bound,
                    self.metrics,
                )
                for k in range(self.n_shards)
            ]
            self.metrics.gauge("Notary.Shards", lambda: self.n_shards)
            if qos is not None and hasattr(qos, "ensure_shards"):
                qos.ensure_shards(self.n_shards)
            if shard_workers:
                from collections import deque

                self._completions = deque()
                for shard in self._shards:
                    t = threading.Thread(
                        target=self._shard_worker,
                        args=(shard,),
                        name=f"notary-shard-{shard.id}",
                        daemon=True,
                    )
                    self._workers.append(t)
                    t.start()

    # -- back-compat views over the registry-backed metrics ----------------

    @property
    def batches_dispatched(self) -> int:
        return self._batches_counter.count

    @property
    def requests_batched(self) -> int:
        return self._requests_counter.count

    @property
    def phase_seconds(self) -> Optional[dict]:
        """The CORDA_TPU_NOTARY_PROFILE accumulation dict (None when
        profiling is off) — the live object, so callers may clear() it
        between warm-up and timed reps as before."""
        return self._phase_profile

    @property
    def effective_max_batch(self) -> int:
        """The live flush-depth knob: the adaptive controller's when
        QoS is attached, the static config otherwise."""
        qos = self.qos
        return qos.controller.batch if qos is not None else self.max_batch

    @property
    def effective_wait_micros(self) -> int:
        """The live batching-window knob (see effective_max_batch)."""
        qos = self.qos
        return (
            qos.controller.wait_micros if qos is not None
            else self.max_wait_micros
        )

    def process(
        self,
        stx: SignedTransaction,
        requester: Party,
        deadline: Optional[int] = None,
        trace=None,
    ):
        from ..flows.api import FlowFuture, wait_future

        if stx.wtx.notary != self.identity:
            return NotaryError(
                "wrong-notary",
                f"tx names notary {stx.wtx.notary}, I am {self.identity}",
            )
        qos = self.qos
        arrival = None
        if qos is not None:
            from . import qos as qoslib

            arrival = self.services.clock.now_micros()
            if qoslib.expired(deadline, arrival):
                # dead on arrival: answer without queuing — the flow
                # entry's pre-decode-equivalent cheapest point. These
                # pre-queue sheds have no answer future, so shed_tx
                # closes the lifecycle story directly (terminal=True).
                qos.shed_tx(
                    qoslib.SHED_EXPIRED_INGRESS, stx.id,
                    terminal=True,
                )
                return NotaryError(
                    qoslib.SHED_KIND,
                    f"deadline {deadline} already expired at arrival",
                )
            # per-client admission gate on the REQUEST path (the same
            # token bucket the lane router applies at ring-seam
            # fabrics): one flooding requester is rate-shaped here,
            # before any queue slot or verify work is spent on it
            if not qos.admission.admit(requester.name, arrival):
                qos.shed_tx(
                    qoslib.SHED_ADMISSION, stx.id, terminal=True
                )
                return NotaryError(
                    qoslib.SHED_KIND,
                    f"admission rate exceeded for {requester.name}",
                )
            # brownout on the request path: at level 2 deadline-less
            # traffic sheds here too — with no SLO to serve it by, it
            # is the first load the degraded notary stops carrying
            if qos.brownout_level >= 2 and deadline is None:
                qos.shed_tx(
                    qoslib.SHED_BROWNOUT_NO_DEADLINE, stx.id,
                    terminal=True,
                )
                return NotaryError(
                    qoslib.SHED_KIND,
                    "brownout: deadline-less requests are being shed",
                )
            qos.admit_tx(stx.id)
        fut = FlowFuture()
        # flow-driven requests trace too: a root span per notarisation
        # (the wire-ingest path arrives with its span already attached
        # via attach_ingest; this is the fabric-less service entry).
        # With a propagated `trace` context the span JOINS the
        # requester's trace instead of opening a fresh id, so a
        # cross-node pull assembles the client and notary halves.
        tracer = tracing.get_tracer()
        span = None
        if tracer.enabled:
            span = tracer.start_trace(
                "notarise.request", parent=trace,
                tx_id=str(stx.id), requester=requester.name,
            )
        p = _PendingNotarisation(
            stx, requester, fut, span=span,
            deadline=deadline, arrival_micros=arrival,
        )
        self.enqueue_pending(p)
        if (
            self._shards is None
            and len(self._pending) >= self.effective_max_batch
        ):
            self.flush()
        result = yield from wait_future(fut)
        return result

    def submit(
        self,
        stx: SignedTransaction,
        requester: Party,
        deadline: Optional[int] = None,
        arrival_micros: Optional[int] = None,
    ):
        """Queue one notarisation WITHOUT the flow machinery and return
        its FlowFuture (bench rigs, tests, embedded drivers). Routes to
        the owning shard on the sharded plane; on the classic plane it
        appends to the single pending queue. The future resolves on
        flush (worker-mode callers drive tick()/flush() to drain
        completions)."""
        from ..flows.api import FlowFuture

        fut = FlowFuture()
        p = _PendingNotarisation(
            stx, requester, fut,
            deadline=deadline, arrival_micros=arrival_micros,
        )
        self.enqueue_pending(p)
        return fut

    def enqueue_pending(self, p: _PendingNotarisation) -> None:
        """THE queue-routing step every intake path shares (process,
        submit, the canary probe): the owning shard on the sharded
        plane, the single pending queue — with its oldest-arrival
        stamp — otherwise. The canary (utils/health.notary_canary_fn)
        MUST come through here: a bare `_pending.append` starves
        forever on a sharded notary, whose tick only drains the shard
        queues (the deadman would fire on a perfectly healthy node).
        Full-batch flush triggers stay with the callers: process()
        flushes the unsharded queue at effective_max_batch, the shard
        router flushes a full shard itself, submit() never flushes
        (bench rigs fill the whole plane first)."""
        journal = self.intent_journal
        fresh = p.intent_seq is None
        if journal is not None and fresh:
            # durable intake: the intent row lands BEFORE the request
            # can enter any queue — from here on a crash replays it
            # instead of losing it. Resolution (any answer: signature,
            # conflict, shed, unavailable) deletes the row; the delete
            # itself is group-committed per flush tick.
            p.intent_seq = journal.append(p.stx, p.requester, p.deadline)
            p.future.add_done_callback(
                lambda f, j=journal, s=p.intent_seq: j.mark_resolved(s)
            )
        # lifecycle ledger: admit (+ journal) events for a fresh
        # arrival, `wal.replay` was already stamped by replay_intents
        # for a re-enqueued intent — either way the future's answer
        # records this transaction's one terminal event
        self._story_intake(p, fresh)
        if self._shards is not None:
            self._enqueue_sharded(p)
            return
        if not self._pending:
            self._oldest_arrival = self.services.clock.now_micros()
        self._pending.append(p)

    def attach_intent_journal(self, journal) -> None:
        """Wire (or detach, with None) the durable intake WAL after
        construction — the embedded/sim seam (node.py passes it at
        build time)."""
        self.intent_journal = journal

    def replay_intents(self) -> list:
        """Boot-time recovery: re-enqueue every unresolved intent from
        the WAL through the NORMAL intake path with a fresh future.
        Already-committed replays (the answer raced the crash) are
        absorbed by the uniqueness provider's same-tx idempotent
        re-commit; genuinely lost requests flush as if they had just
        arrived. Returns [(seq, tx_id, future)] so an embedding driver
        can re-attach waiters it still holds for those transactions."""
        journal = self.intent_journal
        if journal is None:
            return []
        from ..flows.api import FlowFuture

        out = []
        now = self.services.clock.now_micros()
        for seq, stx, requester, deadline in journal.unresolved():
            fut = FlowFuture()
            fut.add_done_callback(
                lambda f, j=journal, s=seq: j.mark_resolved(s)
            )
            if self.txstory is not None:
                # the replay marker doubles as the story's (re-)admit
                # milestone — a tx whose pre-crash story died with the
                # process still reconciles: replay -> one terminal
                self.txstory.replay(str(stx.id), seq)
            p = _PendingNotarisation(
                stx, requester, fut,
                deadline=deadline, arrival_micros=now, intent_seq=seq,
            )
            self.enqueue_pending(p)
            journal.replayed += 1
            out.append((seq, stx.id, fut))
        return out

    # -- shard routing (round 6) --------------------------------------------

    def shard_of(self, stx) -> int:
        """The shard a transaction routes to (state-ref-prefix of its
        first input; pure and restart-stable — see shard_of_tx)."""
        return shard_of_tx(stx, self.n_shards)

    def _shard_cap(self, shard) -> int:
        qos = self.qos
        if qos is None:
            return self.max_batch
        if hasattr(qos, "controller_for"):
            return qos.controller_for(shard.id).batch
        return qos.controller.batch

    def _shard_wait(self, shard) -> int:
        qos = self.qos
        if qos is None:
            return self.max_wait_micros
        if hasattr(qos, "controller_for"):
            return qos.controller_for(shard.id).wait_micros
        return qos.controller.wait_micros

    def _enqueue_sharded(self, p: _PendingNotarisation):
        shard = self._shards[shard_of_tx(p.stx, self.n_shards)]
        if self._completions is not None:
            # worker mode: the flush runs on the shard's thread, but
            # FlowFutures must resolve on the pump thread — proxy the
            # outcome through the completion queue
            p.future = _ShardAnswer(p.future, self._completions)
        flush_now = False
        with shard.cond:
            if not shard.pending:
                shard.oldest_arrival = self.services.clock.now_micros()
            shard.pending.append(p)
            depth = len(shard.pending)
            if depth >= self._shard_cap(shard) or depth >= shard.queue_bound:
                # full batch (or full bounded queue): flush THIS shard —
                # the others keep accumulating their own batches
                if self._workers:
                    shard.wake = True
                    shard.cond.notify_all()
                else:
                    flush_now = True
        if flush_now:
            self._flush_one_shard(shard)
        return shard

    def attach_ingest(self, ring) -> None:
        """Wire the pipelined wire-ingest seam (node/ingest.py): the
        ring carries batches of _PendingNotarisation whose stx was
        decoded, Merkle-id'd and signature-staged by the ingest
        pipeline — the flush drains them directly, and its stage phase
        reuses the memoised staging instead of re-staging. The ring is
        BOUNDED: when this notary falls behind, the producer's `put`
        blocks, which is the backpressure that keeps the decode pool
        from running unboundedly ahead of the TPU dispatch."""
        self._ingest_ring = ring
        # backpressure visibility: depth + high-water gauges on this
        # notary's registry, so the ring filling up shows on /metrics
        # BEFORE it stalls the producer
        from .messaging import register_ring_gauges

        register_ring_gauges(self.metrics, "notary", ring)

    def attach_health(self, monitor) -> None:
        """Register this notary's flush loop on the health plane
        (utils/health.py): a `notary.flush` heartbeat beaten every
        tick, carrying requests answered as progress and the live
        queue depth (pending + ingest ring) for livelock detection —
        a flush loop that ticks forever while its queue sits full and
        nothing resolves is wedged in a way the stall detector can't
        see. On the sharded plane EVERY shard additionally registers
        its own `notary.shard<k>.flush` heartbeat (beaten by its flush
        — worker thread or inline wave — with its own queue depth), so
        one wedged shard flips /healthz even while its siblings keep
        serving. Pass None to detach (bench A/B rigs)."""
        if monitor is None:
            self._health_heartbeat = None
            if self._shards is not None:
                for shard in self._shards:
                    shard.heartbeat = None
            return
        self._health_heartbeat = monitor.heartbeat(
            "notary.flush",
            queue_depth=lambda: sum(self.shard_depths())
            + (
                len(self._ingest_ring)
                if self._ingest_ring is not None
                else 0
            ),
        )
        if self._shards is not None:
            for shard in self._shards:
                shard.heartbeat = monitor.heartbeat(
                    f"notary.shard{shard.id}.flush",
                    queue_depth=(lambda s=shard: s.depth()),
                )
        # degraded-mode alert (round 9): fires while the device verify
        # path is distrusted (a flush fell back to the CPU reference),
        # carrying the triggering error + slowest matching traces as
        # evidence; auto-resolves when a later flush's device probe
        # succeeds. for/clear 0: entering and leaving degraded mode
        # already encode their own duration (one whole flush each way).
        from ..utils.health import AlertRule

        monitor.add_rule(
            AlertRule(
                "notary.degraded_mode",
                lambda now: (self._degraded, self.degraded_evidence),
                severity="critical",
                for_micros=0,
                clear_for_micros=0,
                trace_filter="notar",
            )
        )

    def attach_txstory(self, story) -> None:
        """Wire the transaction lifecycle ledger (utils/txstory.py):
        every intake path emits `notary.admit` (+ `wal.journal` /
        `wal.replay` under the intent WAL), every flush stamps
        `notary.flush` membership with its batch id (+ shard), the
        validate pass stamps `notary.verified`, degraded flushes and
        quarantines carry their outcomes, and the answer future's
        resolution records EXACTLY ONE terminal event per admitted
        transaction. Pass None to detach (bench A/B rigs)."""
        self.txstory = story

    def _story_intake(self, p: _PendingNotarisation, fresh: bool) -> None:
        """The shared lifecycle-intake hook (enqueue_pending AND the
        ingest-ring drain): admit + journal events for fresh arrivals,
        terminal hook on the answer future either way. The canary
        (intent_seq == -1 sentinel) stays invisible — a synthetic
        probe per tick would churn one story with endless re-answers."""
        story = self.txstory
        if story is None or p.intent_seq == -1:
            return
        tid = str(p.stx.id)
        if fresh:
            span = p.span
            story.admit(
                tid,
                trace_id=(
                    f"{span.trace_id:#x}"
                    if span and not span.ended else None
                ),
                deadline=p.deadline,
                requester=(
                    p.requester.name
                    if getattr(p.requester, "name", None) else None
                ),
            )
            if p.intent_seq is not None:
                story.journal(tid, p.intent_seq)
        story.watch_future(tid, p.future)

    def attach_perf(self, plane) -> None:
        """Wire the performance-attribution plane (utils/perf.py):
        every flush feeds its phase marks in — per-shard flush wall +
        request counts for the skew window, link-blocked time for the
        wave overlap-efficiency gauge — and the notary's served-request
        counter becomes the plane's in-process
        `batching_notary_notarisations_per_sec` history key (the same
        key bench.py records, so the node can diff itself against the
        committed BENCH baseline between offline rounds). Pass None to
        detach (bench A/B rigs)."""
        self._perf = plane
        if plane is None:
            return
        if self._shards is not None:
            plane.attach_shards(
                self.n_shards,
                [(lambda s=shard: s.depth()) for shard in self._shards],
            )
        else:
            plane.attach_shards(1, [lambda: len(self._pending)])
        plane.watch_rate(
            "batching_notary_notarisations_per_sec",
            lambda: self._requests_counter.count,
        )

    def backlog(self) -> int:
        """Live pending depth across the commit plane (all shards, or
        the single queue) — the device plane's starvation signal and
        the fleet rigs' public depth read."""
        if self._shards is not None:
            return sum(shard.depth() for shard in self._shards)
        return len(self._pending)

    def attach_device(self, plane) -> None:
        """Wire the device-telemetry plane (utils/device_telemetry):
        per-shard pending-queue depths mapped onto the devices their
        verifiers pin to (the per-device dispatch-queue feed), and the
        round-9 degraded-mode flag bridged as `device.fallback_active`
        evidence. The notary holds no reference back — the plane reads
        THROUGH the registered lambdas — so None is simply a no-op
        (re-attach a different notary to repoint a plane)."""
        if plane is None:
            return
        if self._shards is not None:
            plane.attach_queues(
                [(lambda s=shard: s.depth()) for shard in self._shards],
                [
                    getattr(
                        getattr(shard.verifier, "device", None),
                        "id", None,
                    )
                    for shard in self._shards
                ],
            )
        else:
            plane.attach_queues([lambda: len(self._pending)], [None])
        plane.watch_fallback(
            lambda: self.degraded, lambda: self.degraded_evidence
        )

    def _drain_ingest(self) -> None:
        ring = self._ingest_ring
        if ring is None:
            return
        story = self.txstory
        if self._shards is not None:
            for batch in ring.drain():
                for p in batch:
                    if story is not None:
                        # ring arrivals bypass enqueue_pending (no
                        # intent journal on the wire path) but still
                        # admit into the lifecycle ledger
                        self._story_intake(p, fresh=True)
                    self._enqueue_sharded(p)
            return
        for batch in ring.drain():
            if story is not None:
                for p in batch:
                    self._story_intake(p, fresh=True)
            self._pending.extend(batch)
        if self._pending and self._oldest_arrival is None:
            self._oldest_arrival = self.services.clock.now_micros()

    def tick(self) -> int:
        """Pump hook (MockNetwork `node.ticks` / Node._tick_services):
        flush whatever accumulated during the last delivery round —
        unless a batching deadline is set and neither it nor max_batch
        has been reached yet. Returns requests answered (0 = held or
        quiescent)."""
        if self.intent_journal is not None:
            # group-commit the WAL's resolution deletes once per tick
            # (the fsync discipline of the fabric journals): answers
            # buffered since the last tick clear in ONE transaction
            self.intent_journal.flush_resolved()
        if self._shards is not None:
            return self._tick_sharded()
        self._drain_ingest()
        hb = self._health_heartbeat
        n = len(self._pending)
        if not n:
            if hb is not None:
                hb.beat()
            return 0
        if self.effective_wait_micros and n < self.effective_max_batch:
            age = (
                self.services.clock.now_micros()
                - (self._oldest_arrival or 0)
            )
            if age < self.effective_wait_micros:
                # held, not wedged: the loop is alive (beat), it just
                # chose to wait — zero progress, which is exactly what
                # livelock detection should see while a batch forms
                if hb is not None:
                    hb.beat()
                return 0
        self.flush()
        if hb is not None:
            hb.beat(progress=n)
        return n

    def _tick_sharded(self) -> int:
        """One pump round over the sharded commit plane: route fresh
        ingest arrivals, then flush every shard whose batch is due —
        inline as a dispatch-all-then-consume wave (device compute for
        shard k overlaps host work for shard j), or by waking each due
        shard's worker thread. Completions from worker flushes resolve
        HERE, on the pump thread."""
        self._drain_ingest()
        now = self.services.clock.now_micros()
        due: list[_NotaryShard] = []
        total_backlog = 0
        for shard in self._shards:
            with shard.cond:
                n = len(shard.pending)
                total_backlog += n
                if not n:
                    if not self._workers and shard.heartbeat is not None:
                        shard.heartbeat.beat()   # alive, quiescent
                    continue
                wait = self._shard_wait(shard)
                if wait and n < self._shard_cap(shard):
                    age = now - (shard.oldest_arrival or 0)
                    if age < wait:
                        # held, not wedged (see the unsharded tick)
                        if shard.heartbeat is not None:
                            shard.heartbeat.beat()
                        continue
                if self._workers:
                    shard.wake = True
                    shard.cond.notify_all()
                else:
                    due.append(shard)
        answered = self._flush_wave(due) if due else 0
        answered += self._drain_completions()
        if self.qos is not None and hasattr(self.qos, "observe_backlog"):
            # ONE brownout observation per pump round, on the aggregate
            # backlog — per-shard flush feedback only retunes that
            # shard's controller (a hot shard cannot brown out the node
            # by itself; a node-wide backlog still does)
            self.qos.observe_backlog(total_backlog)
        hb = self._health_heartbeat
        if hb is not None:
            hb.beat(progress=answered)
        return answered

    def _drain_completions(self) -> int:
        """Resolve worker-flushed answers on the calling (pump) thread."""
        q = self._completions
        if not q:
            return 0
        n = 0
        while True:
            try:
                fut, outcome = q.popleft()
            except IndexError:
                break
            fut.set_result(outcome)
            n += 1
        return n

    def stop(self) -> None:
        """Stop shard worker threads (no-op without them)."""
        if not self._workers:
            return
        self._stop_workers = True
        for shard in self._shards or ():
            with shard.cond:
                shard.cond.notify_all()
        for t in self._workers:
            t.join(timeout=5)
        self._workers = []
        self._drain_completions()

    def _mark(
        self, phase: str, t_prev: float, marks: Optional[list] = None
    ) -> float:
        """Phase boundary: charge now - t_prev to `phase` on the
        registry timer (always), the profile dict (when
        CORDA_TPU_NOTARY_PROFILE is set), and `marks` (the per-flush
        interval list trace-span emission consumes). Always returns
        now so call sites stay one-liners."""
        now = time.perf_counter()
        dt = now - t_prev
        timer = self._phase_timers.get(phase)
        if timer is None:
            timer = self._phase_timers[phase] = self.metrics.timer(
                "Notary.FlushPhase." + phase
            )
        timer.update(dt)
        if self._phase_profile is not None:
            self._phase_profile[phase] = (
                self._phase_profile.get(phase, 0.0) + dt
            )
        if marks is not None:
            marks.append((phase, t_prev, now))
        return now

    def _gc_pause(self) -> None:
        # A flush allocates O(batch) objects (futures, ladder requests,
        # resolved ltxs) that stay reachable until the scatter at the
        # end — a generational collection mid-flush walks the whole
        # staged heap for nothing, and at 16k-deep flushes those gen-2
        # sweeps were 68% of the serving wall (BASELINE.md round-3
        # profile). Suspend automatic GC for the bounded flush body;
        # collection resumes (and catches up) between pump ticks.
        # Refcounted: concurrent shard-worker flushes share one pause.
        with self._gc_lock:
            self._gc_depth += 1
            if self._gc_depth == 1:
                self._gc_reenable = gc.isenabled()
                if self._gc_reenable:
                    gc.disable()

    def _gc_resume(self) -> None:
        with self._gc_lock:
            self._gc_depth -= 1
            if self._gc_depth == 0 and self._gc_reenable:
                gc.enable()

    def flush(self) -> None:
        """Drain everything pending NOW. On the sharded plane this
        flushes every shard: inline as one dispatch-all-then-consume
        wave, or — with worker threads — by waking every shard and
        blocking until they go idle, then resolving the completions on
        the calling thread (which acts as the pump)."""
        if self.intent_journal is not None:
            self.intent_journal.flush_resolved()
        self._drain_ingest()   # pre-ingested arrivals join this flush
        if self._shards is not None:
            if self._workers:
                for shard in self._shards:
                    with shard.cond:
                        if shard.pending:
                            shard.wake = True
                            shard.cond.notify_all()
                for shard in self._shards:
                    with shard.cond:
                        # bounded waits: a stopped plane (or a worker
                        # killed by a BaseException) must not park this
                        # caller forever on a predicate no thread will
                        # ever satisfy
                        while not shard.cond.wait_for(
                            lambda: not shard.pending and not shard.busy,
                            timeout=0.5,
                        ):
                            if self._stop_workers or not any(
                                t.is_alive() for t in self._workers
                            ):
                                break
                self._drain_completions()
            else:
                self._flush_wave(
                    [s for s in self._shards if s.pending]
                )
            return
        self._gc_pause()
        try:
            self._flush_inner()
        finally:
            self._gc_resume()

    # -- sharded flush machinery (round 6) ----------------------------------

    def _take_pending(self, shard) -> list[_PendingNotarisation]:
        with shard.cond:
            pending, shard.pending = shard.pending, []
            shard.oldest_arrival = None
            if pending:
                shard.busy = True
            return pending

    def _flush_wave(self, shards: list) -> int:
        """Inline sharded flush: phase A stages + dispatches EVERY due
        shard's verify batch (per-device, async), phase B consumes them
        in shard order — so while shard k's host validate/commit runs,
        shards k+1..N's device compute is already in flight. One GC
        pause spans the wave."""
        if not shards:
            return 0
        total = 0
        self._gc_pause()
        try:
            staged = []
            for shard in shards:
                pending = self._take_pending(shard)
                if not pending:
                    continue
                if self.qos is not None:
                    pending = self._qos_admit(pending, shard)
                    if not pending:
                        self._shard_done(shard, 0)
                        continue
                marks: list[tuple[str, float, float]] = []
                ctx = self._stage_and_dispatch(pending, marks, shard)
                staged.append((shard, pending, marks, ctx))
            for shard, pending, marks, ctx in staged:
                try:
                    if ctx is not None:
                        self._consume_flush(ctx, marks, shard)
                finally:
                    self._emit_flush_trace(pending, marks, shard)
                    if self.qos is not None:
                        self._qos_feedback(pending, shard)
                    self._shard_done(shard, len(pending))
                total += len(pending)
            if self._perf is not None and staged:
                # one wave observation: per-shard skew feeds plus the
                # dispatch-vs-consume overlap efficiency (the wave's
                # reason to exist — device compute of shard k+1 under
                # host consume of shard k)
                self._perf.observe_wave(
                    [
                        (shard.id, len(pending), marks)
                        for shard, pending, marks, _ctx in staged
                    ]
                )
        finally:
            self._gc_resume()
        return total

    def _flush_one_shard(self, shard) -> int:
        """Full flush pipeline for ONE shard (worker threads; also the
        queue-full inline trigger)."""
        pending = self._take_pending(shard)
        if not pending:
            return 0
        self._gc_pause()
        try:
            if self.qos is not None:
                pending = self._qos_admit(pending, shard)
                if not pending:
                    self._shard_done(shard, 0)
                    return 0
            marks: list[tuple[str, float, float]] = []
            try:
                ctx = self._stage_and_dispatch(pending, marks, shard)
                if ctx is not None:
                    self._consume_flush(ctx, marks, shard)
            finally:
                self._emit_flush_trace(pending, marks, shard)
                if self._perf is not None:
                    self._perf.observe_flush(shard.id, len(pending), marks)
                if self.qos is not None:
                    self._qos_feedback(pending, shard)
                self._shard_done(shard, len(pending))
            return len(pending)
        finally:
            self._gc_resume()

    def _shard_done(self, shard, answered: int) -> None:
        shard.flushes.inc()
        if answered:
            shard.requests.inc(answered)
            shard.answered.inc(answered)
        if shard.heartbeat is not None:
            shard.heartbeat.beat(progress=answered)
        with shard.cond:
            shard.busy = False
            shard.cond.notify_all()

    def _shard_worker(self, shard) -> None:
        """One shard's dedicated flush loop: wait for work (or a wake
        from the router/tick), honour the batching deadline, flush.
        Never dies — every flush path answers its futures on error, and
        an unexpected exception here logs rather than silently wedging
        the shard (the per-shard heartbeat would flag it anyway)."""
        clock = self.services.clock
        while not self._stop_workers:
            with shard.cond:
                shard.cond.wait_for(
                    lambda: shard.wake or shard.pending or self._stop_workers,
                    timeout=0.05,
                )
                if self._stop_workers:
                    return
                woken, shard.wake = shard.wake, False
                n = len(shard.pending)
                if not n:
                    if shard.heartbeat is not None:
                        shard.heartbeat.beat()   # alive, quiescent
                    continue
                if not woken:
                    wait = self._shard_wait(shard)
                    if wait and n < self._shard_cap(shard):
                        age = clock.now_micros() - (shard.oldest_arrival or 0)
                        if age < wait:
                            if shard.heartbeat is not None:
                                shard.heartbeat.beat()   # held, not wedged
                            continue
            try:
                self._flush_one_shard(shard)
            except Exception:   # noqa: BLE001 - keep the shard serving
                import logging

                logging.getLogger("corda_tpu.notary").exception(
                    "shard %d flush failed", shard.id
                )
                with shard.cond:
                    shard.busy = False
                    shard.cond.notify_all()

    def shard_depths(self) -> list[int]:
        """Live pending depth per shard (health/qos introspection)."""
        if self._shards is None:
            return [len(self._pending)]
        return [s.depth() for s in self._shards]

    def _flush_inner(self) -> None:
        pending, self._pending = self._pending, []
        self._oldest_arrival = None
        if not pending:
            return
        if self.qos is not None:
            pending = self._qos_admit(pending)
            if not pending:
                self.qos.observe_flush(0, len(self._pending))
                return
        # `marks` collects this flush's phase intervals; the finally
        # attributes them to every member frame's trace and ENDS the
        # per-frame root spans — on every exit path (normal, streamed,
        # dispatch failure), so upstream traces always complete
        marks: list[tuple[str, float, float]] = []
        try:
            self._flush_body(pending, marks)
        finally:
            self._emit_flush_trace(pending, marks)
            if self._perf is not None:
                self._perf.observe_flush(0, len(pending), marks)
            if self.qos is not None:
                self._qos_feedback(pending)

    def _qos_admit(
        self, pending: list[_PendingNotarisation], shard=None
    ) -> list[_PendingNotarisation]:
        """Pre-stage QoS pass over one flush's intake: shed requests
        whose deadline passed while they queued (a typed `shed` answer
        — the client gave up; verifying it would burn a TPU batch lane
        on a dead request), then cap the served depth at the adaptive
        controller's batch (the owning SHARD's controller on the
        sharded plane) so one flush cannot blow the latency budget;
        the overflow re-queues AHEAD of newer arrivals (FIFO holds)."""
        from . import qos as qoslib

        qos = self.qos
        now = self.services.clock.now_micros()
        live: list[_PendingNotarisation] = []
        for p in pending:
            if qoslib.expired(p.deadline, now):
                # the answer future below carries the story terminal;
                # shed_tx only stamps the qos.shed event + counter
                qos.shed_tx(qoslib.SHED_EXPIRED_FLUSH, p.stx.id)
                if p.span:
                    # shed events are span events: the trace shows WHY
                    # this notarisation never reached the dispatch
                    p.span.add_event(
                        "qos.shed", reason=qoslib.SHED_EXPIRED_FLUSH
                    )
                    p.span.set_attribute("shed", qoslib.SHED_EXPIRED_FLUSH)
                    p.span.end()
                p.future.set_result(
                    NotaryError(
                        qoslib.SHED_KIND,
                        f"deadline {p.deadline} expired while queued "
                        f"(now {now})",
                    )
                )
            else:
                live.append(p)
        cap = (
            self._shard_cap(shard) if shard is not None
            else qos.controller.batch
        )
        if len(live) > cap:
            overflow = live[cap:]
            live = live[:cap]
            arrival = (
                overflow[0].arrival_micros
                if overflow[0].arrival_micros is not None
                else now
            )
            if shard is not None:
                with shard.cond:
                    shard.pending = overflow + shard.pending
                    shard.oldest_arrival = arrival
            else:
                self._pending = overflow + self._pending
                self._oldest_arrival = arrival
        return live

    def _qos_feedback(
        self, served: list[_PendingNotarisation], shard=None
    ) -> None:
        """Post-flush QoS pass: admitted-request completion latency
        (node-clock micros, arrival -> answer) into the histogram the
        adaptive controller reads, then one controller observation with
        the depth served and the backlog left — the owning shard's
        controller on the sharded plane, so a hot shard retunes ITSELF
        without collapsing the other shards' batching windows.
        Futures still open here (distributed-commit consensus resolves
        them later) record at RESOLUTION via a done callback — slow
        consensus commits must reach the p99 the controller steers by,
        or it would stretch the window while the real SLO breaches."""
        qos = self.qos
        now = self.services.clock.now_micros()
        sid = shard.id if shard is not None else None
        for p in served:
            if p.arrival_micros is None:
                continue
            fut = p.future
            if getattr(fut, "done", False):
                qos.record_admitted(now - p.arrival_micros, shard=sid)
            elif hasattr(fut, "add_done_callback"):
                fut.add_done_callback(
                    lambda f, arr=p.arrival_micros, q=qos, s=sid: (
                        q.record_admitted(q.now_micros() - arr, shard=s)
                    )
                )
        if shard is not None and hasattr(qos, "observe_shard_flush"):
            qos.observe_shard_flush(sid, len(served), shard.depth())
        else:
            qos.observe_flush(len(served), len(self._pending))

    def _emit_flush_trace(self, pending, marks, shard=None) -> None:
        """Per-frame trace assembly: the flush phases ran batched, so
        each interval is shared across the batch and stamped into every
        traced member's tree (batch size as an attribute; the owning
        shard id too on the sharded plane, so per-shard alert evidence
        — the perf plane's skew rule — can cite the traces that
        touched the hot shard). Spans are emitted on the tracer that
        OWNS the frame's root span, so mixed tracer setups still
        assemble whole traces."""
        n = len(pending)
        sid = shard.id if shard is not None else None
        for p in pending:
            span = p.span
            if not span or span.ended:
                # an already-ended root means ITS owner closed the
                # trace at ingest (pipeline feed path): attaching phase
                # spans now would re-open the assembled trace as orphan
                # fragments — the flush only annotates roots it OWNS
                continue
            tracer = getattr(span, "_tracer", None)
            if tracer is not None:
                if sid is not None:
                    span.set_attribute("shard", sid)
                for phase, t0, t1 in marks:
                    if sid is not None:
                        tracer.span_at(
                            "notary." + phase, span, t0, t1,
                            batch=n, shard=sid,
                        )
                    else:
                        tracer.span_at(
                            "notary." + phase, span, t0, t1, batch=n
                        )
            # the root ends when the request is ANSWERED: on the
            # synchronous paths every future resolved inside the flush
            # body, but a distributed provider's commit_async resolves
            # on cluster consensus AFTER this finally — deferring the
            # end there keeps the consensus-commit latency inside the
            # trace (the slow-commit regression the recorder hunts)
            fut = p.future
            if getattr(fut, "done", True) or not hasattr(
                fut, "add_done_callback"
            ):
                span.end()
            else:
                fut.add_done_callback(lambda f, s=span: s.end())

    def _flush_body(self, pending, marks) -> None:
        ctx = self._stage_and_dispatch(pending, marks)
        if ctx is not None:
            self._consume_flush(ctx, marks)

    def _stage_and_dispatch(self, pending, marks, shard=None):
        """Phase A of a flush: stage every pending transaction's
        signature requests and launch the (async) SPI dispatch — on the
        shard's device-pinned verifier when one is wired, the hub's
        shared verifier otherwise. Returns the flush context for
        _consume_flush, or None when there is nothing left to consume
        (every future already answered)."""
        t = time.perf_counter()
        # phase 1 — ONE SPI dispatch across all pending transactions.
        # Staging is per-tx-protected: one malformed transaction (bad
        # scheme in signature_requests) must answer ITS future with an
        # error and leave the rest of the batch alive — aborting here
        # after the queue was swapped out would strand every
        # requester's FlowFuture forever.
        reqs: list = []
        spans: list[tuple[int, int]] = []
        live: list[_PendingNotarisation] = []
        for p in pending:
            try:
                rs = p.stx.signature_requests()
            except Exception as e:
                p.future.set_result(
                    NotaryError("invalid-transaction", str(e))
                )
                continue
            spans.append((len(reqs), len(rs)))
            reqs.extend(rs)
            live.append(p)
        pending = live
        if not pending:
            return None
        if self.txstory is not None:
            # flush membership: batch id + owning shard on every
            # member transaction's story, one lock hold for the batch
            self.txstory.flush_membership(
                [str(p.stx.id) for p in pending],
                shard=shard.id if shard is not None else None,
            )
        t = self._mark("stage", t, marks)
        verifier = (
            shard.verifier
            if shard is not None and shard.verifier is not None
            else self.services.batch_verifier
        )
        poison: set = set()
        try:
            collector: Optional[threading.Thread] = None
            box: dict = {}
            handle = None
            results = None
            # TraceAnnotation (when jax provides it): the dispatch span
            # becomes a named region in an XLA profiler capture, so
            # host-side traces line up with the device timeline
            try:
                with tracing.annotate(
                    "corda_tpu.notary.batch_verify_dispatch"
                ):
                    if hasattr(verifier, "verify_batch_async"):
                        handle = verifier.verify_batch_async(reqs)
                    else:
                        results = verifier.verify_batch(reqs)
                if self._degraded and results is not None:
                    # the recovery probe: a degraded notary keeps
                    # attempting the device each flush — one success
                    # re-arms the device path and resolves the alert.
                    # ONLY a synchronous dispatch proves anything here:
                    # an async handle's real device fault surfaces at
                    # consume/collector time, so the consume path owns
                    # the exit for handles (a broken device must not
                    # "recover" at every dispatch and re-degrade at
                    # every consume).
                    self._exit_degraded()
            except Exception as first_err:
                if not self.degraded_fallback:
                    raise
                handle = None
                if not self._degraded:
                    # transient blip? one device retry before degrading
                    try:
                        results = verifier.verify_batch(reqs)
                    except Exception:
                        results, poison = self._degraded_verify(
                            pending, spans, reqs, first_err
                        )
                else:
                    # already degraded: the probe above just failed —
                    # no second device attempt, straight to the CPU
                    results, poison = self._degraded_verify(
                        pending, spans, reqs, first_err
                    )
            # STREAMING tail (round-5): when the handle's per-chunk
            # transfers were queued at dispatch and the uniqueness
            # provider commits synchronously, chunk k's transactions
            # validate + commit while the device still runs chunk k+1 —
            # the residual link_wait the join path pays disappears into
            # downstream host work. Commit order stays exactly arrival
            # order (the chunk consumer advances a monotonic pointer),
            # so intra-batch first-wins semantics are unchanged.
            stream_ok = (
                handle is not None
                and getattr(handle, "streamed", False)
                and getattr(self.uniqueness, "batch_synchronous", False)
            )
            if handle is not None and not stream_ok:
                # collect on a worker thread: on a remote-attached
                # device the d2h result fetch is GIL-releasing link IO
                # (~100 ms), which this overlaps with the contract loop
                # below instead of serialising after it
                def _collect() -> None:
                    try:
                        box["results"] = handle.result()
                    except Exception as e:   # noqa: BLE001 - rethrown below
                        box["error"] = e

                # named so the sampling profiler (utils/perf.py)
                # attributes the link wait to this thread, not Thread-N
                collector = threading.Thread(
                    target=_collect, name="notary-collect", daemon=True
                )
                collector.start()
            t = self._mark("dispatch", t, marks)
        except Exception as e:
            # a failed dispatch (unsupported scheme in the batch, device
            # unavailable) must answer every waiting requester, not
            # strand them and crash the pump tick
            for p in pending:
                p.future.set_result(
                    NotaryError("verification-unavailable", str(e))
                )
            return None
        return {
            "pending": pending,
            "spans": spans,
            "handle": handle,
            "results": results,
            "collector": collector,
            "box": box,
            "stream_ok": stream_ok,
            "t": t,
            "reqs": reqs,
            "poison": poison,
        }

    # -- degraded-mode verify (round 9) --------------------------------------

    @property
    def degraded(self) -> bool:
        """True while the device verify path is distrusted (the last
        flush fell back to the CPU reference and no probe has
        succeeded since) — the `notary.degraded_mode` alert condition."""
        return self._degraded

    @property
    def degraded_evidence(self) -> dict:
        return dict(self._degraded_last)

    def _cpu_ref(self):
        if self._cpu_reference is None:
            from ..crypto.batch_verifier import CpuBatchVerifier

            self._cpu_reference = CpuBatchVerifier()
        return self._cpu_reference

    def _enter_degraded(self, error) -> None:
        self._degraded_counter.inc()
        self._degraded_last = {
            "error": f"{type(error).__name__}: {error}",
            "at_micros": self.services.clock.now_micros(),
            "degraded_flushes": self._degraded_counter.count,
        }
        self._degraded = True

    def _exit_degraded(self) -> None:
        if self._degraded:
            self._degraded = False
            self._degraded_last = dict(
                self._degraded_last,
                recovered_at_micros=self.services.clock.now_micros(),
            )

    def _degraded_verify(self, pending, spans, reqs, error):
        """One flush's CPU-reference fallback after the device path
        failed twice: bit-exact semantics (CpuBatchVerifier is the
        correctness anchor the kernels are pinned against), so the
        degraded flush commits EXACTLY the answers the device path
        would. When even the CPU pass raises — the failure is
        deterministic, i.e. a poison transaction, not a dead device —
        bisect by transaction to isolate it: the poison indices are
        returned for quarantine and every other transaction still gets
        real results. Returns (results, poison_tx_indices)."""
        self._enter_degraded(error)
        if self.txstory is not None:
            # degraded outcome, attributed per member transaction: the
            # flush that answers these was served by the CPU reference
            self.txstory.degraded_flush(
                [str(p.stx.id) for p in pending],
                f"{type(error).__name__}: {error}",
            )
        cpu = self._cpu_ref()
        try:
            return list(cpu.verify_batch(reqs)), set()
        except Exception:
            pass
        results: list = [False] * len(reqs)
        poison: set[int] = set()

        def attempt(lo: int, hi: int) -> None:
            o0 = spans[lo][0]
            o1 = spans[hi - 1][0] + spans[hi - 1][1]
            if o1 == o0:
                return   # no signature rows: cannot be the poison
            try:
                sub = cpu.verify_batch(reqs[o0:o1])
            except Exception:
                if hi - lo == 1:
                    poison.add(lo)
                    return
                mid = (lo + hi) // 2
                attempt(lo, mid)
                attempt(mid, hi)
                return
            results[o0:o1] = sub

        # seed with the two halves: the full range just FAILED above —
        # re-verifying it whole would repeat the most expensive pass
        n = len(pending)
        if n == 1:
            poison.add(0)
        else:
            attempt(0, n // 2)
            attempt(n // 2, n)
        return results, poison

    def _quarantine(self, p: _PendingNotarisation) -> None:
        """Answer a poison transaction with its typed error and record
        it — the rest of its batch commits normally around it."""
        self._quarantined_counter.inc()
        self.quarantined.append(p.stx.id)
        p.future.set_result(
            NotaryError(
                "poison-quarantined",
                f"transaction {p.stx.id} deterministically crashed the "
                f"batch verifier and was quarantined "
                f"({self._degraded_last.get('error', 'no detail')})",
            )
        )

    def _consume_flush(self, ctx, marks, shard=None) -> None:
        """Phase B of a flush: host-side resolve+contract pass, then
        consume the verify results (streamed or joined), validate,
        commit against the (possibly partitioned) uniqueness provider,
        sign and scatter replies. Runs while OTHER shards' device
        batches are still computing — that overlap is the sharded
        plane's wave pipeline."""
        pending = ctx["pending"]
        spans = ctx["spans"]
        handle = ctx["handle"]
        results = ctx["results"]
        collector = ctx["collector"]
        box = ctx["box"]
        stream_ok = ctx["stream_ok"]
        t = ctx["t"]
        poison = ctx.get("poison") or set()
        contract_errs = deferred_ltx = None
        try:
            # overlap: contract execution (host Python) runs while the
            # device computes the signature batch and the collector
            # thread drains the result transfer. Contracts run through
            # the SPI's BATCH entry point: one grouped-by-contract pass
            # for the in-memory service (asset contracts verify the
            # whole flush in a specialized sweep, core/batch_verify.py),
            # ONLY registered (operator-installed) contracts run
            # speculatively here — attachment-carried sandboxed code is
            # peer-supplied, so it DEFERS until the transaction's
            # signatures are known-good (phase 2 below), matching the
            # verifier worker's gate. The SPI seam is honoured only for
            # SYNCHRONOUS verifier services: an async (out-of-process)
            # pool resolves its futures via the message pump this flush
            # is running ON, so blocking on it here would deadlock —
            # the batching notary then verifies in-process instead.
            tv = self.services.transaction_verifier
            tv_sync = getattr(tv, "synchronous", False)
            # ONE batched resolve+verify pass (services.py
            # resolve_verify_batch): asset-shaped transactions take the
            # object-less fast sweep, the rest build LedgerTransactions
            # and honour the SPI seam / attachment-code deferral as
            # before. Async (out-of-process) pools resolve their
            # futures via the pump this flush runs ON, so the SPI is
            # honoured only when synchronous — the in-process grouped
            # sweep covers the rest.
            contract_errs, deferred_ltx = self.services.resolve_verify_batch(
                [p.stx for p in pending],
                spi=tv if tv_sync else None,
            )
            t = self._mark("resolve_verify", t, marks)
            if stream_ok:
                self._stream_tail(
                    pending, spans, contract_errs, deferred_ltx,
                    handle, tv, tv_sync, t, marks,
                    reqs=ctx.get("reqs"), poison=poison,
                )
                return
            if collector is not None:
                collector.join()
                if "error" in box:
                    raise box["error"]
                results = box["results"]
                if self._degraded:
                    # async probe success: the handle's results really
                    # came back from the device — NOW it has recovered
                    self._exit_degraded()
            t = self._mark("link_wait", t, marks)
        except Exception as e:
            # the device batch died AFTER dispatch (collector fetch /
            # link failure): same degraded seam as the dispatch guard,
            # minus the retry — the in-flight compute is gone, so the
            # CPU reference serves this flush (bit-exact) and the next
            # flush's device attempt is the recovery probe. Host-side
            # resolve failures (contract_errs still unset) are NOT a
            # device fault — re-verifying signatures cannot fix them.
            if (
                self.degraded_fallback
                and contract_errs is not None
                and ctx.get("reqs") is not None
            ):
                try:
                    results, late_poison = self._degraded_verify(
                        pending, spans, ctx["reqs"], e
                    )
                    poison = poison | late_poison
                    t = self._mark("link_wait", t, marks)
                except Exception as e2:   # noqa: BLE001 - answer, not strand
                    for p in pending:
                        p.future.set_result(
                            NotaryError("verification-unavailable", str(e2))
                        )
                    return
            else:
                # a failed dispatch (unsupported scheme in the batch,
                # device unavailable with fallback off) must answer
                # every waiting requester, not strand them and crash
                # the pump tick
                for p in pending:
                    p.future.set_result(
                        NotaryError("verification-unavailable", str(e))
                    )
                return
        self._batches_counter.inc()
        self._requests_counter.inc(len(pending))
        # phase 2 — per-tx validation in arrival order
        eligible: list[_PendingNotarisation] = []
        for i, (p, (off, n), cerr) in enumerate(
            zip(pending, spans, contract_errs)
        ):
            if i in poison:
                # deterministic verifier crash isolated to THIS tx: a
                # typed quarantine answer; its batchmates commit
                self._quarantine(p)
                continue
            if not self._validate_one(p, results[off : off + n], cerr):
                continue
            dltx = deferred_ltx.get(i)
            if dltx is not None:
                # signatures just validated: NOW the peer-supplied
                # attachment code may run (sandboxed) — through the SPI
                # when it resolves inline, in-process otherwise (an
                # async pool cannot complete inside this pump tick)
                try:
                    if tv_sync:
                        tv.verify(dltx).result()
                    else:
                        dltx.verify()
                except Exception as e:
                    p.future.set_result(
                        NotaryError("invalid-transaction", str(e))
                    )
                    continue
            eligible.append(p)
        t = self._mark("validate", t, marks)
        if not eligible:
            return
        conflict_error = self._conflict_error
        finalize = self._finalize_sign

        # phase 3 — uniqueness commit. A synchronous provider takes the
        # WHOLE flush through one commit_many (one lock/DB transaction,
        # no future+callback per tx); a distributed provider keeps the
        # per-tx future path since each commit resolves on consensus.
        if getattr(self.uniqueness, "batch_synchronous", False):
            try:
                outcomes = self.uniqueness.commit_many(
                    [
                        (list(p.stx.wtx.inputs), p.stx.id, p.requester)
                        for p in eligible
                    ]
                )
            except Exception as e:
                # a failed batch write (db locked, disk error) must
                # answer every waiting requester, not strand them and
                # crash the pump tick — same contract as the phase-1
                # dispatch failure path above
                for p in eligible:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(e))
                    )
                return
            committed: dict[int, _PendingNotarisation] = {}
            for i, (p, err) in enumerate(zip(eligible, outcomes)):
                if err is None:
                    committed[i] = p
                elif isinstance(err, UniquenessConflict):
                    p.future.set_result(conflict_error(err))
                else:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(err))
                    )
            t = self._mark("commit", t, marks)
            finalize(committed)
            self._mark("sign_scatter", t, marks)
            return

        committed_async: dict[int, _PendingNotarisation] = {}
        remaining = [len(eligible)]

        def on_commit(f, i: int, p: _PendingNotarisation) -> None:
            try:
                f.result()
            except UniquenessConflict as e:
                p.future.set_result(conflict_error(e))
            except ShardUnavailableError as e:
                # distributed commit plane: the owning partition's
                # member is unreachable — typed degraded answer, the
                # request holds no reservations anywhere
                p.future.set_result(NotaryError("shard-unavailable", str(e)))
            except Exception as e:
                p.future.set_result(NotaryError("commit-unavailable", str(e)))
            else:
                committed_async[i] = p
            remaining[0] -= 1
            if remaining[0] == 0:
                finalize(committed_async)

        for i, p in enumerate(eligible):
            fut = self.uniqueness.commit_async(
                list(p.stx.wtx.inputs), p.stx.id, p.requester,
                # the frame's live root span rides into the provider:
                # a distributed commit stamps its xshard.* phase spans
                # into the requester's trace, cross-member hops included
                trace=(
                    tuple(p.span.context)
                    if p.span and not p.span.ended else None
                ),
            )
            fut.add_done_callback(lambda f, i=i, p=p: on_commit(f, i, p))
        self._mark("sign_scatter", t, marks)

    def _conflict_error(self, e: UniquenessConflict) -> NotaryError:
        return NotaryError(
            "conflict",
            str(e),
            conflict={str(r): h for r, h in e.conflict.items()},
        )

    def _finalize_sign(
        self, committed: dict[int, _PendingNotarisation]
    ) -> None:
        # ONE Merkle-batch notary signature over all committed ids,
        # scattered with per-tx inclusion proofs (host signing is
        # ~70 µs/signature — per-tx signing alone would cap the
        # serving rate near 14k tx/s)
        if not committed:
            return
        order = sorted(committed)
        try:
            sigs = self.services.key_management.sign_batch(
                [committed[i].stx.id for i in order],
                self.identity.owning_key,
            )
        except Exception as e:
            for i in order:
                committed[i].future.set_result(
                    NotaryError("commit-unavailable", str(e))
                )
            return
        for i, sig in zip(order, sigs):
            committed[i].future.set_result(sig)

    def _stream_tail(
        self, pending, spans, contract_errs, deferred_ltx,
        handle, tv, tv_sync, t, marks=None, reqs=None, poison=None,
    ) -> None:
        """Streaming validate+commit (round-5): consume the SPI's
        per-chunk results as each chunk's device compute completes,
        validating and committing chunk k's transactions while the
        device still runs chunk k+1. The pointer over `pending` is
        monotonic and a transaction only passes it when EVERY one of
        its signature rows is resolved, so validation and commit
        happen in exact arrival order — intra-batch first-wins
        double-spend semantics are identical to the join path's one
        commit_many over the whole flush."""
        results = handle.skeleton()
        committed: dict[int, _PendingNotarisation] = {}
        state = {"ptr": 0}
        n_pend = len(pending)
        poison = set() if poison is None else set(poison)
        # counted at dispatch like the join path (line above phase 2):
        # a batch that later fails mid-stream was still dispatched
        self._batches_counter.inc()
        self._requests_counter.inc(n_pend)

        def drain() -> bool:
            """Advance over fully-resolved transactions: validate,
            then commit the ready group. False = batch write failed
            (every requester answered)."""
            ready: list[tuple[int, _PendingNotarisation]] = []
            ptr = state["ptr"]
            while ptr < n_pend:
                off, n = spans[ptr]
                row = results[off : off + n]
                if any(r is None for r in row):
                    break
                i, p = ptr, pending[ptr]
                ptr += 1
                if i in poison:
                    self._quarantine(p)   # typed answer, batchmates live
                    continue
                if not self._validate_one(p, row, contract_errs[i]):
                    continue
                dltx = deferred_ltx.get(i)
                if dltx is not None:
                    # signatures just validated: NOW peer-supplied
                    # attachment code may run (sandboxed)
                    try:
                        if tv_sync:
                            tv.verify(dltx).result()
                        else:
                            dltx.verify()
                    except Exception as e:   # noqa: BLE001 - per tx
                        p.future.set_result(
                            NotaryError("invalid-transaction", str(e))
                        )
                        continue
                ready.append((i, p))
            state["ptr"] = ptr
            if not ready:
                return True
            try:
                outcomes = self.uniqueness.commit_many(
                    [
                        (list(p.stx.wtx.inputs), p.stx.id, p.requester)
                        for _, p in ready
                    ]
                )
            except Exception as e:   # noqa: BLE001 - answer all
                # failed batch write: answer every unanswered
                # requester (already-committed ones re-commit
                # idempotently on client retry)
                for p in pending:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(e))
                    )
                return False
            for (i, p), err in zip(ready, outcomes):
                if err is None:
                    committed[i] = p
                elif isinstance(err, UniquenessConflict):
                    p.future.set_result(self._conflict_error(err))
                else:
                    p.future.set_result(
                        NotaryError("commit-unavailable", str(err))
                    )
            return True

        try:
            for idxs, vals in handle.chunks():
                for j, ok in zip(idxs, vals):
                    results[j] = ok
                if not drain():
                    return
            # all-CPU batches have no device chunks: drain once more
            if state["ptr"] < n_pend and not drain():
                return
            if self._degraded:
                # streamed probe success: every chunk consumed from
                # the device — the degraded path has recovered
                self._exit_degraded()
        except Exception as e:   # noqa: BLE001 - device/link failure
            recovered = False
            if self.degraded_fallback and reqs is not None:
                # mid-stream device failure: transactions already
                # committed keep their answers (the monotonic pointer
                # never revisits them); the CPU reference fills every
                # UNRESOLVED row bit-exact and the drain completes the
                # flush in the same arrival order
                try:
                    fb, late_poison = self._degraded_verify(
                        pending, spans, reqs, e
                    )
                    poison.update(late_poison)
                    for j, v in enumerate(results):
                        if v is None:
                            results[j] = fb[j]
                    recovered = drain()
                except Exception:   # noqa: BLE001 - fall through to answer
                    recovered = False
            if not recovered:
                # a failed chunk fetch must answer every waiting
                # requester, not strand them and crash the pump tick
                # (set_result on an already-answered future is a no-op)
                for p in pending:
                    p.future.set_result(
                        NotaryError("verification-unavailable", str(e))
                    )
                return
        t = self._mark("stream_commit", t, marks)
        self._finalize_sign(committed)
        self._mark("sign_scatter", t, marks)

    def _validate_one(
        self,
        p: _PendingNotarisation,
        sig_results: list[bool],
        contract_err: Optional[Exception] = None,
    ) -> bool:
        """Pre-commit checks; answers the future and returns False on
        failure, True when the tx may proceed to uniqueness commit."""
        stx = p.stx
        try:
            # signature errors take precedence over the (overlapped)
            # contract result, matching the reference's check order
            # (SignedTransaction.kt:143-149)
            stx.raise_on_invalid(sig_results)
            except_keys = self.__dict__.get("_except_keys")
            if except_keys is None:
                except_keys = frozenset((self.identity.owning_key,))
                self._except_keys = except_keys
            stx.verify_required_signatures(except_keys)
            if contract_err is not None:
                raise contract_err
        except Exception as e:
            p.future.set_result(NotaryError("invalid-transaction", str(e)))
            return False
        if not self.time_window_checker.is_valid(stx.wtx.time_window):
            p.future.set_result(
                NotaryError(
                    "time-window-invalid",
                    f"window {stx.wtx.time_window} outside notary clock "
                    "tolerance",
                )
            )
            return False
        if self.txstory is not None:
            # the verify->commit stage boundary: signatures + contracts
            # held, this transaction proceeds to the uniqueness commit
            self.txstory.record(str(stx.id), "notary.verified")
        return True


class ValidatingNotaryService(NotaryService):
    """Validating: fully resolves and verifies the transaction —
    signatures through the TPU batch SPI, then contracts — before
    committing (ValidatingNotaryFlow.kt:17-46). Backchain resolution
    happens in the service *flow* (it needs sessions); this class does
    the post-resolution work."""

    validating = True

    def process(
        self,
        stx: SignedTransaction,
        requester: Party,
        deadline: Optional[int] = None,
        trace=None,
    ):
        del deadline   # see SimpleNotaryService.process
        if stx.wtx.notary != self.identity:
            return NotaryError(
                "wrong-notary", f"tx names notary {stx.wtx.notary}, I am "
                f"{self.identity}"
            )
        try:
            stx.verify(
                self.services,
                check_sufficient_signatures=False,   # ours is still missing
                verifier=self.services.batch_verifier,
            )
        except Exception as e:
            return NotaryError("invalid-transaction", str(e))
        return (
            yield from self.commit_and_sign(
                stx.id, list(stx.wtx.inputs), stx.wtx.time_window, requester,
                trace=trace,
            )
        )
