"""SQLite-backed node persistence.

Reference: the node's JDBC/H2 storage layer — `DBTransactionStorage`,
`NodeAttachmentService`, `DBCheckpointStorage` (node/.../services/
persistence/), `PersistentUniquenessProvider` (node/.../services/
transactions/PersistentUniquenessProvider.kt:20), the `JDBCHashMap`
KV-on-SQL primitive (node/.../utilities/JDBCHashMap.kt) and
`CordaPersistence` transaction management (node/.../utilities/
CordaPersistence.kt). H2-behind-ORMs becomes one sqlite database per
node in WAL mode; every store is a write-through cache over its table so
read paths stay as fast as the in-memory Ring-3 services they subclass.

The vault table carries denormalised query columns (contract tag,
fungible quantity/token, linear id, participant fingerprints) — the
analogue of the reference's `MappedSchema` ORM projection
(core/.../schemas/PersistentTypes.kt, node/.../vault/VaultSchema.kt) —
so the QueryCriteria parser (vault_query.py) can compile to SQL the way
HibernateQueryCriteriaParser does.
"""

from __future__ import annotations

import sqlite3
import threading
from ..utils import locks
from typing import Optional

from ..core import serialization as ser
from ..core.contracts import StateRef
from ..core.identity import Party
from ..core.transactions import SignedTransaction
from ..crypto import schemes
from ..crypto.hashes import SecureHash
from .notary import (
    ShardedUniquenessProvider,
    UniquenessConflict,
    UniquenessProvider,
)
from .services import (
    AttachmentStorage,
    CheckpointStorage,
    KeyManagementService,
    TransactionStorage,
    VaultService,
)

_SCHEMA = """
CREATE TABLE IF NOT EXISTS transactions (
    tx_id BLOB PRIMARY KEY,
    data  BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS attachments (
    att_id BLOB PRIMARY KEY,
    data   BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS checkpoints (
    flow_id BLOB PRIMARY KEY,
    record  BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS notary_commits (
    ref_tx    BLOB NOT NULL,
    ref_index INTEGER NOT NULL,
    consumer  BLOB NOT NULL,
    requester TEXT NOT NULL,
    PRIMARY KEY (ref_tx, ref_index)
);
CREATE TABLE IF NOT EXISTS our_keys (
    fingerprint BLOB PRIMARY KEY,
    scheme_id   INTEGER NOT NULL,
    public_key  BLOB NOT NULL,
    private_key BLOB NOT NULL
);
CREATE TABLE IF NOT EXISTS vault_states (
    ref_tx       BLOB NOT NULL,
    ref_index    INTEGER NOT NULL,
    state        BLOB NOT NULL,
    contract_tag TEXT NOT NULL,
    status       INTEGER NOT NULL,          -- 0 unconsumed, 1 consumed
    notary       TEXT,
    quantity     INTEGER,                    -- fungible states
    token        TEXT,                       -- fungible token descriptor
    issuer       TEXT,                       -- fungible issuer party name
    linear_id    BLOB,                       -- linear states
    recorded_at  INTEGER NOT NULL,
    consumed_at  INTEGER,
    PRIMARY KEY (ref_tx, ref_index)
);
CREATE INDEX IF NOT EXISTS vault_status_idx
    ON vault_states (status, contract_tag);
CREATE TABLE IF NOT EXISTS vault_parts (
    ref_tx      BLOB NOT NULL,
    ref_index   INTEGER NOT NULL,
    fingerprint BLOB NOT NULL
);
CREATE INDEX IF NOT EXISTS vault_parts_idx ON vault_parts (fingerprint);
CREATE TABLE IF NOT EXISTS kv (
    space TEXT NOT NULL,
    k     BLOB NOT NULL,
    v     BLOB NOT NULL,
    PRIMARY KEY (space, k)
);
"""


class NodeDatabase:
    """One sqlite database per node (reference: CordaPersistence over
    H2). A single serialized connection shared by every store; callers
    batch related writes inside `transaction()` the way the reference
    wraps service mutations in `database.transaction {}`."""

    def __init__(self, path: str = ":memory:"):
        self.path = path
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = locks.make_rlock("NodeDatabase._lock")
        self._tx_depth = 0
        with self._lock:
            if path != ":memory:":
                self._conn.execute("PRAGMA journal_mode=WAL")
                self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.executescript(_SCHEMA)
            self._conn.commit()

    def execute(self, sql: str, params: tuple = ()) -> sqlite3.Cursor:
        with self._lock:
            cur = self._conn.execute(sql, params)
            if self._tx_depth == 0:
                self._conn.commit()
            return cur

    def query(self, sql: str, params: tuple = ()) -> list[tuple]:
        with self._lock:
            return self._conn.execute(sql, params).fetchall()

    def execute_script(self, script: str) -> None:
        """DDL for subsystem-owned tables (e.g. the fabric journals).
        Refused inside an open transaction: sqlite's executescript
        implicitly COMMITs pending writes, which would break the
        all-or-nothing guarantee of the surrounding block."""
        with self._lock:
            if self._tx_depth > 0:
                raise RuntimeError(
                    "execute_script inside an open transaction would "
                    "implicitly commit it; run DDL at startup instead"
                )
            self._conn.executescript(script)
            self._conn.commit()

    def transaction(self):
        """Context manager: batched atomic writes. Nests — inner blocks
        (and bare execute() calls) join the outermost transaction, which
        alone commits, so a multi-store mutation like
        record_transactions is all-or-nothing across a crash."""
        return _DbTx(self)

    def close(self) -> None:
        with self._lock:
            if self._conn is None:
                return   # idempotent: teardown paths overlap
            self._conn.commit()
            self._conn.close()
            self._conn = None


class _DbTx:
    """Nested blocks are sqlite SAVEPOINTs: an inner failure that the
    caller catches (e.g. UniquenessConflict inside a notary commit)
    rolls back only the inner writes — the outer transaction's prior
    writes survive and its own exit still decides commit vs rollback."""

    def __init__(self, db: NodeDatabase):
        self._db = db
        self._savepoint: Optional[str] = None

    def __enter__(self):
        self._db._lock.acquire()
        try:
            if self._db._tx_depth > 0:
                self._savepoint = f"sp{self._db._tx_depth}"
                self._db._conn.execute(f"SAVEPOINT {self._savepoint}")
            self._db._tx_depth += 1
        except BaseException:
            self._db._lock.release()   # __exit__ will never run
            raise
        return self._db._conn

    def __exit__(self, exc_type, exc, tb):
        try:
            self._db._tx_depth -= 1
            if self._savepoint is not None:
                if exc_type is not None:
                    self._db._conn.execute(
                        f"ROLLBACK TO {self._savepoint}"
                    )
                self._db._conn.execute(f"RELEASE {self._savepoint}")
            else:
                if exc_type is None:
                    self._db._conn.commit()
                else:
                    self._db._conn.rollback()
        finally:
            self._db._lock.release()
        return False


class PersistentKVStore:
    """Namespaced KV map on SQL — the JDBCHashMap primitive the
    reference builds ad-hoc node state on (JDBCHashMap.kt)."""

    def __init__(self, db: NodeDatabase, space: str):
        self._db = db
        self._space = space

    def get(self, key: bytes) -> Optional[bytes]:
        rows = self._db.query(
            "SELECT v FROM kv WHERE space=? AND k=?", (self._space, key)
        )
        return rows[0][0] if rows else None

    def put(self, key: bytes, value: bytes) -> None:
        self._db.execute(
            "INSERT OR REPLACE INTO kv (space, k, v) VALUES (?,?,?)",
            (self._space, key, value),
        )

    def delete(self, key: bytes) -> None:
        self._db.execute(
            "DELETE FROM kv WHERE space=? AND k=?", (self._space, key)
        )

    def items(self) -> list[tuple[bytes, bytes]]:
        return self._db.query(
            "SELECT k, v FROM kv WHERE space=? ORDER BY k", (self._space,)
        )


# ---------------------------------------------------------------------------
# stores


class PersistentTransactionStorage(TransactionStorage):
    """DBTransactionStorage: canonical-serialized SignedTransactions
    keyed by id, write-through over the in-memory map."""

    def __init__(self, db: NodeDatabase):
        super().__init__()
        self._db = db
        for (tx_id, data) in db.query("SELECT tx_id, data FROM transactions"):
            stx = ser.decode(data)
            self._txs[SecureHash(bytes(tx_id))] = stx

    def add_quiet(self, stx: SignedTransaction) -> bool:
        added = super().add_quiet(stx)
        if added:
            self._db.execute(
                "INSERT OR IGNORE INTO transactions (tx_id, data) VALUES (?,?)",
                (stx.id.bytes_, ser.encode(stx)),
            )
        return added


class PersistentAttachmentStorage(AttachmentStorage):
    """NodeAttachmentService: SHA-256-addressed blobs in the DB."""

    def __init__(self, db: NodeDatabase):
        super().__init__()
        self._db = db
        for (att_id, data) in db.query("SELECT att_id, data FROM attachments"):
            self._blobs[SecureHash(bytes(att_id))] = bytes(data)

    def import_attachment(self, data: bytes) -> SecureHash:
        att_id = super().import_attachment(data)
        self._db.execute(
            "INSERT OR IGNORE INTO attachments (att_id, data) VALUES (?,?)",
            (att_id.bytes_, data),
        )
        return att_id


class PersistentCheckpointStorage(CheckpointStorage):
    """DBCheckpointStorage.kt:18 — flow checkpoints survive restarts;
    StateMachineManager.restore_checkpoints reads them back."""

    def __init__(self, db: NodeDatabase):
        super().__init__()
        self._db = db
        for (flow_id, record) in db.query(
            "SELECT flow_id, record FROM checkpoints"
        ):
            self._checkpoints[bytes(flow_id)] = bytes(record)

    def add(self, flow_id: bytes, record: bytes) -> None:
        super().add(flow_id, record)
        self._db.execute(
            "INSERT OR REPLACE INTO checkpoints (flow_id, record) VALUES (?,?)",
            (flow_id, record),
        )

    def remove(self, flow_id: bytes) -> None:
        super().remove(flow_id)
        self._db.execute(
            "DELETE FROM checkpoints WHERE flow_id=?", (flow_id,)
        )


class PersistentUniquenessProvider(UniquenessProvider):
    """The notary's committed-state registry on SQL (reference:
    PersistentUniquenessProvider.kt:20, commit at :63+). All-or-nothing:
    the conflict check and the inserts share one DB transaction."""

    batch_synchronous = True

    # sqlite's default parameter ceiling is 999; two params per ref
    # pair keeps a healthy margin under it
    _PROBE_CHUNK = 400

    def __init__(self, db: NodeDatabase):
        self._db = db
        # O(1) committed count: scanned ONCE at boot, maintained by
        # actual-new-row deltas from the inserts (INSERT OR IGNORE
        # absorbs idempotent re-commits without double-counting)
        self._count = db.query(
            "SELECT COUNT(*) FROM notary_commits"
        )[0][0]

    @classmethod
    def _probe_in(cls, conn, table: str, refs) -> dict:
        """The batched conflict probe: ONE `IN (VALUES ...)` row-value
        query per chunk of refs instead of a point SELECT per ref in a
        Python loop — the same one-sweep-per-flush shape the commit-log
        store's `prior_consumers_many` serves from its mmap index."""
        out: dict = {}
        refs = list(refs)
        for i in range(0, len(refs), cls._PROBE_CHUNK):
            chunk = refs[i:i + cls._PROBE_CHUNK]
            marks = ",".join("(?,?)" for _ in chunk)
            params: list = []
            for ref in chunk:
                params += [ref.txhash.bytes_, ref.index]
            for ref_tx, ref_index, consumer in conn.execute(
                f"SELECT ref_tx, ref_index, consumer FROM {table}"
                f" WHERE (ref_tx, ref_index) IN (VALUES {marks})",
                params,
            ):
                out[StateRef(SecureHash(bytes(ref_tx)), ref_index)] = (
                    SecureHash(bytes(consumer))
                )
        return out

    def commit(
        self, states: list[StateRef], tx_id: SecureHash, requester: Party
    ) -> None:
        with self._db.transaction() as conn:
            prior_map = self._probe_in(conn, "notary_commits", states)
            conflict = {
                ref: prior
                for ref, prior in prior_map.items()
                if prior != tx_id
            }
            if conflict:
                raise UniquenessConflict(conflict)
            before = conn.total_changes
            conn.executemany(
                "INSERT OR IGNORE INTO notary_commits"
                " (ref_tx, ref_index, consumer, requester)"
                " VALUES (?,?,?,?)",
                [
                    (
                        ref.txhash.bytes_,
                        ref.index,
                        tx_id.bytes_,
                        requester.name,
                    )
                    for ref in states
                ],
            )
            self._count += conn.total_changes - before

    def commit_many(self, entries) -> list:
        """A whole notary flush in ONE DB transaction (the reference
        batches JDBC work per CommitRequest the same way): sequential
        first-wins semantics per entry, ONE batched `IN (...)` probe
        for every distinct ref in the flush (the persisted view is
        fixed for the whole transaction — only the staged view evolves
        entry to entry), and one executemany for all the surviving
        inserts."""
        from .notary import UniquenessConflict

        out = []
        rows = []
        with self._db.transaction() as conn:
            distinct: list = []
            seen: set = set()
            for states, _tx, _req in entries:
                for ref in states:
                    if ref not in seen:
                        seen.add(ref)
                        distinct.append(ref)
            persisted = self._probe_in(conn, "notary_commits", distinct)
            # staged view: refs committed by EARLIER entries in this
            # batch must conflict later ones exactly as sequential
            # commits would
            staged: dict = {}
            for states, tx_id, requester in entries:
                conflict = {}
                for ref in states:
                    prior = staged.get(ref)
                    if prior is None:
                        prior = persisted.get(ref)
                    if prior is not None and prior != tx_id:
                        conflict[ref] = prior
                if conflict:
                    out.append(UniquenessConflict(conflict))
                    continue
                for ref in states:
                    staged[ref] = tx_id
                    rows.append(
                        (
                            ref.txhash.bytes_,
                            ref.index,
                            tx_id.bytes_,
                            requester.name,
                        )
                    )
                out.append(None)
            if rows:
                before = conn.total_changes
                conn.executemany(
                    "INSERT OR IGNORE INTO notary_commits"
                    " (ref_tx, ref_index, consumer, requester)"
                    " VALUES (?,?,?,?)",
                    rows,
                )
                self._count += conn.total_changes - before
        return out

    @property
    def committed_count(self) -> int:
        return self._count


class ShardedPersistentUniquenessProvider(ShardedUniquenessProvider):
    """The sharded notary's committed-state registry on sqlite: the
    uniqueness namespace partitioned by state-ref prefix into one table
    per shard (`notary_commits_s<k>`), so every shard flush pipeline
    commits against ITS OWN table while cross-shard transactions take
    the provider's two-phase reserve→commit (notary.py
    ShardedUniquenessProvider — the reserve maps stay in memory: a
    crash releases every reservation, and a partially-written
    cross-shard commit completes on the client's idempotent same-tx
    re-commit, the retry invariant docs/serving-notary.md pins).

    Shard-count changes are a MIGRATION, not a reinterpretation: the
    layout's shard count persists in node_meta kv; on mismatch (first
    sharded boot over a legacy `notary_commits`, or an operator
    re-tuning the shard knob) every committed row is re-routed into
    the new partition tables inside one DB transaction — a ref checked
    against the wrong partition would silently miss the commit that
    conflicts it."""

    _META_SPACE = "notary_sharding"

    def __init__(
        self, db: NodeDatabase, n_shards: int = 1,
        record_decisions: bool = False,
    ):
        super().__init__(n_shards, record_decisions)
        self._db = db
        self._ensure_layout()
        # O(1) committed counts: one COUNT(*) per partition at boot,
        # maintained by actual-new-row insert deltas from there on
        self._counts = [
            self._db.query(
                f"SELECT COUNT(*) FROM {self._table(k)}"
            )[0][0]
            for k in range(self.n_shards)
        ]

    def _table(self, shard: int) -> str:
        return f"notary_commits_s{shard}"

    def _ensure_layout(self) -> None:
        meta = PersistentKVStore(self._db, self._META_SPACE)
        stored = meta.get(b"shards")
        stored_n = int.from_bytes(stored, "big") if stored else None
        ddl = "\n".join(
            f"CREATE TABLE IF NOT EXISTS {self._table(k)} ("
            " ref_tx BLOB NOT NULL, ref_index INTEGER NOT NULL,"
            " consumer BLOB NOT NULL, requester TEXT NOT NULL,"
            " PRIMARY KEY (ref_tx, ref_index));"
            for k in range(self.n_shards)
        )
        self._db.execute_script(ddl)
        if stored_n == self.n_shards:
            return
        # gather every committed row from the old layout: the legacy
        # single table (first sharded boot) plus any previous shard
        # tables (shard-count retune)
        rows: list[tuple] = []
        old_tables = ["notary_commits"]
        if stored_n:
            old_tables += [self._table(k) for k in range(stored_n)]
        with self._db.transaction() as conn:
            for table in old_tables:
                try:
                    rows.extend(
                        conn.execute(
                            f"SELECT ref_tx, ref_index, consumer,"
                            f" requester FROM {table}"
                        ).fetchall()
                    )
                except sqlite3.OperationalError:
                    continue   # table from a layout that never existed
            by_shard: dict[int, list[tuple]] = {}
            for (ref_tx, ref_index, consumer, requester) in rows:
                ref = StateRef(SecureHash(bytes(ref_tx)), ref_index)
                by_shard.setdefault(self.shard_of(ref), []).append(
                    (bytes(ref_tx), ref_index, bytes(consumer), requester)
                )
            for k in range(self.n_shards):
                conn.execute(f"DELETE FROM {self._table(k)}")
                batch = by_shard.get(k)
                if batch:
                    conn.executemany(
                        f"INSERT OR IGNORE INTO {self._table(k)}"
                        " (ref_tx, ref_index, consumer, requester)"
                        " VALUES (?,?,?,?)",
                        batch,
                    )
            # the legacy table's rows now live in the partitions; clear
            # it so nothing double-reads a stale copy
            conn.execute("DELETE FROM notary_commits")
            # the meta row commits WITH the moved rows: written outside
            # this transaction, a crash between the two would replay
            # the migration on next boot against the already-emptied
            # source tables and DELETE every committed row
            meta.put(b"shards", self.n_shards.to_bytes(4, "big"))

    # -- storage backend overrides (called under the partition cond) -------

    def _prior_consumer(self, shard: int, ref):
        row = self._db.query(
            f"SELECT consumer FROM {self._table(shard)}"
            " WHERE ref_tx=? AND ref_index=?",
            (ref.txhash.bytes_, ref.index),
        )
        return SecureHash(bytes(row[0][0])) if row else None

    def _prior_consumers_many(self, shard: int, refs) -> dict:
        with self._db.transaction() as conn:
            return PersistentUniquenessProvider._probe_in(
                conn, self._table(shard), refs
            )

    def _write_shard(self, shard: int, refs, tx_id, requester) -> None:
        self._write_rows(shard, [(ref, tx_id, requester) for ref in refs])

    def _write_rows(self, shard: int, rows) -> None:
        with self._db.transaction() as conn:
            before = conn.total_changes
            conn.executemany(
                f"INSERT OR IGNORE INTO {self._table(shard)}"
                " (ref_tx, ref_index, consumer, requester)"
                " VALUES (?,?,?,?)",
                [
                    (ref.txhash.bytes_, ref.index, tx_id.bytes_,
                     requester.name)
                    for ref, tx_id, requester in rows
                ],
            )
            self._counts[shard] += conn.total_changes - before

    @property
    def committed_count(self) -> int:
        return sum(self._counts)

    @property
    def committed(self) -> dict:
        """Merged StateRef -> consuming-tx view across the partition
        tables (tests, snapshots) — the base class reads its in-memory
        partitions, which this subclass leaves empty."""
        out: dict = {}
        for k in range(self.n_shards):
            for (ref_tx, ref_index, consumer) in self._db.query(
                f"SELECT ref_tx, ref_index, consumer FROM {self._table(k)}"
            ):
                out[StateRef(SecureHash(bytes(ref_tx)), ref_index)] = (
                    SecureHash(bytes(consumer))
                )
        return out

    def partition_depth(self, shard: int) -> int:
        return self._counts[shard]


class NotaryIntentJournal:
    """Durable intake WAL for the batching notary (round 9).

    Every ADMITTED notarisation request appends one intent row —
    transaction, requester, deadline — BEFORE it enters the pending
    queue, and the row is deleted when the request's future resolves
    (any answer counts: signature, conflict, shed, unavailable). The
    table lives in the node's WAL-mode sqlite database under the same
    fsync discipline as the fabric journals: appends are WAL writes
    (synchronous=NORMAL — no per-row fsync), resolution deletes are
    buffered in memory and group-committed once per flush tick.

    On boot, `BatchingNotaryService.replay_intents` re-enqueues every
    row still present — requests that were admitted but in flight when
    the process died — through the normal flush path. Replays of
    requests that had actually committed before the crash (the answer
    raced the buffered delete) are absorbed by the uniqueness
    provider's idempotent same-tx re-commit, so the replay can only
    ADD answers, never change one: in-flight-at-kill loss goes to
    zero and the fleet checker's loss bound tightens to an equality.
    """

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS notary_intents (
        seq       INTEGER PRIMARY KEY AUTOINCREMENT,
        tx_id     BLOB NOT NULL,
        data      BLOB NOT NULL,
        requester BLOB NOT NULL,
        deadline  INTEGER
    );
    """

    def __init__(self, db: NodeDatabase):
        self._db = db
        db.execute_script(self._SCHEMA)
        self._lock = locks.make_lock("NotaryIntentJournal._lock")
        self._resolved_buf: list[int] = []
        self.appended = 0
        self.resolved = 0
        self.replayed = 0
        # intents whose payload no longer decodes (a cordapp removed
        # between boots): kept in the table, surfaced here, never
        # allowed to crash the boot replay
        self.undecodable: list[int] = []

    def append(self, stx, requester: Party, deadline: Optional[int]) -> int:
        """Journal one admitted request; returns its intent seq. The
        row is on the WAL before this returns — from here a crash
        replays the request instead of losing it."""
        cur = self._db.execute(
            "INSERT INTO notary_intents (tx_id, data, requester, deadline)"
            " VALUES (?,?,?,?)",
            (
                stx.id.bytes_,
                ser.encode(stx),
                ser.encode(requester),
                deadline,
            ),
        )
        self.appended += 1
        return cur.lastrowid

    def mark_resolved(self, seq: int) -> None:
        """Buffer one intent's resolution (called from the answer
        path's done-callback — cheap, lock-only). The delete lands in
        the next `flush_resolved` group commit; a crash inside that
        window replays an already-answered request, which the
        uniqueness dedupe absorbs."""
        with self._lock:
            self._resolved_buf.append(seq)

    def flush_resolved(self) -> int:
        """Group-commit every buffered resolution in ONE transaction
        (the per-flush-tick fsync discipline). Returns rows cleared."""
        with self._lock:
            buf, self._resolved_buf = self._resolved_buf, []
        if not buf:
            return 0
        with self._db.transaction() as conn:
            conn.executemany(
                "DELETE FROM notary_intents WHERE seq=?",
                [(s,) for s in buf],
            )
        self.resolved += len(buf)
        return len(buf)

    def lose_unflushed_resolutions(self) -> int:
        """Crash simulation (testing/fleet.py kill_notary): a real
        process death loses the in-memory resolution buffer — those
        answered-but-undeleted intents must REPLAY on boot (and be
        absorbed by uniqueness dedupe). Drops the buffer; returns how
        many resolutions were lost."""
        with self._lock:
            n, self._resolved_buf = len(self._resolved_buf), []
        return n

    def unresolved(self) -> list:
        """Every intent not yet resolved, oldest first, decoded:
        [(seq, stx, requester_party, deadline)]. Buffered-but-unflushed
        resolutions are excluded — they ARE answered, only their
        delete is pending."""
        with self._lock:
            buffered = set(self._resolved_buf)
        out = []
        self.undecodable = []
        for seq, data, requester, deadline in self._db.query(
            "SELECT seq, data, requester, deadline FROM notary_intents"
            " ORDER BY seq"
        ):
            if seq in buffered:
                continue
            try:
                stx = ser.decode(bytes(data))
                who = ser.decode(bytes(requester))
            except Exception as e:   # noqa: BLE001 - surfaced, not fatal
                # a state/contract class registered when this intent
                # was journaled but absent now (cordapp change between
                # boots) must not crash the boot: keep the row, tell
                # the operator, replay the rest
                import logging

                self.undecodable.append(seq)
                logging.getLogger("corda_tpu.notary").warning(
                    "intent %d does not decode (%s: %s); kept in the "
                    "WAL, skipped by replay", seq, type(e).__name__, e,
                )
                continue
            out.append((seq, stx, who, deadline))
        return out

    @property
    def unresolved_count(self) -> int:
        with self._lock:
            buffered = len(self._resolved_buf)
        return (
            self._db.query("SELECT COUNT(*) FROM notary_intents")[0][0]
            - buffered
        )


class XShardCoordinatorJournal:
    """Presumed-abort decision WAL for the distributed cross-shard
    coordinator (node/distributed_uniqueness.py).

    Every cross-MEMBER transaction appends one intent row BEFORE its
    first ShardReserve leaves the coordinator; the commit decision is
    marked durably BEFORE any ShardCommit is sent (the 2PC commit
    point); the row is deleted once every owner acked its commit. The
    recovery contract is classic presumed abort:

      - row with the commit mark  -> the transaction COMMITTED: a
        restarted coordinator re-drives ShardCommit until every owner
        acks (participants apply idempotently);
      - row without the mark      -> ABORT: recovery sends ShardAbort
        to every involved owner and deletes the row — and a
        participant status query against a coordinator with no row
        gets "abort", which is what releases orphaned reservations.

    Same WAL-mode/no-per-row-fsync sqlite discipline as the intent and
    fabric journals (the node database is already in WAL mode)."""

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS xshard_intents (
        xid       INTEGER PRIMARY KEY AUTOINCREMENT,
        tx_id     BLOB NOT NULL,
        refs      BLOB NOT NULL,
        requester BLOB NOT NULL,
        committed INTEGER NOT NULL DEFAULT 0
    );
    """

    def __init__(self, db: NodeDatabase):
        self._db = db
        db.execute_script(self._SCHEMA)
        self.begun = 0
        self.decided = 0
        self.finished = 0

    def begin(self, tx_id, refs, requester: Party) -> int:
        """Journal one cross-member intent; returns its xid. The row is
        on the WAL before the first reserve leaves this process."""
        cur = self._db.execute(
            "INSERT INTO xshard_intents (tx_id, refs, requester, committed)"
            " VALUES (?,?,?,0)",
            (
                tx_id.bytes_,
                ser.encode(list(refs)),
                ser.encode(requester),
            ),
        )
        self.begun += 1
        return cur.lastrowid

    def decide_commit(self, xid: int) -> None:
        """Mark the commit decision durably — THE 2PC commit point:
        from here the transaction completes even across a coordinator
        kill (recovery re-drives). Aborts are never marked — a missing
        mark IS the abort decision (presumed abort)."""
        self._db.execute(
            "UPDATE xshard_intents SET committed=1 WHERE xid=?", (xid,)
        )
        self.decided += 1

    def finish(self, xid: int) -> None:
        """Every owner acked (commit) or the abort resolved: the row
        has no further recovery value."""
        self._db.execute("DELETE FROM xshard_intents WHERE xid=?", (xid,))
        self.finished += 1

    def is_committed(self, tx_id) -> bool:
        """Durable decision lookup for a status query against a tx this
        boot no longer holds in memory."""
        rows = self._db.query(
            "SELECT committed FROM xshard_intents WHERE tx_id=?",
            (tx_id.bytes_,),
        )
        return any(bool(c) for (c,) in rows)

    def unresolved(self) -> list:
        """Every intent still journaled, oldest first:
        [(xid, tx_id, refs, requester, committed)] — recovery's replay
        input. Rows that no longer decode are kept and skipped (the
        intent-journal stance: a cordapp change must not crash boot)."""
        out = []
        self.undecodable: list[int] = []
        for xid, tx_id, refs, requester, committed in self._db.query(
            "SELECT xid, tx_id, refs, requester, committed"
            " FROM xshard_intents ORDER BY xid"
        ):
            try:
                decoded_refs = [r for r in ser.decode(bytes(refs))]
                who = ser.decode(bytes(requester))
            except Exception as e:   # noqa: BLE001 - surfaced, not fatal
                import logging

                self.undecodable.append(xid)
                logging.getLogger("corda_tpu.notary").warning(
                    "xshard intent %d does not decode (%s: %s); kept, "
                    "skipped by recovery", xid, type(e).__name__, e,
                )
                continue
            out.append(
                (xid, SecureHash(bytes(tx_id)), decoded_refs, who,
                 bool(committed))
            )
        return out

    @property
    def unresolved_count(self) -> int:
        return self._db.query(
            "SELECT COUNT(*) FROM xshard_intents"
        )[0][0]


class XShardReservationJournal:
    """Durable participant-side reservations for the distributed
    provider: a row lands BEFORE the ShardReserveAck leaves this
    member and is deleted when the reservation resolves (commit or
    abort). A participant killed -9 mid-reserve reloads its held rows
    on boot and drives them to resolution through the normal orphan
    machinery (status query -> coordinator WAL answer) — without this,
    a restarted owner would forget a reservation whose coordinator
    already decided commit, and a rival could consume the refs in the
    gap: the silent double-spend window the design refuses."""

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS xshard_reservations (
        tx_id       BLOB NOT NULL,
        xid         INTEGER NOT NULL,
        coordinator TEXT NOT NULL,
        refs        BLOB NOT NULL,
        requester   BLOB NOT NULL,
        PRIMARY KEY (tx_id)
    );
    """

    def __init__(self, db: NodeDatabase):
        self._db = db
        db.execute_script(self._SCHEMA)

    def reserve(self, tx_id, xid: int, coordinator: str, refs, requester):
        self._db.execute(
            "INSERT OR REPLACE INTO xshard_reservations"
            " (tx_id, xid, coordinator, refs, requester) VALUES (?,?,?,?,?)",
            (
                tx_id.bytes_, xid, coordinator,
                ser.encode(list(refs)), ser.encode(requester),
            ),
        )

    def release(self, tx_id) -> None:
        self._db.execute(
            "DELETE FROM xshard_reservations WHERE tx_id=?", (tx_id.bytes_,)
        )

    def held(self) -> list:
        """[(tx_id, xid, coordinator, refs, requester)], the boot-time
        reload input. Undecodable rows are dropped WITH their table row
        — unlike an intent, a reservation that cannot be interpreted
        cannot be resolved either, and holding it forever would wedge
        its refs."""
        out = []
        for tx_id, xid, coordinator, refs, requester in self._db.query(
            "SELECT tx_id, xid, coordinator, refs, requester"
            " FROM xshard_reservations"
        ):
            tid = SecureHash(bytes(tx_id))
            try:
                out.append(
                    (tid, xid, coordinator,
                     [r for r in ser.decode(bytes(refs))],
                     ser.decode(bytes(requester)))
                )
            except Exception as e:   # noqa: BLE001 - surfaced, not fatal
                import logging

                logging.getLogger("corda_tpu.notary").warning(
                    "xshard reservation %s does not decode (%s: %s); "
                    "dropped", tid, type(e).__name__, e,
                )
                self.release(tid)
        return out

    @property
    def held_count(self) -> int:
        return self._db.query(
            "SELECT COUNT(*) FROM xshard_reservations"
        )[0][0]


class TxStoryIndex:
    """Sqlite spill for the transaction lifecycle ledger (round 13,
    utils/txstory.py): every recorded event also lands here, so a
    story the bounded in-memory ring evicted stays answerable at
    GET /tx/<id>.

    Same WAL discipline as the intent journal above: the table lives
    in the node's WAL-mode database (synchronous=NORMAL — no per-row
    fsync), appends buffer IN MEMORY on the emitting thread (one lock,
    no sqlite on the hot path) and group-commit once per pump tick via
    `flush()` — a crash loses at most one tick's worth of forensic
    events, never serving-path answers (the ledger is an observer
    plane; the intent WAL owns exactly-once)."""

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS tx_story_events (
        seq       INTEGER PRIMARY KEY AUTOINCREMENT,
        tx_id     TEXT NOT NULL,
        name      TEXT NOT NULL,
        at_micros INTEGER NOT NULL,
        mono_us   INTEGER NOT NULL,
        attrs     TEXT
    );
    CREATE INDEX IF NOT EXISTS tx_story_events_tx
        ON tx_story_events (tx_id, seq);
    """

    def __init__(self, db: NodeDatabase, max_rows: int = 200_000):
        self._db = db
        db.execute_script(self._SCHEMA)
        self._lock = locks.make_lock("TxStoryIndex._lock")
        self._buf: list[tuple] = []
        self._max_rows = max(1_000, max_rows)
        self.appended = 0
        self.flushes = 0

    def append(self, tx_id: str, name: str, at: int, mono: int, attrs) -> None:
        """Buffer one event (called under the TxStory lock — memory
        only, the sqlite write happens at flush())."""
        with self._lock:
            self._buf.append((tx_id, name, at, mono, attrs))

    def flush(self) -> int:
        """Group-commit the buffer in ONE transaction (the
        flush_resolved discipline); returns rows written. Retention is
        enforced here too: past `max_rows` the oldest rows fall off so
        the spill stays bounded like everything else in the plane."""
        import json as _json

        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return 0
        rows = [
            (
                tx_id, name, at, mono,
                _json.dumps(attrs) if attrs else None,
            )
            for tx_id, name, at, mono, attrs in buf
        ]
        with self._db.transaction() as conn:
            conn.executemany(
                "INSERT INTO tx_story_events"
                " (tx_id, name, at_micros, mono_us, attrs)"
                " VALUES (?,?,?,?,?)",
                rows,
            )
            conn.execute(
                "DELETE FROM tx_story_events WHERE seq <= ("
                "SELECT COALESCE(MAX(seq), 0) - ? FROM tx_story_events)",
                (self._max_rows,),
            )
        self.appended += len(rows)
        self.flushes += 1
        return len(rows)

    def events_for(self, tx_id: str) -> list[dict]:
        """One transaction's journaled events, oldest first, decoded to
        the same row shape the in-memory story exports."""
        import json as _json

        out = []
        for name, at, mono, attrs in self._db.query(
            "SELECT name, at_micros, mono_us, attrs FROM tx_story_events"
            " WHERE tx_id=? ORDER BY seq",
            (tx_id,),
        ):
            row = {"name": name, "at_micros": at, "mono_us": mono}
            if attrs:
                try:
                    row.update(_json.loads(attrs))
                except ValueError:
                    pass
            out.append(row)
        return out

    @property
    def row_count(self) -> int:
        return self._db.query(
            "SELECT COUNT(*) FROM tx_story_events"
        )[0][0]


class PersistentKeyManagementService(KeyManagementService):
    """PersistentKeyManagementService: fresh (anonymous) keys persist so
    confidential identities survive a node restart."""

    def __init__(self, db: NodeDatabase, *initial_keys: schemes.KeyPair, rng=None):
        super().__init__(*initial_keys, rng=rng)
        self._db = db
        # Key material is stored as raw columns, NOT via the canonical
        # codec: registering a PrivateKey serializer would silently make
        # private keys wire-encodable anywhere (checkpoints, session
        # payloads), defeating the encode-time guard in serialization.py.
        for (fp, scheme_id, pub, priv) in db.query(
            "SELECT fingerprint, scheme_id, public_key, private_key"
            " FROM our_keys"
        ):
            public = schemes.PublicKey(scheme_id, bytes(pub))
            self._keys[public] = schemes.PrivateKey(
                scheme_id, bytes(priv), public
            )
        for kp in initial_keys:
            self._persist(kp.public, kp.private)

    def _persist(self, public, private) -> None:
        self._db.execute(
            "INSERT OR IGNORE INTO our_keys"
            " (fingerprint, scheme_id, public_key, private_key)"
            " VALUES (?,?,?,?)",
            (public.fingerprint(), public.scheme_id, public.data, private.data),
        )

    def fresh_key(self, scheme_id: int = schemes.DEFAULT_SCHEME):
        public = super().fresh_key(scheme_id)
        self._persist(public, self._keys[public])
        return public

    def register_keypair(self, kp: schemes.KeyPair) -> None:
        super().register_keypair(kp)
        self._persist(kp.public, kp.private)


# ---------------------------------------------------------------------------
# vault


class PersistentVaultService(VaultService):
    """NodeVaultService over sqlite: the in-memory maps stay (hot path
    for flows/coin-selection), every delta also lands in `vault_states`
    with denormalised query columns for vault_query.py. Soft-locks are
    deliberately NOT persisted: in-flight spends die with the process
    and their flows resume from checkpoints, which re-lock."""

    def __init__(self, services):
        super().__init__(services)
        self._db: NodeDatabase = services.db
        self._ensured_schemas: set[str] = set()
        for row in self._db.query(
            "SELECT ref_tx, ref_index, state, status FROM vault_states"
        ):
            ref = StateRef(SecureHash(bytes(row[0])), row[1])
            ts = ser.decode(bytes(row[2]))
            (self._unconsumed if row[3] == 0 else self._consumed)[ref] = ts
        # after the state load: table creation backfills from the maps
        self._ensure_schema_tables()
    def _ensure_schema_tables(self) -> None:
        """Create every registered MappedSchema's table (memoized).
        Runs at open AND before queries: cordapps may register schemas
        after the vault opened, and a custom-column query over a table
        no state ever populated must return empty, not crash."""
        from .schemas import registered_schemas

        missing = [
            s
            for s in registered_schemas()
            if s.name not in self._ensured_schemas
        ]
        if not missing:
            return
        with self._db.transaction() as conn:
            for schema in missing:
                conn.execute(schema.ddl())
                # backfill: states recorded before this schema was
                # registered (cordapp installed onto an existing node)
                # must project too, or the SQL and in-memory vaults
                # answer CustomColumnCriteria differently
                for ref, ts in list(self._unconsumed.items()) + list(
                    self._consumed.items()
                ):
                    if not isinstance(ts.data, schema.applies_to):
                        continue
                    values = schema.row_values(ts.data)
                    marks = ",".join("?" * (2 + len(values)))
                    conn.execute(
                        f"INSERT OR REPLACE INTO {schema.table} VALUES"
                        f" ({marks})",
                        (ref.txhash.bytes_, ref.index, *values),
                    )
        # memoize only after the transaction committed: a rolled-back
        # CREATE TABLE must not leave the schema marked as ensured
        for schema in missing:
            self._ensured_schemas.add(schema.name)

    def query_by(self, criteria, paging=None, sorting=None):
        """Same criteria AST as the in-memory vault, compiled to SQL
        over vault_states (the HibernateQueryCriteriaParser role)."""
        from .vault_query import PageSpecification, Sort, run_sql

        self._ensure_schema_tables()
        return run_sql(
            self._db,
            criteria,
            paging or PageSpecification(),
            sorting or Sort(),
        )

    def _on_delta(self, update) -> None:
        """Persist one vault delta — O(tx size), not O(vault size). Runs
        before observers (base notify) so rows are on disk first; a
        failure here aborts the surrounding record transaction."""
        now = self._services.clock.now_micros()
        with self._db.transaction() as conn:
            for sar in update.consumed:
                conn.execute(
                    "UPDATE vault_states SET status=1, consumed_at=?"
                    " WHERE ref_tx=? AND ref_index=?",
                    (now, sar.ref.txhash.bytes_, sar.ref.index),
                )
            for sar in update.produced:
                # single source of truth for the schema projection:
                # vault_query.row_of — the in-memory query path uses the
                # same function, so both backends answer identically
                from .vault_query import UNCONSUMED, row_of

                row = row_of(sar, UNCONSUMED, now)
                ref, ts = sar.ref, sar.state
                conn.execute(
                    "INSERT OR REPLACE INTO vault_states"
                    " (ref_tx, ref_index, state, contract_tag, status,"
                    "  notary, quantity, token, issuer, linear_id,"
                    "  recorded_at, consumed_at)"
                    " VALUES (?,?,?,?,0,?,?,?,?,?,?,NULL)",
                    (
                        ref.txhash.bytes_,
                        ref.index,
                        ser.encode(ts),
                        row.contract_tag,
                        row.notary_name,
                        row.quantity,
                        row.product,
                        row.issuer_name,
                        row.linear_id,
                        now,
                    ),
                )
                for fp in row.participant_fps:
                    conn.execute(
                        "INSERT INTO vault_parts"
                        " (ref_tx, ref_index, fingerprint) VALUES (?,?,?)",
                        (ref.txhash.bytes_, ref.index, fp),
                    )
                # CorDapp-registered schema projections (the
                # HibernateObserver role, node/.../services/schema/):
                # one row per applying MappedSchema, in ITS table,
                # within the same delta transaction
                from .schemas import schemas_for

                for schema in schemas_for(ts.data):
                    if schema.name not in self._ensured_schemas:
                        conn.execute(schema.ddl())
                        self._ensured_schemas.add(schema.name)
                    values = schema.row_values(ts.data)
                    marks = ",".join("?" * (2 + len(values)))
                    conn.execute(
                        f"INSERT OR REPLACE INTO {schema.table} VALUES"
                        f" ({marks})",
                        (ref.txhash.bytes_, ref.index, *values),
                    )


# ---------------------------------------------------------------------------
# assembly


class PersistentServiceHub:
    """Builds a ServiceHub whose stores are all sqlite-backed — the
    Phase-3 node's storage plane (reference: AbstractNode.
    initialiseDatabasePersistence + makeServices, AbstractNode.kt:
    412-423,538). Constructed via `open()` so callers get the same
    ServiceHub type flows already talk to."""

    @staticmethod
    def open(
        path: str,
        my_info,
        identity,
        *initial_keys: schemes.KeyPair,
        network_map_cache=None,
        clock=None,
        batch_verifier=None,
        rng=None,
        db=None,
    ):
        """Pass `db` to share one NodeDatabase with other subsystems
        (the fabric journals live in the same file, so one sqlite tx
        can span a handler's effects and its message acks)."""
        from .services import ServiceHub

        if db is None:
            db = NodeDatabase(path)
        key_management = PersistentKeyManagementService(
            db, *initial_keys, rng=rng
        )
        return ServiceHub(
            my_info,
            key_management,
            identity,
            network_map_cache=network_map_cache,
            clock=clock,
            batch_verifier=batch_verifier,
            db=db,
            validated_transactions=PersistentTransactionStorage(db),
            attachments=PersistentAttachmentStorage(db),
            checkpoint_storage=PersistentCheckpointStorage(db),
            vault_factory=PersistentVaultService,
        )
