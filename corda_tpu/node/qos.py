"""SLO-aware admission control + adaptive batching for the serving path.

The north star is a notary that serves heavy traffic as fast as the
hardware allows — but "fast as the hardware allows" is a *throughput*
property, and under sustained overload throughput without admission
control is wasted: the TPU burns batch-verify work on requests whose
clients timed out long ago, and bulk traffic (backchain-resolution
floods) queues ahead of fresh notarisations. The reference makes the
latency-vs-throughput trade an operator concern (docs/
key-concepts-notaries.md part 4, docs/loadtest.md Disruption
reconciliation); inference servers make it a *control loop* (dynamic
batching against a latency SLO). This module is both, four cooperating
pieces behind one `NotaryQos` facade:

  deadline propagation — an optional absolute-microsecond deadline
      rides the fabric as a message header (messaging.Message.deadline,
      journaled across the TCP fabric next to the trace header) and
      through the ingest pipeline. An expired request is shed at the
      CHEAPEST point it is noticed — pre-decode at ingress, pre-stage
      at the flush — into a typed `shed` NotaryError instead of being
      silently verified-then-useless.

  priority lanes — two bounded ingest rings (`interactive` for fresh
      notarisation requests, `bulk` for resolution floods and other
      elastic traffic) with weighted-fair draining, so a bulk flood can
      delay bulk, never starve interactive. A per-client token bucket
      at the fabric seam caps any single sender's admission rate.

  adaptive batching — a feedback controller that retunes the notary's
      effective `max_wait_micros` / `max_batch` each flush from the
      observed queue depth and the admitted-request latency histogram's
      p99 (utils.metrics.Histogram.quantile) against a configured
      target: latency above target collapses the batching window
      multiplicatively (serve NOW); latency comfortably under target
      with full batches stretches it additively (deeper, faster
      flushes) — AIMD, the same shape TCP uses for the same reason.

  brownout — when the backlog keeps growing for K consecutive flushes
      despite the controller, degrade deliberately: level 1 sheds the
      bulk lane at admission, level 2 additionally sheds deadline-less
      interactive traffic. Every shed increments a `Qos.Shed.<reason>`
      counter and the controller state is exported as gauges — all of
      it served as JSON at `GET /qos` next to /metrics and /traces.

Everything here is host-side control plane: no consensus input, no
wire-format change beyond the optional header, and with `qos=None` the
notary's hot path pays a single attribute check.
"""

from __future__ import annotations

import threading
from ..utils import locks
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..utils.metrics import Histogram, MetricRegistry

# shed reasons — ONE vocabulary for counters, NotaryError.kind payloads
# and the /qos endpoint, so dashboards and clients never fork
SHED_KIND = "shed"                    # NotaryError.kind for every shed

SHED_EXPIRED_INGRESS = "ExpiredIngress"   # dead on arrival, pre-decode
SHED_EXPIRED_FLUSH = "ExpiredFlush"       # died queued, pre-stage
SHED_ADMISSION = "Admission"              # per-client token bucket
SHED_BROWNOUT_BULK = "BrownoutBulk"       # level >= 1: bulk lane dropped
SHED_BROWNOUT_NO_DEADLINE = "BrownoutNoDeadline"  # level >= 2

LANE_INTERACTIVE = "interactive"
LANE_BULK = "bulk"


class DeadlineExpired(Exception):
    """Pre-decode shed marker: the frame's deadline passed before any
    work was spent on it. Carried in IngestedTx.error so the wire path
    reports sheds per-slot exactly like malformed frames."""

    def __init__(self, deadline_micros: int, now_micros: int):
        self.deadline_micros = deadline_micros
        self.now_micros = now_micros
        super().__init__(
            f"deadline {deadline_micros} expired "
            f"{now_micros - deadline_micros} us before processing"
        )


def expired(deadline_micros: Optional[int], now_micros: int) -> bool:
    """The ONE expiry predicate (ingest, lanes, notary flush all call
    this): None never expires; expiry is inclusive so a deadline equal
    to `now` sheds — serving it would complete strictly after it."""
    return deadline_micros is not None and now_micros >= deadline_micros


@dataclass(frozen=True)
class QosPolicy:
    """Operator knobs (config.py maps node TOML onto this).

    `target_p99_micros` is THE SLO: the controller holds the admitted-
    request p99 completion latency at or under it. The wait/batch
    bounds fence the controller — it tunes freely inside them, so a
    misbehaving feedback signal can degrade batching efficiency but
    never violate the operator's latency floor/ceiling outright."""

    target_p99_micros: int = 50_000
    min_wait_micros: int = 0
    max_wait_micros: int = 20_000
    min_batch: int = 16
    max_batch: int = 512
    # weighted-fair drain: per round, up to `interactive_weight` frames
    # leave the interactive ring for every `bulk_weight` bulk frames
    interactive_weight: int = 4
    bulk_weight: int = 1
    lane_depth: int = 4096            # per-lane ring bound (frames)
    # per-client token bucket at the fabric seam; rate 0 disables
    admission_rate_per_sec: float = 0.0
    admission_burst: int = 256
    # brownout: raise the level after this many consecutive flushes of
    # growing backlog, drop it after the same count of shrinking ones
    brownout_after_flushes: int = 4
    # additive increase step for the batching window (micros per flush)
    wait_step_micros: int = 1_000


class TokenBucket:
    """Per-client admission gate at the fabric seam.

    Classic token bucket in integer microseconds: `rate` tokens/sec
    refill, `burst` capacity. One bucket per client name, created on
    first sight; clients the map never admitted cannot reach this layer
    (the fabric authenticated the sender), so the table is bounded by
    the peer set."""

    def __init__(self, rate_per_sec: float, burst: int):
        self.rate = float(rate_per_sec)
        self.burst = max(1, int(burst))
        self._lock = locks.make_lock("TokenBucket._lock")
        self._state: dict[str, tuple[float, int]] = {}  # name -> (tokens, t)

    def admit(self, client: str, now_micros: int, cost: int = 1) -> bool:
        if self.rate <= 0:
            return True
        with self._lock:
            tokens, t_prev = self._state.get(client, (float(self.burst), now_micros))
            tokens = min(
                float(self.burst),
                tokens + (now_micros - t_prev) * self.rate / 1e6,
            )
            if tokens < cost:
                self._state[client] = (tokens, now_micros)
                return False
            self._state[client] = (tokens - cost, now_micros)
            return True

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "rate_per_sec": self.rate,
                "burst": self.burst,
                "clients": len(self._state),
            }


class LaneRouter:
    """Two bounded rings in front of the ingest pipeline with weighted-
    fair draining — the fabric-seam half of the QoS plane.

    `offer(msg)` is ring-shaped so `MessagingService.add_ring` can
    route a topic straight into a lane: it admission-gates the sender,
    sheds expired / browned-out frames PRE-DECODE (a count and a falsy
    return of work, not a park — a shed frame must not be redelivered),
    and enqueues survivors on the lane the classifier picks. `drain`
    interleaves the lanes by weight so a resolution flood on `bulk` can
    never starve `interactive` notarisations; within a lane order stays
    FIFO. Returns True from offer for every consumed-or-shed frame —
    False ONLY when the target lane is full, which is the park-for-
    retry_parked backpressure signal the fabric already speaks."""

    def __init__(
        self,
        qos: "NotaryQos",
        classify: Optional[Callable[[Any], str]] = None,
    ):
        from .ingest import IngestRing

        self._qos = qos
        policy = qos.policy
        self.lanes = {
            LANE_INTERACTIVE: IngestRing(depth=policy.lane_depth),
            LANE_BULK: IngestRing(depth=policy.lane_depth),
        }
        self._classify = classify or _classify_by_topic
        self._weights = (
            max(1, policy.interactive_weight),
            max(1, policy.bulk_weight),
        )

    def offer(self, msg) -> bool:
        qos = self._qos
        now = qos.now_micros()
        deadline = getattr(msg, "deadline", None)
        if expired(deadline, now):
            qos.count_shed(SHED_EXPIRED_INGRESS)
            return True   # consumed: dead on arrival, zero decode spent
        sender = getattr(msg, "sender", "")
        if sender and not qos.admission.admit(sender, now):
            qos.count_shed(SHED_ADMISSION)
            return True
        lane = self._classify(msg)
        if lane not in self.lanes:
            lane = LANE_BULK
        level = qos.brownout_level
        if level >= 1 and lane == LANE_BULK:
            qos.count_shed(SHED_BROWNOUT_BULK)
            return True
        if level >= 2 and lane == LANE_INTERACTIVE and deadline is None:
            # deadline-less traffic cannot be meaningfully prioritised
            # under brownout: the client gave us no SLO to serve it by
            qos.count_shed(SHED_BROWNOUT_NO_DEADLINE)
            return True
        return self.lanes[lane].offer(msg)

    def drain(self, budget: Optional[int] = None) -> list:
        """Weighted-fair interleave across the lanes, up to `budget`
        frames (None = everything waiting). Expired frames are shed
        here too — they may have died *queued* — still pre-decode."""
        qos = self._qos
        w_i, w_b = self._weights
        inter, bulk = self.lanes[LANE_INTERACTIVE], self.lanes[LANE_BULK]
        out: list = []
        now = qos.now_micros()

        def take(ring, n: int) -> int:
            moved = 0
            while moved < n:
                item = ring.take(timeout=0)
                if item is None:
                    break
                if expired(getattr(item, "deadline", None), now):
                    qos.count_shed(SHED_EXPIRED_INGRESS)
                    continue   # shed, but the slot was drained: count it
                out.append(item)
                moved += 1
            return moved

        while budget is None or len(out) < budget:
            room = None if budget is None else budget - len(out)
            got = take(inter, w_i if room is None else min(w_i, room))
            room = None if budget is None else budget - len(out)
            got += take(bulk, w_b if room is None else min(w_b, room))
            if not got:
                break
        return out

    def depth(self) -> int:
        return sum(len(r) for r in self.lanes.values())

    def close(self) -> None:
        for r in self.lanes.values():
            r.close()


def _classify_by_topic(msg) -> str:
    """Default lane classifier: resolution/backchain topics are bulk,
    everything else (notarisation requests, session traffic) is
    interactive. Topic names are the only signal every fabric carries."""
    topic = getattr(msg, "topic", "") or ""
    if "resolve" in topic or "resolution" in topic or "bulk" in topic:
        return LANE_BULK
    return LANE_INTERACTIVE


class AdaptiveBatchController:
    """The feedback loop: (max_wait_micros, max_batch) retuned each
    flush to hold the admitted-request p99 at the target while keeping
    batch occupancy — the throughput lever (BASELINE.md round-3 sweep:
    the serving rate rides flush depth) — as high as the SLO allows.

    AIMD on the batching window: p99 above target halves the window
    (and sheds depth pressure immediately — latency breaches are paid
    by EVERY queued request, so the reaction is multiplicative); p99
    under half the target with full flushes stretches the window one
    additive step. `max_batch` follows the window: a collapsed window
    also caps depth so one flush can't blow the budget, a stretched one
    re-opens toward the policy ceiling."""

    def __init__(self, policy: QosPolicy, latency: Histogram):
        self.policy = policy
        self.latency = latency            # admitted micros, shared w/ /qos
        self.wait_micros = min(
            max(policy.min_wait_micros, policy.max_wait_micros // 4),
            policy.max_wait_micros,
        )
        self.batch = policy.max_batch
        self.flushes = 0
        self._last_p99 = 0.0

    def observe_flush(self, batch_size: int, backlog: int) -> None:
        """Called after every flush with the depth it served and the
        backlog it left behind (lanes + re-queued arrivals)."""
        pol = self.policy
        self.flushes += 1
        p99 = self.latency.quantile(0.99)
        self._last_p99 = p99
        if p99 > pol.target_p99_micros:
            self.wait_micros = max(pol.min_wait_micros, self.wait_micros // 2)
            self.batch = max(pol.min_batch, self.batch // 2)
        elif p99 < pol.target_p99_micros * 0.5:
            if batch_size >= self.batch or backlog == 0:
                self.wait_micros = min(
                    pol.max_wait_micros,
                    self.wait_micros + pol.wait_step_micros,
                )
            self.batch = min(pol.max_batch, max(self.batch * 2, pol.min_batch))

    def snapshot(self) -> dict:
        return {
            "wait_micros": self.wait_micros,
            "batch": self.batch,
            "target_p99_micros": self.policy.target_p99_micros,
            "admitted_p99_micros": round(self._last_p99, 1),
            "flushes_observed": self.flushes,
        }


class NotaryQos:
    """The facade the notary, node wiring, webserver and tests hold.

    Owns the admission gate, the lanes, the adaptive controller, the
    brownout state machine and every Qos.* metric — registered on the
    node's MetricRegistry so /metrics carries them, mirrored as JSON by
    `snapshot()` for GET /qos. `now_micros` is injected (the node
    clock) so simulated-time rigs drive the whole control plane
    deterministically."""

    def __init__(
        self,
        policy: Optional[QosPolicy] = None,
        clock=None,
        metrics: Optional[MetricRegistry] = None,
        classify: Optional[Callable[[Any], str]] = None,
    ):
        self.policy = policy or QosPolicy()
        self._clock = clock
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.admission = TokenBucket(
            self.policy.admission_rate_per_sec, self.policy.admission_burst
        )
        # admitted-request completion latency (micros, node clock):
        # the controller's feedback signal AND the /qos p99 readout
        self.admitted_latency = self.metrics.histogram(
            "Qos.AdmittedLatencyMicros"
        )
        self.controller = AdaptiveBatchController(
            self.policy, self.admitted_latency
        )
        self.lanes = LaneRouter(self, classify=classify)
        self._shed: dict[str, Any] = {}
        self.admitted = self.metrics.counter("Qos.Admitted")
        self.answered = self.metrics.counter("Qos.Answered")
        self._brownout_level = 0
        self._backlog_trend = 0       # +k growing / -k shrinking streak
        self._last_backlog = 0
        # every brownout level change, as (node-clock micros, new
        # level): the assertion surface chaos rigs reconcile against —
        # "brownout engaged during the spike and ONLY during the
        # spike" needs the transition times, not just the live level.
        # Bounded (an oscillation bug must not grow memory forever).
        self.brownout_transitions: list[tuple[int, int]] = []
        self._lock = locks.make_lock("NotaryQos._lock")
        # sharded commit plane (round 6): one AIMD controller + admitted
        # latency histogram PER SHARD, created by ensure_shards — a hot
        # shard (one partition's refs contended or deep) then collapses
        # ITS batching window without browning out its siblings. The
        # global controller stays as the unsharded/back-compat lane.
        self.shard_controllers: list[AdaptiveBatchController] = []
        self._shard_latency: list[Histogram] = []
        # distributed cross-shard commit latency lane (round 12):
        # created lazily on the first record_xshard so nodes without
        # the distributed plane register no extra series
        self._xshard_latency: Optional[Histogram] = None
        self.metrics.gauge(
            "Qos.Controller.WaitMicros", lambda: self.controller.wait_micros
        )
        self.metrics.gauge(
            "Qos.Controller.Batch", lambda: self.controller.batch
        )
        self.metrics.gauge("Qos.BrownoutLevel", lambda: self._brownout_level)
        self.metrics.gauge("Qos.LaneDepth", self.lanes.depth)
        # transaction lifecycle ledger (utils/txstory.py): wired by
        # node.py when the provenance plane is on — admit/shed events
        # with the tx id land in the per-tx story next to the counters
        self.txstory = None

    # -- lifecycle-ledger hooks (round 13) ------------------------------------

    def admit_tx(self, tx_id) -> None:
        """Count one admitted request AND stamp `qos.admit` on its
        lifecycle story (when both the ledger and a tx id are known —
        pre-decode lane traffic has no id yet and only counts).
        `tx_id` may be the raw SecureHash: the str conversion is paid
        only when a ledger is attached."""
        self.admitted.inc()
        if self.txstory is not None and tx_id is not None:
            self.txstory.record(str(tx_id), "qos.admit")

    def shed_tx(
        self,
        reason: str,
        tx_id=None,
        terminal: bool = False,
    ) -> None:
        """Count one shed AND stamp `qos.shed` (with the reason) on
        the transaction's story. `terminal=True` additionally CLOSES
        the story as shed — the pre-queue shed sites, where no answer
        future exists to carry the terminal; flush-time sheds resolve
        their future and terminal through it instead."""
        self.count_shed(reason)
        if self.txstory is not None and tx_id is not None:
            from ..utils.txstory import shed_reason as _canonical

            tid = str(tx_id)
            self.txstory.record(tid, "qos.shed", reason=reason)
            if terminal:
                self.txstory.close(tid, "shed", reason=_canonical(reason))

    # -- clock ---------------------------------------------------------------

    def now_micros(self) -> int:
        if self._clock is not None:
            return self._clock.now_micros()
        import time

        return time.time_ns() // 1_000

    # -- shed accounting -----------------------------------------------------

    def count_shed(self, reason: str) -> None:
        counter = self._shed.get(reason)
        if counter is None:
            with self._lock:
                counter = self._shed.get(reason)
                if counter is None:
                    counter = self.metrics.counter("Qos.Shed." + reason)
                    self._shed[reason] = counter
        counter.inc()

    @property
    def shed_total(self) -> int:
        with self._lock:
            counters = list(self._shed.values())
        return sum(c.count for c in counters)

    # -- per-shard lanes (round 6) -------------------------------------------

    def ensure_shards(self, n: int) -> None:
        """Create the per-shard controller lanes (idempotent; called by
        the sharded BatchingNotaryService with its shard count). Each
        lane = its own AIMD controller over its own
        Qos.Shard<k>.AdmittedLatencyMicros histogram, fenced by the SAME
        policy — so per-shard tuning can never escape the operator's
        latency floor/ceiling either."""
        while len(self.shard_controllers) < n:
            k = len(self.shard_controllers)
            hist = self.metrics.histogram(
                f"Qos.Shard{k}.AdmittedLatencyMicros"
            )
            self._shard_latency.append(hist)
            self.shard_controllers.append(
                AdaptiveBatchController(self.policy, hist)
            )
            self.metrics.gauge(
                f"Qos.Shard{k}.Batch",
                (lambda c=self.shard_controllers[k]: c.batch),
            )
            self.metrics.gauge(
                f"Qos.Shard{k}.WaitMicros",
                (lambda c=self.shard_controllers[k]: c.wait_micros),
            )

    def controller_for(self, shard: Optional[int]):
        """The AIMD lane steering one shard's flush (the global
        controller when unsharded or for an unknown shard id)."""
        if shard is None or shard >= len(self.shard_controllers):
            return self.controller
        return self.shard_controllers[shard]

    def observe_shard_flush(
        self, shard: int, batch_size: int, backlog: int
    ) -> None:
        """Per-shard flush feedback: retunes THAT shard's lane only.
        Brownout deliberately does not walk here — one hot shard must
        not brown out the whole node; the notary tick feeds the
        aggregate backlog to observe_backlog once per pump round."""
        self.controller_for(shard).observe_flush(batch_size, backlog)

    # -- flush feedback ------------------------------------------------------

    def record_admitted(
        self, latency_micros: int, shard: Optional[int] = None
    ) -> None:
        self.answered.inc()
        self.admitted_latency.update(max(0, latency_micros))
        if shard is not None and shard < len(self._shard_latency):
            self._shard_latency[shard].update(max(0, latency_micros))

    # -- cross-shard lane (round 12) -----------------------------------------

    def record_xshard(self, latency_micros: int) -> None:
        """Resolution latency of one DISTRIBUTED cross-shard commit
        (reserve sent -> decided/aborted, node-clock micros). Its own
        lane, not mixed into the admitted histogram: a cross-member
        round trip is structurally slower than a local flush commit,
        and folding it in would stretch the adaptive controller's p99
        signal — the operator reads the two latencies side by side at
        GET /qos instead."""
        hist = self._xshard_latency
        if hist is None:
            with self._lock:
                hist = self._xshard_latency
                if hist is None:
                    hist = self.metrics.histogram("Qos.XShardLatencyMicros")
                    self._xshard_latency = hist
        hist.update(max(0, latency_micros))

    def xshard_snapshot(self) -> dict:
        hist = self._xshard_latency
        if hist is None:
            return {"count": 0}
        return {
            "count": hist.count,
            "p50_micros": hist.quantile(0.5),
            "p99_micros": hist.quantile(0.99),
        }

    def observe_flush(self, batch_size: int, backlog: int) -> None:
        """One call per notary flush: feeds the controller and walks
        the brownout state machine on the backlog trend."""
        self.controller.observe_flush(batch_size, backlog)
        self.observe_backlog(backlog)

    def observe_backlog(self, backlog: int) -> None:
        """Walk the brownout state machine on the (aggregate) backlog
        trend — split from observe_flush so the sharded notary can feed
        per-shard controller observations separately from the ONE
        node-level backlog observation per pump round."""
        pol = self.policy
        with self._lock:
            # "growing" means NOT draining: a backlog holding level or
            # rising despite the flush. A shrinking backlog — however
            # large — is recovery and must step the level DOWN, not up
            # (a single deep burst draining over several flushes is
            # not sustained overload).
            if backlog > 0 and backlog >= self._last_backlog:
                self._backlog_trend = max(1, self._backlog_trend + 1)
            else:
                self._backlog_trend = min(-1, self._backlog_trend - 1)
            self._last_backlog = backlog
            if self._backlog_trend >= pol.brownout_after_flushes:
                if self._brownout_level < 2:
                    self._brownout_level += 1
                    self._note_transition()
                self._backlog_trend = 0
            elif self._backlog_trend <= -pol.brownout_after_flushes:
                if self._brownout_level > 0:
                    self._brownout_level -= 1
                    self._note_transition()
                self._backlog_trend = 0

    def _note_transition(self) -> None:
        """Record one brownout level change (caller holds the lock)."""
        self.brownout_transitions.append(
            (self.now_micros(), self._brownout_level)
        )
        if len(self.brownout_transitions) > 256:
            del self.brownout_transitions[:128]

    @property
    def brownout_level(self) -> int:
        return self._brownout_level

    # -- export --------------------------------------------------------------

    def snapshot(self) -> dict:
        """The GET /qos payload: JSON-safe, one read of live state."""
        lanes = {
            name: {"depth": len(ring), "high_water": ring.high_water}
            for name, ring in self.lanes.lanes.items()
        }
        with self._lock:
            # copy under the lock count_shed inserts under: the
            # webserver thread must not iterate a dict the pump thread
            # is growing mid-overload (the exact moment /qos matters)
            shed = dict(self._shed)
        shard_lanes = [
            c.snapshot() for c in list(self.shard_controllers)
        ]
        return {
            "enabled": True,
            "controller": self.controller.snapshot(),
            # per-shard AIMD lanes (round 6): one entry per commit-plane
            # shard, in shard order — empty when unsharded
            "shards": shard_lanes,
            "brownout": {
                "level": self._brownout_level,
                "trend": self._backlog_trend,
                "after_flushes": self.policy.brownout_after_flushes,
                # (at_micros, level) history — the chaos-rig assertion
                # surface (tail only; the live level is above)
                "transitions": [
                    list(t) for t in self.brownout_transitions[-16:]
                ],
            },
            "shed": {
                reason: counter.count
                for reason, counter in sorted(shed.items())
            },
            "shed_total": self.shed_total,
            # distributed cross-shard commit latency (round 12): its
            # own lane next to the admitted p99 — count 0 when the
            # node runs no distributed plane
            "xshard": self.xshard_snapshot(),
            "admitted": self.admitted.count,
            "answered": self.answered.count,
            "admission": self.admission.snapshot(),
            "lanes": lanes,
            "policy": {
                "target_p99_micros": self.policy.target_p99_micros,
                "max_wait_micros": self.policy.max_wait_micros,
                "max_batch": self.policy.max_batch,
                "interactive_weight": self.policy.interactive_weight,
                "bulk_weight": self.policy.bulk_weight,
            },
        }
