"""Raft consensus over the message fabric + the replicated uniqueness map.

Reference: `RaftUniquenessProvider` (node/.../transactions/
RaftUniquenessProvider.kt:41) — a Copycat-replicated
`DistributedImmutableMap` (DistributedImmutableMap.kt) of
stateRef→consumingTx, with the Raft transport running over its own
Netty mesh (`:72-110`). The TPU build runs Raft over the same DCN
fabric the rest of the node uses (one transport, SURVEY §2.5), and the
notary awaits commits through the FlowFuture seam so the service flow
suspends while the cluster replicates.

The algorithm is standard Raft (election §5.2, replication §5.3, the
current-term commit rule §5.4.2 — Ongaro & Ousterhout, "In Search of an
Understandable Consensus Algorithm", public spec): persistent
(term, votedFor, log) in the node database, randomized election
timeouts driven by explicit `tick()` calls from the node's pump loop —
deterministic under the Ring-3 manual pump, wall-clock on a real node.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..core import serialization as ser
from ..utils import tracing
from ..flows.api import FlowFuture
from .messaging import Message, MessagingService

TOPIC_RAFT = "raft"

# consensus-phase vocabulary: per-member spans (`raft.<phase>`, each
# carrying member= and at= attributes) and always-on Raft.Phase.*
# timers. propose = submission handling on the origin member; append =
# AppendEntries processing on any member; quorum = leader-side wait
# from local append to commit-index advance; commit = commit-known to
# entry-resolved on each member (apply nested inside it); apply =
# apply_fn alone; view_change / catch_up are root spans over the
# protocol's repair arcs.
RAFT_PHASES = (
    "propose", "append", "quorum", "commit", "apply",
    "view_change", "catch_up",
)
# bound on the per-entry trace/timing tables: a trace context whose
# entry never commits (deposed leader, lost quorum) must not leak
_TRACE_TABLE_CAP = 4096


def _story_consensus_commit(story, command, index, member, term) -> None:
    """Stamp `consensus.commit` on a just-applied uniqueness command's
    lifecycle story (utils/txstory.py). Only the notary's `["commit",
    tx_id_bytes, refs]` command shape carries a tx id; anything else
    (noops, foreign state machines) is silently skipped — the ledger
    is an observer, never a failure source."""
    try:
        if not isinstance(command, (list, tuple)) or len(command) < 2:
            return
        if command[0] == "commit":
            # notary cluster shape: tx id rides as raw hash bytes
            from ..crypto.hashes import SecureHash

            story.consensus_commit(
                str(SecureHash(bytes(command[1]))),
                index=index, member=member, term=term,
            )
        elif command[0] == "xcommit":
            # partition-group replication shape (distributed
            # uniqueness): tx id rides as the SecureHash itself
            story.consensus_commit(
                str(command[1]), index=index, member=member, term=term,
            )
    except Exception:   # noqa: BLE001 - observer plane, never fatal
        pass


class RaftUnavailable(Exception):
    """No leader reachable within the command deadline (the caller —
    e.g. a notary client — retries, NotaryFlow.kt:159-162)."""


ser.register_custom(
    RaftUnavailable,
    "RaftUnavailable",
    lambda e: str(e),
    lambda v: RaftUnavailable(v),
)


# -- wire messages (all peer-to-peer on the cluster topic) -------------------


@dataclass(frozen=True)
class RequestVote:
    term: int
    candidate: str
    last_log_index: int
    last_log_term: int


@dataclass(frozen=True)
class VoteReply:
    term: int
    granted: bool
    voter: str


@dataclass(frozen=True)
class AppendEntries:
    term: int
    leader: str
    prev_log_index: int
    prev_log_term: int
    # (term, command) pairs; a TRACED entry ships as a
    # (term, command, wire_trace_header) triple so a 64-entry batch
    # attributes each entry to ITS OWN client trace (one message-level
    # header could not say which entry it belongs to). The header is
    # observability metadata: receivers strip it before the log append,
    # so replication state is identical traced or not.
    entries: tuple
    leader_commit: int


@dataclass(frozen=True)
class AppendReply:
    term: int
    follower: str
    success: bool
    match_index: int


@dataclass(frozen=True)
class InstallSnapshot:
    """Leader→lagging-follower state transfer (Raft §7): the follower's
    next entry was compacted away, so ship the state machine snapshot
    instead of replaying from genesis. Copycat streams snapshots the
    same way for the reference's RaftUniquenessProvider
    (RaftUniquenessProvider.kt:41 delegates storage/compaction to
    Copycat).

    Chunked per §7 (offset/done): `data` is a slice of the CTS-encoded
    snapshot at `offset`; a real uniqueness map (millions of
    StateRefs) encodes far past the fabric's frame limit, so one
    message cannot carry it. The transfer is follower-paced: each
    chunk is acked with a SnapshotAck naming the next offset wanted,
    and the leader answers statelessly from its cached blob — a lost
    chunk heals when the heartbeat re-sends chunk 0 and the follower
    re-acks its true position."""

    term: int
    leader: str
    last_included_index: int
    last_included_term: int
    offset: int             # byte position of `data` in the blob
    data: bytes             # one chunk of ser.encode(snapshot state)
    done: bool              # True on the final chunk
    total: int              # full blob size (progress/validation)


@dataclass(frozen=True)
class SnapshotAck:
    """Follower→leader: got chunks up to `next_offset`; send more."""

    term: int
    follower: str
    last_included_index: int
    next_offset: int


@dataclass(frozen=True)
class ClientCommand:
    """A command forwarded to the (believed) leader by any member."""

    cmd_id: int
    origin: str
    command: Any


@dataclass(frozen=True)
class ClientResult:
    cmd_id: int
    ok: bool
    value: Any


for _cls in (
    RequestVote, VoteReply, AppendEntries, AppendReply,
    InstallSnapshot, SnapshotAck, ClientCommand, ClientResult,
):
    ser.serializable(_cls)


# -- configuration -----------------------------------------------------------


@dataclass(frozen=True)
class RaftConfig:
    heartbeat_micros: int = 50_000
    election_min_micros: int = 150_000
    election_max_micros: int = 300_000
    command_deadline_micros: int = 10_000_000
    # take a state-machine snapshot and truncate the log every N
    # applied entries (0 disables; requires snapshot_fn/restore_fn)
    snapshot_interval: int = 1024
    # InstallSnapshot chunk size, bytes — comfortably under the
    # fabric's 64 MiB frame limit with CTS overhead to spare
    snapshot_chunk_bytes: int = 1 << 20


_RAFT_SCHEMA = """
CREATE TABLE IF NOT EXISTS raft_log (
    cluster TEXT NOT NULL,
    idx     INTEGER NOT NULL,
    term    INTEGER NOT NULL,
    command BLOB NOT NULL,
    PRIMARY KEY (cluster, idx)
);
CREATE TABLE IF NOT EXISTS raft_meta (
    cluster  TEXT PRIMARY KEY,
    term     INTEGER NOT NULL,
    voted_for TEXT
);
CREATE TABLE IF NOT EXISTS raft_snapshot (
    cluster TEXT PRIMARY KEY,
    idx     INTEGER NOT NULL,
    term    INTEGER NOT NULL,
    state   BLOB NOT NULL
);
"""

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftNode:
    """One cluster member. The log is 1-indexed; `apply_fn(command)` is
    the replicated state machine, invoked exactly once per committed
    entry in log order on every member (DistributedImmutableMap's
    role). `submit()` returns a FlowFuture resolved with apply_fn's
    return value once the entry commits."""

    def __init__(
        self,
        name: str,
        peers: list[str],                  # all members, self included
        messaging: MessagingService,
        apply_fn: Callable[[Any], Any],
        clock,
        cluster: str = "notary",
        db=None,
        rng=None,
        config: RaftConfig = RaftConfig(),
        snapshot_fn: Optional[Callable[[], Any]] = None,
        restore_fn: Optional[Callable[[Any], None]] = None,
        metrics=None,
        tracer=None,
        txstory=None,
    ):
        """`metrics`: an optional MetricRegistry — Raft.Phase.* timers
        over every consensus phase plus quorum-lag gauges land on it
        (always-on, the Notary.FlushPhase.* discipline). `tracer`: an
        optional utils/tracing.Tracer — commands submitted with a
        trace context get per-member `raft.<phase>` spans stamped into
        it, and traced protocol frames feed the tracer's ClockSync so
        cross-node assembly can order spans honestly. `txstory`: an
        optional utils/txstory.TxStory — every applied uniqueness
        commit command stamps a `consensus.commit` lifecycle event
        (log index + member) on its transaction's story, on EVERY
        member that applies it. All default to None: the bare protocol
        stays dependency- and overhead-free."""
        import random as _random

        assert name in peers, "peers must include this member"
        self.name = name
        self.peers = list(peers)
        self.others = [p for p in peers if p != name]
        self.messaging = messaging
        self.apply_fn = apply_fn
        self.snapshot_fn = snapshot_fn
        self.restore_fn = restore_fn
        self.clock = clock
        self.cluster = cluster
        self.config = config
        self.rng = rng or _random.Random()
        self._db = db
        if db is not None:
            db.execute_script(_RAFT_SCHEMA)

        # persistent state (reloaded from db). The log is logically
        # 1-indexed but physically holds only entries ABOVE the last
        # snapshot: self.log[k] is entry snap_index+1+k. A snapshot
        # (state-machine dump at snap_index) replaces the compacted
        # prefix — restart restores it and replays only the tail,
        # bounding both disk and restart time (Copycat's storage
        # semantics for the reference, RaftUniquenessProvider.kt:41).
        self.term = 0
        self.voted_for: Optional[str] = None
        self.snap_index = 0
        self.snap_term = 0
        self._snap_state: Any = None   # last snapshot payload (for IS)
        # leader: cached ser.encode(_snap_state), keyed by snap_index,
        # answering SnapshotAck chunk requests without re-encoding
        self._snap_blob: Optional[bytes] = None
        self._snap_blob_index = -1
        # leader: peer -> (snap_index, last_chunk_sent_micros) — gates
        # heartbeat re-initiation so one transfer runs per follower
        self._snap_inflight: dict[str, tuple] = {}
        # follower: in-progress chunked transfer —
        # (leader, last_included_index, last_included_term, buffer)
        self._snap_incoming: Optional[tuple] = None
        self.log: list[tuple[int, Any]] = []   # [(term, command)]
        self._load()

        # volatile
        self.role = FOLLOWER
        self.leader: Optional[str] = None
        self.commit_index = self.snap_index
        self.last_applied = self.snap_index
        self.next_index: dict[str, int] = {}
        self.match_index: dict[str, int] = {}
        self.votes: set[str] = set()
        # leader: log index -> (term, future, deadline);
        # everywhere: cmd_id -> (future, deadline)
        self._index_futures: dict[int, tuple[int, FlowFuture, int]] = {}
        self._client_futures: dict[int, tuple[FlowFuture, int]] = {}
        # leader: log index -> (origin, cmd_id, term) for forwarded cmds
        self._forwarded: dict[int, tuple[str, int, int]] = {}
        # unresolved client commands awaiting a (possibly future) leader;
        # re-flushed whenever leadership changes — commands MUST be
        # idempotent (the uniqueness map is), because a leader change
        # can replicate a command twice
        self._pending_client: dict[int, Any] = {}
        self._flushed_to: Optional[str] = None
        self._next_cmd = 0
        self._last_heartbeat_sent = 0
        self._election_deadline = self._fresh_election_deadline()
        self.applied_count = 0

        # -- observability (PR 11): phase timers, lag gauges, spans ----
        self.metrics = metrics
        self.tracer = tracer
        self.txstory = txstory
        self._phase_timers: dict[str, Any] = {}
        if metrics is not None:
            for phase in RAFT_PHASES:
                self._phase_timers[phase] = metrics.timer(
                    "Raft.Phase." + phase.title().replace("_", "")
                )
            metrics.gauge(
                "Raft.QuorumLagEntries",
                lambda: self.last_log_index - self.commit_index,
            )
            metrics.gauge(
                "Raft.ApplyLagEntries",
                lambda: self.commit_index - self.last_applied,
            )
            for peer in self.others:
                metrics.gauge(
                    f"Raft.PeerLag.{peer}",
                    lambda p=peer: (
                        self.last_log_index - self.match_index.get(p, 0)
                        if self.role == LEADER else 0
                    ),
                )
        # log idx -> propagated wire trace header (the client's trace);
        # log idx -> perf_counter seconds at local append (phase t0)
        self._entry_trace: dict[int, tuple] = {}
        self._entry_t0: dict[int, float] = {}
        # cmd_id -> wire trace header for commands parked/forwarded
        self._cmd_trace: dict[int, tuple] = {}
        # open repair-arc spans (root traces, not client-joined)
        self._vc_span = None
        self._vc_t0 = 0.0
        self._catchup_span = None
        self._catchup_t0 = 0.0

        self.topic = f"{TOPIC_RAFT}.{cluster}"
        messaging.add_handler(self.topic, self._on_message)
        self.stopped = False

        # Restart semantics: the snapshot (restored in _load) covers
        # everything up to snap_index; commit_index above that is
        # volatile and rediscovered from the leader, so the tail is
        # re-applied lazily as commit_index advances past last_applied.
        # apply_fn must be deterministic AND rebuildable (the
        # uniqueness provider's map is; reference: Copycat
        # snapshot+replay).

    # -- persistence ---------------------------------------------------------

    def _load(self) -> None:
        if self._db is None:
            return
        rows = self._db.query(
            "SELECT term, voted_for FROM raft_meta WHERE cluster=?",
            (self.cluster,),
        )
        if rows:
            self.term, self.voted_for = rows[0][0], rows[0][1]
        snap = self._db.query(
            "SELECT idx, term, state FROM raft_snapshot WHERE cluster=?",
            (self.cluster,),
        )
        if snap:
            self.snap_index, self.snap_term = snap[0][0], snap[0][1]
            self._snap_state = ser.decode(bytes(snap[0][2]))
            if self.restore_fn is None:
                raise RuntimeError(
                    "raft snapshot on disk but no restore_fn configured"
                )
            self.restore_fn(self._snap_state)
        for idx, term, blob in self._db.query(
            "SELECT idx, term, command FROM raft_log WHERE cluster=?"
            " AND idx>? ORDER BY idx",
            (self.cluster, self.snap_index),
        ):
            assert idx == self.snap_index + len(self.log) + 1, (
                "raft log has holes"
            )
            self.log.append((term, ser.decode(bytes(blob))))

    def _persist_meta(self) -> None:
        if self._db is None:
            return
        self._db.execute(
            "INSERT OR REPLACE INTO raft_meta (cluster, term, voted_for)"
            " VALUES (?,?,?)",
            (self.cluster, self.term, self.voted_for),
        )

    def _persist_append(self, start_idx: int) -> None:
        """Persist log[start_idx-1:] (1-indexed start)."""
        if self._db is None:
            return
        with self._db.transaction():
            self._db.execute(
                "DELETE FROM raft_log WHERE cluster=? AND idx>=?",
                (self.cluster, start_idx),
            )
            for i in range(start_idx, self.last_log_index + 1):
                term, command = self._entry(i)
                self._db.execute(
                    "INSERT INTO raft_log (cluster, idx, term, command)"
                    " VALUES (?,?,?,?)",
                    (self.cluster, i, term, ser.encode(command)),
                )

    def _persist_snapshot(self) -> None:
        if self._db is None:
            return
        with self._db.transaction():
            self._db.execute(
                "INSERT OR REPLACE INTO raft_snapshot"
                " (cluster, idx, term, state) VALUES (?,?,?,?)",
                (
                    self.cluster, self.snap_index, self.snap_term,
                    ser.encode(self._snap_state),
                ),
            )
            self._db.execute(
                "DELETE FROM raft_log WHERE cluster=? AND idx<=?",
                (self.cluster, self.snap_index),
            )

    # -- log helpers ---------------------------------------------------------

    @property
    def last_log_index(self) -> int:
        return self.snap_index + len(self.log)

    @property
    def last_log_term(self) -> int:
        return self.log[-1][0] if self.log else self.snap_term

    def _entry(self, idx: int) -> tuple[int, Any]:
        """Entry at 1-indexed log position `idx` (> snap_index)."""
        return self.log[idx - self.snap_index - 1]

    def _term_at(self, idx: int) -> int:
        if idx == self.snap_index:
            return self.snap_term
        if self.snap_index < idx <= self.last_log_index:
            return self._entry(idx)[0]
        return 0

    # -- consensus-phase observability ---------------------------------------

    def _tracing(self) -> bool:
        return self.tracer is not None and self.tracer.enabled

    def _observing(self) -> bool:
        """True when per-entry phase timing is worth collecting at all
        (a timer or a tracer will consume it)."""
        return self.metrics is not None or self._tracing()

    def _stamp(self, phase: str, hdr, t0: float, t1: Optional[float] = None,
               **attrs) -> None:
        """One consensus phase interval: always into the Raft.Phase.*
        timer (when metrics are wired), and — when the entry carries a
        trace context and tracing is on — as a completed
        `raft.<phase>` span joined to the client's trace, carrying
        member= (which replica) and at= (node-clock micros at phase
        end, the simulated-time-honest ordering key `phase_summary`
        ranks members by)."""
        t1 = time.perf_counter() if t1 is None else t1
        timer = self._phase_timers.get(phase)
        if timer is not None:
            timer.update(t1 - t0)
        if hdr is not None and self._tracing():
            self.tracer.span_at(
                "raft." + phase, hdr, t0, t1,
                member=self.name, at=self.clock.now_micros(), **attrs,
            )

    def _bind_trace(self, idx: int, hdr) -> None:
        if hdr is None:
            return
        if len(self._entry_trace) >= _TRACE_TABLE_CAP:
            self._entry_trace.pop(next(iter(self._entry_trace)))
        self._entry_trace[idx] = tuple(hdr)

    def _bind_t0(self, idx: int) -> None:
        if not self._observing():
            return
        if len(self._entry_t0) >= _TRACE_TABLE_CAP:
            self._entry_t0.pop(next(iter(self._entry_t0)))
        self._entry_t0[idx] = time.perf_counter()

    def _open_repair_span(self, name: str):
        if not self._tracing():
            return None
        return self.tracer.start_trace(
            name, member=self.name, at=self.clock.now_micros()
        )

    def _close_vc_span(self, outcome: str) -> None:
        if self._vc_span is not None:
            self._vc_span.set_attribute("outcome", outcome)
            self._vc_span.end()
            self._vc_span = None
        if self._vc_t0:
            timer = self._phase_timers.get("view_change")
            if timer is not None:
                timer.update(time.perf_counter() - self._vc_t0)
            self._vc_t0 = 0.0

    def _close_catchup_span(self, outcome: str) -> None:
        if self._catchup_span is not None:
            self._catchup_span.set_attribute("outcome", outcome)
            self._catchup_span.end()
            self._catchup_span = None
        if self._catchup_t0:
            timer = self._phase_timers.get("catch_up")
            if timer is not None:
                timer.update(time.perf_counter() - self._catchup_t0)
            self._catchup_t0 = 0.0

    # -- timers --------------------------------------------------------------

    def _fresh_election_deadline(self) -> int:
        span = (
            self.config.election_max_micros - self.config.election_min_micros
        )
        return (
            self.clock.now_micros()
            + self.config.election_min_micros
            + self.rng.randrange(span + 1)
        )

    def tick(self) -> int:
        """Drive timers; returns number of messages sent (so pump loops
        can detect quiescence)."""
        if self.stopped:
            return 0
        now = self.clock.now_micros()
        sent = 0
        if self.role == LEADER:
            if now - self._last_heartbeat_sent >= self.config.heartbeat_micros:
                sent += self._broadcast_append()
        elif now >= self._election_deadline:
            sent += self._start_election()
        sent += self._expire_client_futures(now)
        return sent

    def _expire_client_futures(self, now: int) -> int:
        for cmd_id, (fut, deadline) in list(self._client_futures.items()):
            if now >= deadline:
                del self._client_futures[cmd_id]
                self._pending_client.pop(cmd_id, None)
                fut.set_exception(
                    RaftUnavailable(
                        f"no commit within deadline (leader={self.leader})"
                    )
                )
        for idx, (term, fut, deadline) in list(self._index_futures.items()):
            if now >= deadline:
                del self._index_futures[idx]
                fut.set_exception(
                    RaftUnavailable("deposed before entry committed")
                )
        return 0

    # -- elections -----------------------------------------------------------

    def _start_election(self) -> int:
        self.term += 1
        self.role = CANDIDATE
        self.voted_for = self.name
        self.leader = None
        self.votes = {self.name}
        if self._vc_span is None:
            # a repair arc, not client work: its own root trace, so
            # the flight recorder answers "was there an election while
            # that commit was slow" — ends on leadership or yield
            self._vc_span = self._open_repair_span("raft.view_change")
            self._vc_t0 = time.perf_counter() if self._observing() else 0.0
        self._persist_meta()
        self._election_deadline = self._fresh_election_deadline()
        msg = RequestVote(
            self.term, self.name, self.last_log_index, self.last_log_term
        )
        for peer in self.others:
            self._send(peer, msg)
        if self._quorum(len(self.votes)):   # single-member cluster
            self._become_leader()
        return len(self.others)

    def _quorum(self, n: int) -> bool:
        return n * 2 > len(self.peers)

    def _become_leader(self) -> None:
        self.role = LEADER
        self.leader = self.name
        self._close_vc_span("leader")
        self.next_index = {p: self.last_log_index + 1 for p in self.others}
        self.match_index = {p: 0 for p in self.others}
        # commit a no-op entry so prior-term entries can commit under
        # the §5.4.2 current-term rule without waiting for client load
        self.log.append((self.term, ["noop"]))
        self._persist_append(self.last_log_index)
        # commands awaiting a leader: we ARE the leader now
        for cmd_id, command in list(self._pending_client.items()):
            self.log.append((self.term, command))
            idx = self.last_log_index
            self._bind_t0(idx)
            self._bind_trace(idx, self._cmd_trace.get(cmd_id))
            self._persist_append(idx)
            self._forwarded[idx] = (self.name, cmd_id, self.term)
        self._flushed_to = self.name
        self._broadcast_append()
        self._maybe_advance_commit()   # single-member cluster

    def _maybe_step_down(self, term: int) -> None:
        if term > self.term:
            if self.role == CANDIDATE:
                self._close_vc_span("superseded")
            self.term = term
            self.voted_for = None
            self.role = FOLLOWER
            self.leader = None   # stale pointers drop commands silently
            self.votes = set()
            self._persist_meta()

    # -- replication ---------------------------------------------------------

    def _broadcast_append(self) -> int:
        self._last_heartbeat_sent = self.clock.now_micros()
        for peer in self.others:
            self._send_append(peer)
        return len(self.others)

    def _send_append(self, peer: str) -> None:
        nxt = self.next_index.get(peer, self.last_log_index + 1)
        prev = nxt - 1
        if prev < self.snap_index:
            # the follower needs entries the log no longer holds:
            # transfer the snapshot instead (Raft §7). Initiate with
            # chunk 0 and let the follower's SnapshotAcks pull the
            # rest — but do NOT re-initiate on every heartbeat while
            # the ack-driven chain is making progress: each redundant
            # chunk 0 would spawn a parallel chunk/ack chain (the
            # follower re-acks its true position on duplicates) and
            # the transfer would amplify linearly with its own
            # duration. Only a stalled transfer (no chunk sent for a
            # few heartbeats — a lost chunk or ack) is re-kicked.
            now = self.clock.now_micros()
            st = self._snap_inflight.get(peer)
            if (
                st is not None
                and st[0] == self.snap_index
                and now - st[1] < 4 * self.config.heartbeat_micros
            ):
                return
            self._send_snapshot_chunk(peer, 0)
            return
        off = prev - self.snap_index
        window = self.log[off : off + 64]
        msg_hdr = None
        if self._entry_trace:
            entries = []
            for k, (t, c) in enumerate(window):
                hdr = self._entry_trace.get(prev + 1 + k)
                if hdr is not None:
                    hdr = tracing.wire_trace(hdr)
                    if msg_hdr is None:
                        # message-level header: the first traced
                        # entry's context — what feeds the receiver's
                        # clock-offset evidence
                        msg_hdr = hdr
                    entries.append((t, c, hdr))
                else:
                    entries.append((t, c))
            entries = tuple(entries)
        else:
            entries = tuple((t, c) for t, c in window)
        self._send(
            peer,
            AppendEntries(
                self.term, self.name, prev, self._term_at(prev),
                entries, self.commit_index,
            ),
            trace=msg_hdr,
        )

    def submit(self, command: Any, trace=None) -> FlowFuture:
        """Replicate one command; future resolves with apply_fn's return
        once committed (leader) or via ClientResult (member/forwarded).
        Submissions while leaderless wait in the client table and are
        flushed to the leader when one emerges (deadline-bounded).

        `trace`: optional trace context (Span / SpanContext / wire
        header) — the command's protocol messages carry it across the
        fabric and every member stamps its `raft.<phase>` spans into
        the SAME trace, so a distributed commit reads as one
        cross-node tree."""
        hdr = tracing.wire_trace(trace)
        t0 = time.perf_counter() if self._observing() else 0.0
        fut = FlowFuture()
        deadline = (
            self.clock.now_micros() + self.config.command_deadline_micros
        )
        if self.role == LEADER:
            # register BEFORE appending: on a single-member cluster the
            # append commits (and applies) inline
            idx = self.last_log_index + 1
            self._index_futures[idx] = (self.term, fut, deadline)
            self._bind_trace(idx, hdr)
            self._leader_append(command)
            self._stamp("propose", hdr, t0)
            return fut
        self._next_cmd += 1
        cmd_id = self._next_cmd
        self._client_futures[cmd_id] = (fut, deadline)
        self._pending_client[cmd_id] = command
        if hdr is not None:
            if len(self._cmd_trace) >= _TRACE_TABLE_CAP:
                self._cmd_trace.pop(next(iter(self._cmd_trace)))
            self._cmd_trace[cmd_id] = hdr
        if self.leader is not None:
            self._send(
                self.leader, ClientCommand(cmd_id, self.name, command),
                trace=tracing.wire_trace(hdr),
            )
        self._stamp("propose", hdr, t0)
        return fut

    def _leader_append(self, command: Any) -> int:
        self.log.append((self.term, command))
        idx = self.last_log_index
        self._bind_t0(idx)
        self._persist_append(idx)
        self._broadcast_append()
        self._maybe_advance_commit()   # single-member clusters commit now
        return idx

    # -- message handling ----------------------------------------------------

    def _on_message(self, msg: Message) -> None:
        if self.stopped:
            return
        try:
            m = ser.decode(msg.payload)
        except ser.SerializationError:
            return
        if msg.trace is not None and self._tracing():
            # traced frames carry the sender's monotonic send stamp:
            # the receive pairing is the clock-offset evidence cross-
            # node assembly orders spans by (tracing.ClockSync)
            self.tracer.clock_sync.observe_header(msg.sender, msg.trace)
        if isinstance(m, RequestVote):
            self._on_request_vote(m, msg.sender)
        elif isinstance(m, VoteReply):
            self._on_vote_reply(m)
        elif isinstance(m, AppendEntries):
            self._on_append(m, msg.sender, msg.trace)
        elif isinstance(m, InstallSnapshot):
            self._on_install_snapshot(m, msg.sender)
        elif isinstance(m, SnapshotAck):
            if msg.sender == m.follower:
                self._on_snapshot_ack(m)
        elif isinstance(m, AppendReply):
            self._on_append_reply(m)
        elif isinstance(m, ClientCommand):
            self._on_client_command(m, msg.trace)
        elif isinstance(m, ClientResult):
            self._on_client_result(m)

    def _on_request_vote(self, m: RequestVote, sender: str) -> None:
        if sender != m.candidate or m.candidate not in self.peers:
            return   # a non-member (or spoofing member) gets no vote
        self._maybe_step_down(m.term)
        up_to_date = (m.last_log_term, m.last_log_index) >= (
            self.last_log_term, self.last_log_index,
        )
        grant = (
            m.term == self.term
            and self.voted_for in (None, m.candidate)
            and up_to_date
        )
        if grant:
            self.voted_for = m.candidate
            self._persist_meta()
            self._election_deadline = self._fresh_election_deadline()
        self._send(m.candidate, VoteReply(self.term, grant, self.name))

    def _on_vote_reply(self, m: VoteReply) -> None:
        self._maybe_step_down(m.term)
        if self.role != CANDIDATE or m.term != self.term or not m.granted:
            return
        if m.voter not in self.peers:
            return
        self.votes.add(m.voter)
        if self._quorum(len(self.votes)):
            self._become_leader()

    def _on_append(self, m: AppendEntries, sender: str, hdr=None) -> None:
        if sender != m.leader or m.leader not in self.peers:
            return
        t0 = time.perf_counter() if self._observing() else 0.0
        self._maybe_step_down(m.term)
        if m.term < self.term:
            self._send(
                m.leader, AppendReply(self.term, self.name, False, 0)
            )
            return
        # valid leader for this term
        if self.role == CANDIDATE:
            self._close_vc_span("yielded")
        self.role = FOLLOWER
        self.leader = m.leader
        self.votes = set()
        self._election_deadline = self._fresh_election_deadline()
        self._flush_parked()
        # log consistency check (prev below our snapshot is committed
        # state — consistent by definition, term no longer checkable)
        if m.prev_log_index > self.last_log_index or (
            m.prev_log_index >= max(1, self.snap_index)
            and self._term_at(m.prev_log_index) != m.prev_log_term
        ):
            self._send(
                m.leader,
                AppendReply(self.term, self.name, False, 0),
            )
            return
        # append, truncating any conflicting suffix
        insert_at = m.prev_log_index
        changed_from = None
        for i, entry in enumerate(m.entries):
            term, command = entry[0], entry[1]
            idx = insert_at + i + 1
            if idx <= self.snap_index:
                continue   # compacted == committed: matches by definition
            # per-entry header, named apart from the MESSAGE-level
            # `hdr` parameter (the first traced entry's context, which
            # the batch append span below is stamped into)
            e_hdr = tuple(entry[2]) if len(entry) > 2 and entry[2] else None
            if idx <= self.last_log_index:
                if self._term_at(idx) == term:
                    # term-matched redelivery: bind the header if the
                    # first copy predated the trace
                    if e_hdr is not None and idx not in self._entry_trace:
                        self._bind_trace(idx, e_hdr)
                    continue
                del self.log[idx - self.snap_index - 1 :]
                # the truncated entries' trace/timing bindings die with
                # them: a REPLACEMENT entry at the same index must not
                # stamp its commit/apply spans into the overwritten
                # entry's trace
                for table in (self._entry_trace, self._entry_t0):
                    for k in [k for k in table if k >= idx]:
                        del table[k]
            self.log.append((term, list(command) if isinstance(command, tuple) else command))
            if e_hdr is not None:
                self._bind_trace(idx, e_hdr)
            self._bind_t0(idx)
            if changed_from is None:
                changed_from = idx
        if changed_from is not None:
            self._persist_append(changed_from)
            self._stamp("append", hdr, t0, batch=len(m.entries))
        if m.leader_commit > self.commit_index:
            self.commit_index = min(m.leader_commit, self.last_log_index)
            self._apply_committed()
        self._send(
            m.leader,
            AppendReply(self.term, self.name, True, insert_at + len(m.entries)),
        )

    def _flush_parked(self) -> None:
        """(Re)send unresolved client commands when leadership changes —
        a command sent to a since-crashed leader would otherwise hang
        until its deadline despite a healthy new leader."""
        if self.leader is None or self._flushed_to == self.leader:
            return
        self._flushed_to = self.leader
        for cmd_id, command in list(self._pending_client.items()):
            self._send(
                self.leader, ClientCommand(cmd_id, self.name, command),
                trace=tracing.wire_trace(self._cmd_trace.get(cmd_id)),
            )

    def _on_append_reply(self, m: AppendReply) -> None:
        self._maybe_step_down(m.term)
        if self.role != LEADER or m.term != self.term:
            return
        if m.follower not in self.peers:
            return
        if m.success:
            self.match_index[m.follower] = max(
                self.match_index.get(m.follower, 0), m.match_index
            )
            self.next_index[m.follower] = self.match_index[m.follower] + 1
            self._maybe_advance_commit()
            if self.next_index[m.follower] <= self.last_log_index:
                self._send_append(m.follower)   # more to stream
        else:
            self.next_index[m.follower] = max(
                1, self.next_index.get(m.follower, 1) - 1
            )
            if self.next_index[m.follower] - 1 < self.snap_index:
                # next step is an InstallSnapshot; a follower that
                # rejects it (e.g. no restore_fn) would otherwise
                # ping-pong the full snapshot in a tight reply loop —
                # let the heartbeat timer pace the retry instead
                return
            self._send_append(m.follower)

    def _snapshot_blob(self) -> bytes:
        if self._snap_blob_index != self.snap_index or self._snap_blob is None:
            self._snap_blob = ser.encode(self._snap_state)
            self._snap_blob_index = self.snap_index
        return self._snap_blob

    def _send_snapshot_chunk(self, peer: str, offset: int) -> None:
        blob = self._snapshot_blob()
        chunk = max(1, self.config.snapshot_chunk_bytes)
        offset = min(max(offset, 0), len(blob))
        data = blob[offset : offset + chunk]
        self._snap_inflight[peer] = (
            self.snap_index, self.clock.now_micros(),
        )
        self._send(
            peer,
            InstallSnapshot(
                self.term, self.name, self.snap_index, self.snap_term,
                offset, data, offset + len(data) >= len(blob), len(blob),
            ),
        )

    def _on_snapshot_ack(self, m: SnapshotAck) -> None:
        """Stateless chunk server: answer each ack with the chunk the
        follower asks for next. An ack for a superseded snapshot (we
        compacted again mid-transfer) restarts it at chunk 0 of the
        current one."""
        self._maybe_step_down(m.term)
        if self.role != LEADER or m.term != self.term:
            return
        if m.follower not in self.peers:
            return
        if m.last_included_index != self.snap_index:
            self._send_snapshot_chunk(m.follower, 0)
            return
        if m.next_offset < len(self._snapshot_blob()):
            self._send_snapshot_chunk(m.follower, m.next_offset)
        # else: the follower holds every byte and is installing; its
        # final AppendReply advances next_index past the snapshot

    def _maybe_advance_commit(self) -> None:
        for idx in range(self.last_log_index, self.commit_index, -1):
            if self._term_at(idx) != self.term:
                break   # §5.4.2: only current-term entries count directly
            replicated = 1 + sum(
                1 for p in self.others if self.match_index.get(p, 0) >= idx
            )
            if self._quorum(replicated):
                self.commit_index = idx
                self._apply_committed()
                break

    def _apply_committed(self) -> None:
        while self.last_applied < self.commit_index:
            self.last_applied += 1
            idx = self.last_applied
            if self.role == LEADER:
                # the leader RETAINS the binding past apply: a follower
                # that missed the original frames (drop/partition — the
                # lagging replica this plane exists to identify) gets
                # the header on the re-send; the snapshot prune and the
                # table cap bound the retention
                hdr = self._entry_trace.get(idx)
            else:
                hdr = self._entry_trace.pop(idx, None)
            append_t0 = self._entry_t0.pop(idx, None)
            observing = self._observing()
            t_commit = time.perf_counter() if observing else 0.0
            if self.role == LEADER and append_t0 is not None:
                # quorum: leader-side wait from local append to the
                # commit-index advance that covered this entry
                self._stamp("quorum", hdr, append_t0, t_commit)
            term, command = self._entry(self.last_applied)
            t_apply = time.perf_counter() if observing else 0.0
            result = (
                None if command == ["noop"] else self.apply_fn(command)
            )
            if observing:
                self._stamp("apply", hdr, t_apply)
            if self.txstory is not None:
                _story_consensus_commit(
                    self.txstory, command, idx, self.name, term
                )
            self.applied_count += 1
            entry = self._index_futures.pop(self.last_applied, None)
            if entry is not None:
                fterm, fut, _deadline = entry
                if fterm == term:
                    fut.set_result(result)
                else:
                    fut.set_exception(
                        RaftUnavailable("entry overwritten by new leader")
                    )
            fwd = self._forwarded.pop(self.last_applied, None)
            if fwd is not None:
                origin, cmd_id, fwd_term = fwd
                if fwd_term != term:
                    # a new leader overwrote this index with a DIFFERENT
                    # entry: reporting success would hand the origin a
                    # result for someone else's command (a double-spend
                    # window at the notary)
                    if origin != self.name:
                        self._send(
                            origin,
                            ClientResult(
                                cmd_id, False, "entry overwritten"
                            ),
                        )
                elif origin == self.name:
                    # a command parked here pre-election: resolve locally
                    entry = self._client_futures.pop(cmd_id, None)
                    if entry is not None:
                        self._pending_client.pop(cmd_id, None)
                        entry[0].set_result(result)
                else:
                    self._send(
                        origin, ClientResult(cmd_id, True, result),
                        trace=tracing.wire_trace(hdr),
                    )
            if observing:
                # commit: commit-known to entry-resolved on THIS member
                # (apply_fn nested inside as raft.apply)
                self._stamp("commit", hdr, t_commit)
        # a deposed leader's outstanding futures must not hang forever:
        # indexes at/below commit that resolved above are gone; the rest
        # expire via the client-deadline path or on overwrite
        self._maybe_snapshot()

    def _maybe_snapshot(self) -> None:
        """Compact: dump the state machine at last_applied, drop the
        log prefix it covers. Disk stays bounded and restart replays
        only the post-snapshot tail."""
        interval = self.config.snapshot_interval
        if (
            self.snapshot_fn is None
            or interval <= 0
            or self.last_applied - self.snap_index < interval
        ):
            return
        new_term = self._term_at(self.last_applied)
        self._snap_state = self.snapshot_fn()
        del self.log[: self.last_applied - self.snap_index]
        self.snap_index = self.last_applied
        self.snap_term = new_term
        # compacted entries can never be re-sent (InstallSnapshot
        # covers them): drop their retained trace bindings
        for table in (self._entry_trace, self._entry_t0):
            for k in [k for k in table if k <= self.snap_index]:
                del table[k]
        self._persist_snapshot()

    def _on_install_snapshot(self, m: InstallSnapshot, sender: str) -> None:
        if sender != m.leader or m.leader not in self.peers:
            return
        self._maybe_step_down(m.term)
        if m.term < self.term:
            self._send(
                m.leader, AppendReply(self.term, self.name, False, 0)
            )
            return
        self.role = FOLLOWER
        self.leader = m.leader
        self.votes = set()
        self._election_deadline = self._fresh_election_deadline()
        self._flush_parked()
        # -- chunk assembly (Raft §7 offset/done) -------------------------
        if not (m.done and m.offset == 0):
            key = (m.leader, m.last_included_index, m.last_included_term)
            buf = (
                self._snap_incoming[3]
                if self._snap_incoming is not None
                and self._snap_incoming[:3] == key
                else None
            )
            if m.offset == 0:
                if buf and not m.done:
                    # duplicate heartbeat-paced chunk 0 mid-transfer:
                    # re-ack our true position instead of restarting,
                    # which also heals a lost chunk/ack
                    self._send(
                        m.leader,
                        SnapshotAck(
                            self.term, self.name,
                            m.last_included_index, len(buf),
                        ),
                    )
                    return
                buf = bytearray()
                self._snap_incoming = (*key, buf)
                if self._catchup_span is None:
                    # the state-transfer arc: one root span from first
                    # chunk to installed (or abandoned)
                    self._catchup_span = self._open_repair_span(
                        "raft.catch_up"
                    )
                    self._catchup_t0 = (
                        time.perf_counter() if self._observing() else 0.0
                    )
            elif buf is None or m.offset != len(buf):
                # out-of-order / superseded chunk: report where we
                # really are (0 if we hold nothing for this snapshot)
                self._send(
                    m.leader,
                    SnapshotAck(
                        self.term, self.name, m.last_included_index,
                        len(buf) if buf is not None else 0,
                    ),
                )
                return
            buf += bytes(m.data)
            if not m.done:
                self._send(
                    m.leader,
                    SnapshotAck(
                        self.term, self.name,
                        m.last_included_index, len(buf),
                    ),
                )
                return
            self._snap_incoming = None
            try:
                state = ser.decode(bytes(buf))
            except ser.SerializationError:
                # corrupt assembled blob: abandon the transfer WITHOUT
                # acking — an ack(0) would restart the whole stream at
                # network speed (an unthrottled loop when the failure
                # is deterministic); silence lets the leader's stall
                # re-kick retry at heartbeat pace instead
                self._close_catchup_span("corrupt")
                return
        else:
            try:
                state = ser.decode(bytes(m.data))
            except ser.SerializationError:
                return   # malformed single-chunk snapshot: drop
        if m.last_included_index > self.last_applied:
            if self.restore_fn is None:
                # cannot install: answer failure rather than hang the
                # leader's retry loop silently
                self._send(
                    m.leader, AppendReply(self.term, self.name, False, 0)
                )
                return
            self.restore_fn(state)
            keep_suffix = (
                m.last_included_index <= self.last_log_index
                and self._term_at(m.last_included_index)
                == m.last_included_term
            )
            if keep_suffix:
                del self.log[: m.last_included_index - self.snap_index]
            else:
                self.log = []
            self.snap_index = m.last_included_index
            self.snap_term = m.last_included_term
            self._snap_state = state
            self.last_applied = self.snap_index
            self.commit_index = max(self.commit_index, self.snap_index)
            if self._db is not None:
                if not keep_suffix:
                    self._db.execute(
                        "DELETE FROM raft_log WHERE cluster=?",
                        (self.cluster,),
                    )
                self._persist_snapshot()
        # entries up to the snapshot point are committed on the leader,
        # so they "match" regardless of whether we installed or were
        # already past it
        self._close_catchup_span("installed")
        self._send(
            m.leader,
            AppendReply(
                self.term, self.name, True, m.last_included_index
            ),
        )

    def _on_client_command(self, m: ClientCommand, hdr=None) -> None:
        if m.origin not in self.peers:
            return
        if self.role != LEADER:
            return   # origin re-flushes on leader discovery
        idx = self.last_log_index + 1
        self._forwarded[idx] = (m.origin, m.cmd_id, self.term)
        self._bind_trace(idx, hdr)
        self._leader_append(m.command)

    def _on_client_result(self, m: ClientResult) -> None:
        entry = self._client_futures.pop(m.cmd_id, None)
        if entry is None:
            return
        self._pending_client.pop(m.cmd_id, None)
        self._cmd_trace.pop(m.cmd_id, None)
        fut, _deadline = entry
        if m.ok:
            fut.set_result(m.value)
        else:
            fut.set_exception(RaftUnavailable(str(m.value)))

    # -- plumbing ------------------------------------------------------------

    def _send(self, peer: str, message, trace=None) -> None:
        if trace is None:
            # the common untraced path keeps the bare send signature
            # (narrow test doubles stub send(topic, payload, target))
            self.messaging.send(self.topic, ser.encode(message), peer)
        else:
            self.messaging.send(
                self.topic, ser.encode(message), peer, trace=trace
            )

    def stop(self) -> None:
        self.stopped = True
        remove = getattr(self.messaging, "remove_handler", None)
        if remove is not None:
            remove(self.topic, self._on_message)

    def __repr__(self) -> str:
        return (
            f"<RaftNode {self.name} {self.role} term={self.term}"
            f" log={self.last_log_index} commit={self.commit_index}>"
        )


# ---------------------------------------------------------------------------
# the replicated uniqueness map


class RaftUniquenessProvider:
    """stateRef→consumingTx map replicated by Raft (reference:
    RaftUniquenessProvider.kt:41 + DistributedImmutableMap.kt — put-all
    is atomic: any conflict rejects the whole batch).

    Every member applies the same deterministic conflict check, so the
    map is identical cluster-wide; the submitting member's future
    resolves with the conflict set (or None) once the entry commits.
    """

    def __init__(self, raft_factory: Callable[..., RaftNode]):
        """raft_factory(apply_fn, snapshot_fn=..., restore_fn=...) ->
        RaftNode — the provider owns the state machine, the caller owns
        transport/cluster wiring."""
        self.committed: dict = {}   # StateRef -> SecureHash
        # factories MUST forward the snapshot hooks (accept **kwargs):
        # silently dropping them would disable compaction — unbounded
        # log growth — so a non-conforming factory fails loudly here
        self.raft = raft_factory(
            self._apply,
            snapshot_fn=self._snapshot,
            restore_fn=self._restore,
        )

    # snapshot hooks: the whole uniqueness map, deterministic order ----------

    def _snapshot(self) -> list:
        from .notary import snapshot_uniqueness_map

        return snapshot_uniqueness_map(self.committed)

    def _restore(self, state) -> None:
        from .notary import restore_uniqueness_map

        self.committed = restore_uniqueness_map(state)

    # the replicated state machine ------------------------------------------

    def _apply(self, command) -> Any:
        from ..core.contracts import StateRef
        from ..crypto.hashes import SecureHash

        kind, tx_id_b, refs_b = command
        assert kind == "commit", f"unknown raft command {kind!r}"
        tx_id = SecureHash(bytes(tx_id_b))
        refs = [ser.decode(bytes(r)) for r in refs_b]
        conflict = {
            str(ref): str(self.committed[ref])
            for ref in refs
            if ref in self.committed and self.committed[ref] != tx_id
        }
        if conflict:
            return ["conflict", conflict]
        for ref in refs:
            self.committed[ref] = tx_id
        return ["ok"]

    # the UniquenessProvider surface ----------------------------------------

    def commit_async(self, states, tx_id, requester, trace=None) -> FlowFuture:
        from .notary import UniquenessConflict

        raft_fut = self.raft.submit(
            ["commit", tx_id.bytes_, [ser.encode(r) for r in states]],
            trace=trace,
        )
        out = FlowFuture()

        def adapt(fut: FlowFuture) -> None:
            try:
                result = fut.result()
            except BaseException as e:
                out.set_exception(e)
                return
            if result and result[0] == "conflict":
                out.set_exception(UniquenessConflict(dict(result[1])))
            else:
                out.set_result(None)

        raft_fut.add_done_callback(adapt)
        return out

    def commit(self, states, tx_id, requester) -> None:
        raise NotImplementedError(
            "Raft commits are asynchronous; use commit_async"
        )


def partition_raft_groups(
    name: str,
    peers: list,
    messaging: MessagingService,
    clock,
    apply_for: Callable[[int], Callable],
    partitions,
    cluster: str = "xshard",
    db=None,
    rng=None,
    config: Optional[RaftConfig] = None,
    metrics=None,
    tracer=None,
    txstory=None,
) -> dict:
    """One Raft group PER uniqueness partition (round 12, the
    distributed sharded uniqueness plane): group k rides the
    `raft.<cluster>.p<k>` topic namespace — the groups' protocol
    frames stay disjoint on ONE fabric endpoint per member, and the
    persistence tables are already cluster-keyed, so every group can
    share the node database.

    `apply_for(k)` supplies partition k's replicated state machine
    (DistributedUniquenessProvider.partition_apply: idempotent
    committed-row writes into the member's local store copy, so a
    partition owner's rows gain a replica on every member and a
    failover owner boots warm). Returns {partition: RaftNode} — the
    caller ticks each group alongside the provider."""
    groups: dict[int, RaftNode] = {}
    for k in partitions:
        groups[k] = RaftNode(
            name,
            list(peers),
            messaging,
            apply_for(k),
            clock,
            cluster=f"{cluster}.p{k}",
            db=db,
            rng=rng,
            config=config or RaftConfig(),
            metrics=metrics,
            tracer=tracer,
            txstory=txstory,
        )
    return groups
