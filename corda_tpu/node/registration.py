"""Network permissioning (doorman) and initial node registration.

Reference: `node/.../utilities/registration/` —
`NetworkRegistrationHelper.kt:31` (buildKeystore: self-signed temp key
held while the request is in flight, submit-or-resume via a persisted
`certificate-request-id.txt`, poll loop, then store the signed node-CA
chain + a freshly minted TLS cert and the root into the trust store),
`HTTPNetworkRegistrationService.kt:16` (the HTTP client: POST
`/api/certificate` -> request id; GET `/api/certificate/<id>` ->
200 chain | 204 pending | 401 rejected) and the
`NetworkRegistrationService.kt:7` interface.

The reference ships only the CLIENT half — its permissioning server
("doorman") is an external R3 service. Here the doorman itself is part
of the framework so a permissioned network can be stood up end-to-end:
`python -m corda_tpu.node.registration --port 8080 --data-dir dm/`
runs one over HTTP, auto-approving by default or holding requests for
an operator (`--manual` + the /admin endpoints).

Scope note: registration certifies the node's *transport* identity —
the node-CA chain and the TLS leaf the fabric serves (node.py prefers
`certificates/tls.pem` over a generated self-signed cert). Ledger
identity keys remain the node's own (identity service); the stored
node-CA key is the material a production deployment would use to
certify them.
"""

from __future__ import annotations

import json
import threading
from ..utils import locks
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional

from ..utils import x509 as xu


class CertificateRequestException(Exception):
    """The signing request was rejected (HTTP 401 in the reference)."""


# ---------------------------------------------------------------------------
# Doorman: the signing authority + request ledger


class Doorman:
    """The permissioning authority: holds the network intermediate CA,
    keeps a ledger of signing requests, and issues node-CA chains.

    Request ids are the SHA-256 of the CSR's subject + public key
    (NOT the signed CSR bytes — ECDSA signatures are randomised, so a
    re-created CSR over the same key would hash differently). A node
    that lost its request-id file and resubmits with the same key
    resumes the same request instead of colliding with itself (the
    reference leaves this to the operator; determinism costs nothing).
    """

    def __init__(
        self,
        root: xu.CertAndKey,
        intermediate: xu.CertAndKey,
        auto_approve: bool = True,
        data_dir: Optional[str] = None,
    ):
        self.root = root
        self.intermediate = intermediate
        self.auto_approve = auto_approve
        self._dir = Path(data_dir) if data_dir else None
        self._lock = locks.make_lock("Doorman._lock")
        # id -> {"csr": pem, "status": pending|approved|rejected,
        #        "reason": str}
        self._requests: dict[str, dict] = {}
        if self._dir is not None:
            self._dir.mkdir(parents=True, exist_ok=True)
            journal = self._dir / "requests.json"
            if journal.exists():
                raw = json.loads(journal.read_text())
                self._requests = {
                    rid: {**r, "csr": r["csr"].encode()} for rid, r in raw.items()
                }

    @staticmethod
    def create(
        auto_approve: bool = True, data_dir: Optional[str] = None
    ) -> "Doorman":
        """Fresh authority (new root + intermediate), or reload one
        from `data_dir` if it was persisted there before."""
        if data_dir is not None:
            d = Path(data_dir)
            root_f, inter_f = d / "root.pem", d / "intermediate.pem"
            if root_f.exists() and inter_f.exists():
                return Doorman(
                    _load_certandkey(root_f),
                    _load_certandkey(inter_f),
                    auto_approve,
                    data_dir,
                )
        root = xu.create_root_ca()
        inter = xu.create_intermediate_ca(root)
        dm = Doorman(root, inter, auto_approve, data_dir)
        if data_dir is not None:
            d = Path(data_dir)
            (d / "root.pem").write_bytes(root.cert_pem + root.key_pem)
            (d / "intermediate.pem").write_bytes(inter.cert_pem + inter.key_pem)
        return dm

    def _persist(self) -> None:
        if self._dir is None:
            return
        raw = {
            rid: {**r, "csr": r["csr"].decode()}
            for rid, r in self._requests.items()
        }
        (self._dir / "requests.json").write_text(json.dumps(raw))

    def submit(self, csr_pem: bytes, email: str = "") -> str:
        import hashlib

        from cryptography.x509.oid import NameOID

        from ..utils.legal_name import validate_legal_name

        csr = xu.load_csr(csr_pem)          # raises on garbage
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        cn = csr.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        name = cn[0].value if cn else ""
        rid = hashlib.sha256(
            csr.subject.public_bytes()
            + csr.public_key().public_bytes(_Enc.DER, _PubFmt.SubjectPublicKeyInfo)
        ).hexdigest()[:24]
        with self._lock:
            prior = self._requests.get(rid)
            if prior is not None and prior["status"] != "rejected":
                return rid
            # a resubmission of a previously-rejected request is
            # re-evaluated fresh (round-3 advisor): the operator may
            # have reversed a mistaken rejection or the conflicting
            # name may have freed up — the deterministic request id
            # must not wedge a subject+key on a stale rejection
            status = "approved" if self.auto_approve else "pending"
            reason = ""
            # the reference doorman auto-rejects rule-violating and
            # already-taken legal names (permissioning.rst; the name is
            # THE unique identifier on the network)
            try:
                validate_legal_name(name)
            except ValueError as e:
                status, reason = "rejected", str(e)
            else:
                taken = any(
                    r.get("name") == name and r["status"] != "rejected"
                    for r in self._requests.values()
                )
                if taken:
                    status = "rejected"
                    reason = f"legal name already in use: {name}"
            self._requests[rid] = {
                "csr": csr_pem, "status": status, "reason": reason,
                "name": name, "email": email,
            }
            self._persist()
        return rid

    def retrieve(self, request_id: str) -> Optional[list[bytes]]:
        """Leaf-first PEM chain if approved, None while pending.
        Raises CertificateRequestException if rejected, KeyError if
        the id is unknown."""
        with self._lock:
            req = self._requests[request_id]
            if req["status"] == "pending":
                return None
            if req["status"] == "rejected":
                raise CertificateRequestException(
                    "Certificate signing request has been rejected: "
                    f"{req['reason']}"
                )
            # issue exactly once: repeated polls must return THE
            # certificate, not a fresh one with a new serial
            if "chain" not in req:
                node_ca = xu.sign_csr_as_node_ca(
                    self.intermediate, xu.load_csr(req["csr"])
                )
                req["chain"] = [
                    node_ca.public_bytes(_PEM).decode(),
                    self.intermediate.cert_pem.decode(),
                    self.root.cert_pem.decode(),
                ]
                self._persist()
            return [p.encode() for p in req["chain"]]

    # -- operator surface (the doorman approval workflow) ---------------
    def pending(self) -> list[str]:
        with self._lock:
            return [
                rid for rid, r in self._requests.items()
                if r["status"] == "pending"
            ]

    def approve(self, request_id: str) -> None:
        self._set_status(request_id, "approved", "")

    def reject(self, request_id: str, reason: str) -> None:
        self._set_status(request_id, "rejected", reason)

    def _set_status(self, request_id: str, status: str, reason: str) -> None:
        with self._lock:
            self._requests[request_id]["status"] = status
            self._requests[request_id]["reason"] = reason
            self._persist()


def _load_certandkey(path: Path) -> xu.CertAndKey:
    blocks = dict(xu.pem_blocks(path.read_bytes()))
    return xu.CertAndKey(
        xu.load_cert(blocks["CERTIFICATE"]),
        xu.load_key(blocks["PRIVATE KEY"]),
    )


from cryptography.hazmat.primitives.serialization import (
    Encoding as _Enc,
    PublicFormat as _PubFmt,
)

_PEM = _Enc.PEM


# ---------------------------------------------------------------------------
# The service interface + transports (NetworkRegistrationService.kt:7)


class RegistrationService:
    """What the helper talks to: submit a CSR, poll for the chain."""

    def submit_request(self, csr_pem: bytes, email: str = "") -> str:
        raise NotImplementedError

    def retrieve_certificates(self, request_id: str) -> Optional[list[bytes]]:
        raise NotImplementedError


class InProcessRegistrationService(RegistrationService):
    """Direct doorman binding (tests / MockNetwork)."""

    def __init__(self, doorman: Doorman):
        self.doorman = doorman

    def submit_request(self, csr_pem: bytes, email: str = "") -> str:
        return self.doorman.submit(csr_pem, email)

    def retrieve_certificates(self, request_id: str) -> Optional[list[bytes]]:
        return self.doorman.retrieve(request_id)


class HttpRegistrationService(RegistrationService):
    """The production client (HTTPNetworkRegistrationService.kt:16):
    POST /api/certificate, GET /api/certificate/<id>."""

    client_version = "1.0"

    def __init__(self, server_url: str):
        self.server = server_url.rstrip("/")

    def submit_request(self, csr_pem: bytes, email: str = "") -> str:
        import urllib.request

        headers = {
            "Content-Type": "application/octet-stream",
            "Client-Version": self.client_version,
        }
        if email:
            # the reference submits emailAddress alongside the signing
            # request (NetworkRegistrationHelper.kt:132)
            headers["X-Email"] = email
        req = urllib.request.Request(
            f"{self.server}/api/certificate",
            data=csr_pem,
            method="POST",
            headers=headers,
        )
        with urllib.request.urlopen(req) as resp:
            return resp.read().decode()

    def retrieve_certificates(self, request_id: str) -> Optional[list[bytes]]:
        import urllib.error
        import urllib.request

        url = f"{self.server}/api/certificate/{request_id}"
        try:
            with urllib.request.urlopen(url) as resp:
                if resp.status == 204:
                    return None
                pems = json.loads(resp.read().decode())
                return [p.encode() for p in pems]
        except urllib.error.HTTPError as e:
            if e.code == 401:
                raise CertificateRequestException(e.read().decode()) from None
            raise


class PermissioningServer:
    """HTTP front for a Doorman (the server the reference never shipped).

      POST /api/certificate          submit CSR (PEM body) -> request id
      GET  /api/certificate/<id>     200 JSON [pem,...] | 204 | 401
      GET  /admin/requests           pending request ids
      POST /admin/approve/<id>       operator approval (manual mode)
      POST /admin/reject/<id>        body = reason

    The /admin surface shares the listener with the public /api, so
    when `admin_token` is set every /admin call must carry
    `Authorization: Bearer <token>` — without it, anyone who can reach
    the port could self-admit to the network.
    """

    def __init__(self, doorman: Doorman, host: str = "127.0.0.1",
                 port: int = 0, admin_token: Optional[str] = None):
        self.doorman = doorman
        self.admin_token = admin_token
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes = b"",
                      ctype: str = "text/plain"):
                self.send_response(code)
                if body:
                    self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _admin_ok(self) -> bool:
                if outer.admin_token is None:
                    return True
                auth = self.headers.get("Authorization", "")
                return auth == f"Bearer {outer.admin_token}"

            def do_GET(self):
                if self.path == "/admin/requests":
                    if not self._admin_ok():
                        self._send(403, b"admin token required")
                        return
                    self._send(
                        200,
                        json.dumps(outer.doorman.pending()).encode(),
                        "application/json",
                    )
                    return
                prefix = "/api/certificate/"
                if not self.path.startswith(prefix):
                    self._send(404)
                    return
                rid = self.path[len(prefix):]
                try:
                    chain = outer.doorman.retrieve(rid)
                except KeyError:
                    self._send(404, b"unknown request id")
                    return
                except CertificateRequestException as e:
                    self._send(401, str(e).encode())
                    return
                if chain is None:
                    self._send(204)
                else:
                    body = json.dumps([p.decode() for p in chain]).encode()
                    self._send(200, body, "application/json")

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                if self.path == "/api/certificate":
                    try:
                        rid = outer.doorman.submit(
                            body, self.headers.get("X-Email", "")
                        )
                    except ValueError as e:
                        self._send(400, str(e).encode())
                        return
                    self._send(200, rid.encode())
                    return
                for action in ("approve", "reject"):
                    prefix = f"/admin/{action}/"
                    if self.path.startswith(prefix):
                        if not self._admin_ok():
                            self._send(403, b"admin token required")
                            return
                        rid = self.path[len(prefix):]
                        try:
                            if action == "approve":
                                outer.doorman.approve(rid)
                            else:
                                outer.doorman.reject(
                                    rid, body.decode() or "rejected"
                                )
                        except KeyError:
                            self._send(404, b"unknown request id")
                            return
                        self._send(200, b"ok")
                        return
                self._send(404)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self) -> "PermissioningServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)


# ---------------------------------------------------------------------------
# The node-side helper (NetworkRegistrationHelper.kt:31)


class NetworkRegistrationHelper:
    """Build the node's certificates directory by registering with the
    permissioning service. Restart-safe at every step: the in-flight
    key and request id are persisted, so a crash mid-poll resumes the
    SAME request with the SAME key (submitOrResumeCertificateSigning-
    Request); a completed registration is a no-op."""

    def __init__(
        self,
        base_dir: str,
        legal_name: str,
        service: RegistrationService,
        poll_interval: float = 10.0,
        max_polls: Optional[int] = None,
        log=print,
        email: str = "",
        network_root_pem: Optional[bytes] = None,
    ):
        """`email`: operator contact submitted with the CSR (the
        reference's emailAddress, NetworkRegistrationHelper.kt:132).
        `network_root_pem`: optional out-of-band pinned network root
        certificate — when set, the returned chain's root must match
        it byte-for-byte before anything is stored, closing the
        registration-time MITM window the plain-http transport leaves
        open (without it, trust-on-first-use like the reference)."""
        self.certs_dir = Path(base_dir) / "certificates"
        self.legal_name = legal_name
        self.service = service
        self.poll_interval = poll_interval
        self.max_polls = max_polls
        self.log = log
        self.email = email
        self.network_root_pem = network_root_pem
        self._request_id_file = self.certs_dir / "certificate-request-id.txt"
        self._temp_key_file = self.certs_dir / "selfsigned-key.pem"
        self.node_ca_file = self.certs_dir / "node-ca.pem"
        self.tls_file = self.certs_dir / "tls.pem"
        self.truststore_file = self.certs_dir / "truststore.pem"

    def build_keystore(self) -> bool:
        """True if a registration was performed, False if certificates
        already exist (the reference prints and terminates)."""
        from ..utils.legal_name import validate_legal_name

        validate_legal_name(self.legal_name)   # fail before any IO
        if self.node_ca_file.exists():
            self.log("Certificate already exists, nothing to do.")
            return False
        self.certs_dir.mkdir(parents=True, exist_ok=True)

        if self._temp_key_file.exists():
            key = xu.load_key(self._temp_key_file.read_bytes())
        else:
            key = xu.generate_tls_key()
            self._temp_key_file.write_bytes(xu.key_pem(key))

        request_id = self._submit_or_resume(key)
        try:
            chain_pems = self._poll(request_id)
        except CertificateRequestException:
            # a rejected request must not wedge the node: drop BOTH the
            # dead id AND the in-flight key — the request id is a hash
            # of subject+pubkey, so retrying over the same key would
            # resolve to the same (rejected) request forever (round-3
            # advisor)
            self._request_id_file.unlink(missing_ok=True)
            self._temp_key_file.unlink(missing_ok=True)
            raise

        certs = [xu.load_cert(p) for p in chain_pems]
        self._validate(certs, key)
        self.log(
            "Certificate signing request approved, storing private key "
            "with the certificate chain."
        )
        chain_blob = b"".join(c.public_bytes(_PEM) for c in certs)
        self.node_ca_file.write_bytes(xu.key_pem(key) + chain_blob)
        self.truststore_file.write_bytes(certs[-1].public_bytes(_PEM))

        # TLS leaf under the fresh node CA (the reference generates the
        # messaging-service SSL cert here too)
        node_ca = xu.CertAndKey(certs[0], key)
        tls = xu.create_leaf(node_ca, self.legal_name, tls=True)
        self.tls_file.write_bytes(tls.key_pem + tls.cert_pem + chain_blob)

        self._temp_key_file.unlink(missing_ok=True)
        self._request_id_file.unlink(missing_ok=True)
        self.log(f"Node certificates stored in {self.certs_dir}.")
        return True

    def _submit_or_resume(self, key) -> str:
        if self._request_id_file.exists():
            rid = self._request_id_file.read_text().strip()
            self.log(f"Resuming from previous request, request ID: {rid}.")
            return rid
        csr = xu.create_csr(self.legal_name, key)
        self.log(
            f"Submitting certificate signing request for "
            f"{self.legal_name!r} to the permissioning server."
        )
        rid = self.service.submit_request(xu.csr_pem(csr), self.email)
        self._request_id_file.write_text(rid)
        self.log(f"Successfully submitted request, request ID: {rid}.")
        return rid

    def _poll(self, request_id: str) -> list[bytes]:
        polls = 0
        while True:
            chain = self.service.retrieve_certificates(request_id)
            if chain is not None:
                return chain
            polls += 1
            if self.max_polls is not None and polls >= self.max_polls:
                raise TimeoutError(
                    f"request {request_id} still pending after {polls} polls"
                )
            time.sleep(self.poll_interval)

    def _validate(self, certs, key) -> None:
        spki = (_Enc.DER, _PubFmt.SubjectPublicKeyInfo)
        leaf_pub = certs[0].public_key().public_bytes(*spki)
        my_pub = key.public_key().public_bytes(*spki)
        if leaf_pub != my_pub:
            raise CertificateRequestException(
                "returned certificate is not over this node's key"
            )
        if not xu.validate_chain(*certs):
            raise CertificateRequestException(
                "returned certificate chain does not validate"
            )
        if self.network_root_pem is not None:
            pinned = xu.load_cert(self.network_root_pem)
            if certs[-1].public_bytes(_PEM) != pinned.public_bytes(_PEM):
                raise CertificateRequestException(
                    "returned chain's root does not match the pinned "
                    "network root (network_root_file) — possible MITM "
                    "on the registration transport"
                )


def main(argv=None) -> int:
    """Run a permissioning server:
    `python -m corda_tpu.node.registration --port 8080 --data-dir dm/`"""
    import argparse

    parser = argparse.ArgumentParser(
        prog="corda_tpu.node.registration",
        description="Run a network permissioning (doorman) server",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument(
        "--data-dir", default=None,
        help="persist CA material + request journal here",
    )
    parser.add_argument(
        "--manual", action="store_true",
        help="hold requests for operator approval via /admin endpoints",
    )
    parser.add_argument(
        "--admin-token", default=None,
        help="bearer token required on /admin calls; auto-generated "
        "(and printed) when --manual binds a non-loopback host",
    )
    args = parser.parse_args(argv)

    token = args.admin_token
    if (
        token is None
        and args.manual
        and args.host not in ("127.0.0.1", "localhost", "::1")
    ):
        # an unauthenticated /admin/approve on a reachable port would
        # let anyone self-admit to the network (round-3 advisor)
        import secrets

        token = secrets.token_urlsafe(16)
        print(
            f"ADMIN_TOKEN={token}  (auto-generated: --manual on a "
            "non-loopback host without --admin-token)",
            flush=True,
        )
    doorman = Doorman.create(
        auto_approve=not args.manual, data_dir=args.data_dir
    )
    server = PermissioningServer(
        doorman, args.host, args.port, admin_token=token
    ).start()
    print(f"DOORMAN_URL={server.url}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
