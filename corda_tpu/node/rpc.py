"""RPC: the node's client-facing API over the message fabric.

Reference: `CordaRPCOps` (core/.../messaging/CordaRPCOps.kt:38-284) —
flow start, vault queries, snapshot+feed pairs; served by `RPCServer`
(node/.../messaging/RPCServer.kt:46-80: per-call dispatch, subscription
registry with reaping) and consumed through `CordaRPCClient` /
`RPCClientProxyHandler` (client/rpc/.../RPCClientProxyHandler.kt:37-68),
whose signature move is **Observables as first-class RPC results**: the
server captures returned feeds and streams tagged notifications; the
client rematerialises them. Wire protocol: node-api/.../RPCApi.kt
(ClientToServer/ServerToClient). Authentication/authorization:
`RPCUserService` (node/.../services/RPCUserService.kt) — config-defined
users with per-flow start permissions.

Design notes:
- Requests ride the fabric on `rpc.requests` addressed to the node;
  replies and observations return to the *caller's* fabric address —
  the same durable per-peer queue machinery as P2P (the reference
  multiplexes RPC onto the same Artemis broker with JAAS roles).
- A reply always precedes any observation for handles it carries
  (per-peer FIFO gives this for free), so the client never sees an
  observation for an unknown observable.
- Flow results stream as a one-shot observation hung off the SMM's
  lifecycle observers; feeds stream until the client unsubscribes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..core import serialization as ser
from ..flows.api import FlowLogic
from ..flows.statemachine import (
    FlowStateMachine,
    StateMachineManager,
    _class_tag,
    construct_logic,
)
from .messaging import Message, MessagingService
from .services import DataFeed, Observable, ServiceHub
from .vault_query import PageSpecification, QueryCriteria, Sort

TOPIC_RPC_REQUEST = "rpc.requests"
TOPIC_RPC_REPLY = "rpc.replies"
TOPIC_RPC_OBSERVATION = "rpc.observations"
TOPIC_RPC_UNSUBSCRIBE = "rpc.unsubscribe"


class RpcError(Exception):
    """A server-side failure surfaced to the RPC caller."""

    def __init__(self, error_tag: str, message: str):
        self.error_tag = error_tag
        super().__init__(f"{error_tag}: {message}")


class RpcPermissionError(Exception):
    pass


# ---------------------------------------------------------------------------
# users & permissions


@dataclass(frozen=True)
class RpcUser:
    """One RPC login (reference: RPCUserService.kt User). Permissions:
    "ALL", or "StartFlow.<flow tag>" per startable flow."""

    username: str
    password: str
    permissions: tuple[str, ...] = ()


def start_flow_permission(flow_cls) -> str:
    return f"StartFlow.{_class_tag(flow_cls)}"


class RPCUserService:
    def __init__(self, *users: RpcUser):
        self._users = {u.username: u for u in users}

    def authenticate(self, username: str, password: str) -> Optional[RpcUser]:
        u = self._users.get(username)
        if u is None or u.password != password:
            return None
        return u

    @staticmethod
    def can_start_flow(user: RpcUser, flow_tag: str) -> bool:
        return "ALL" in user.permissions or (
            f"StartFlow.{flow_tag}" in user.permissions
        )


# ---------------------------------------------------------------------------
# wire protocol (RPCApi.kt ClientToServer / ServerToClient)


@dataclass(frozen=True)
class RpcRequest:
    req_id: int
    username: str
    password: str
    method: str
    args: tuple


@dataclass(frozen=True)
class RpcReply:
    req_id: int
    ok: bool
    value: Any                      # result tree (may contain handles)
    error_tag: Optional[str]
    error_message: Optional[str]


@dataclass(frozen=True)
class FeedHandle:
    """Marker for a DataFeed in a reply: snapshot + stream id."""

    observable_id: int
    snapshot: Any


@dataclass(frozen=True)
class FlowHandleWire:
    """Marker for a started flow: its id, the one-shot result stream,
    and the progress-step stream captured from the moment the flow
    started (CordaRPCOps FlowProgressHandle — capture must begin at
    start or synchronously-completing flows lose every label)."""

    flow_id: bytes
    result_observable_id: int
    progress_observable_id: Optional[int] = None


@dataclass(frozen=True)
class RpcObservation:
    observable_id: int
    item: Any


@dataclass(frozen=True)
class RpcUnsubscribe:
    observable_id: int


@dataclass(frozen=True)
class StateMachineInfo:
    """One running flow, as reported over RPC (CordaRPCOps.kt
    StateMachineInfo)."""

    flow_id: bytes
    flow_tag: str


@dataclass(frozen=True)
class StateMachineUpdate:
    """added/removed delta on the state-machines feed."""

    kind: str                       # "added" | "removed"
    info: StateMachineInfo


@dataclass(frozen=True)
class FlowProgressSnapshot:
    """A flow's progress-tracker state at subscription time: declared
    steps, the labels already announced, and the current one (CordaRPCOps
    FlowProgressHandle — what ANSIProgressRenderer consumes)."""

    flow_id: bytes
    steps: tuple[str, ...]
    history: tuple[str, ...]
    current: Optional[str]


for _cls in (
    RpcRequest,
    RpcReply,
    FeedHandle,
    FlowHandleWire,
    RpcObservation,
    RpcUnsubscribe,
    StateMachineInfo,
    StateMachineUpdate,
    FlowProgressSnapshot,
):
    ser.serializable(_cls)


# ---------------------------------------------------------------------------
# ops — the server-side API surface


def rpc_method(fn):
    """Mark a method as RPC-exposed (the dispatch allowlist — only
    marked methods are callable over the wire)."""
    fn._rpc_exposed = True
    return fn


def _subscribe_list(observers: list, cb) -> Callable[[], None]:
    """Append cb to a raw observer list, returning the unsubscriber
    (what Observable.subscribe gives for Observable sources)."""
    observers.append(cb)

    def unsubscribe():
        if cb in observers:
            observers.remove(cb)

    return unsubscribe


class CordaRPCOpsImpl:
    """The node-side implementation bridging to SMM/vault/storage
    (reference: node/.../internal/CordaRPCOpsImpl.kt)."""

    def __init__(self, services: ServiceHub, smm: StateMachineManager):
        self.services = services
        self.smm = smm

    # -- identity & time ----------------------------------------------------

    @rpc_method
    def node_identity(self):
        return self.services.my_info

    @rpc_method
    def current_node_time(self) -> int:
        return self.services.clock.now_micros()

    @rpc_method
    def notary_identities(self):
        return list(self.services.network_map_cache.notary_identities())

    # -- network map --------------------------------------------------------

    @rpc_method
    def network_map_snapshot(self):
        return list(self.services.network_map_cache.all_nodes())

    @rpc_method
    def network_map_last_seen(self) -> dict:
        """name -> micros of each peer's last map sighting (the
        explorer network view's liveness column)."""
        return dict(self.services.network_map_cache.last_seen)

    @rpc_method
    def network_map_feed(self) -> DataFeed:
        cache = self.services.network_map_cache
        updates = Observable()
        unsub = _subscribe_list(cache.observers, updates.emit)
        return DataFeed(list(cache.all_nodes()), updates, dispose=unsub)

    # -- vault --------------------------------------------------------------

    @rpc_method
    def vault_query_by(
        self,
        criteria: QueryCriteria,
        paging: Optional[PageSpecification] = None,
        sorting: Optional[Sort] = None,
    ):
        return self.services.vault.query_by(criteria, paging, sorting)

    @rpc_method
    def vault_track_by(
        self,
        criteria: QueryCriteria,
        paging: Optional[PageSpecification] = None,
        sorting: Optional[Sort] = None,
    ) -> DataFeed:
        return self.services.vault.track_by(criteria, paging, sorting)

    # -- transactions -------------------------------------------------------

    @rpc_method
    def verified_transactions_snapshot(self):
        return list(self.services.validated_transactions.all())

    @rpc_method
    def verified_transactions_count(self) -> int:
        """Count without copying the store over the wire (the explorer
        dashboard polls this every refresh)."""
        return self.services.validated_transactions.count()

    @rpc_method
    def transaction_by_id(self, tx_id):
        """One verified transaction (or None) without copying the
        store — the explorer's detail view resolves a transaction and
        its inputs' source transactions this way."""
        return self.services.validated_transactions.get(tx_id)

    @rpc_method
    def verified_transactions_feed(self) -> DataFeed:
        store = self.services.validated_transactions
        updates = Observable()
        unsub = _subscribe_list(store.observers, updates.emit)
        return DataFeed(list(store.all()), updates, dispose=unsub)

    # -- attachments --------------------------------------------------------

    @rpc_method
    def upload_attachment(self, data: bytes):
        return self.services.attachments.import_attachment(data)

    @rpc_method
    def attachment_exists(self, att_id) -> bool:
        return att_id in self.services.attachments

    @rpc_method
    def open_attachment(self, att_id) -> Optional[bytes]:
        att = self.services.attachments.open_attachment(att_id)
        return None if att is None else att.data

    # -- flows --------------------------------------------------------------

    @rpc_method
    def registered_flows(self) -> list[str]:
        from ..flows.api import registered_initiated_flows

        return sorted(registered_initiated_flows())

    @rpc_method
    def state_machines_snapshot(self):
        return [
            StateMachineInfo(fsm.id, fsm.root_tag)
            for fsm in self.smm.flows.values()
            if not fsm.done
        ]

    @rpc_method
    def state_machines_feed(self) -> DataFeed:
        updates = Observable()

        def on_change(kind: str, fsm: FlowStateMachine) -> None:
            updates.emit(
                StateMachineUpdate(kind, StateMachineInfo(fsm.id, fsm.root_tag))
            )

        unsub = _subscribe_list(self.smm.lifecycle, on_change)
        return DataFeed(self.state_machines_snapshot(), updates, dispose=unsub)

    @rpc_method
    def flow_progress_feed(self, flow_id: bytes) -> DataFeed:
        """Snapshot + live stream of one flow's progress-step labels
        (CordaRPCOps FlowProgressHandle; the shell's `flow watch`
        renders it with utils/progress_render)."""
        fsm = self.smm.flows.get(flow_id)
        tracker = (
            getattr(fsm.logic, "progress_tracker", None)
            if fsm is not None
            else None
        )
        snapshot = FlowProgressSnapshot(
            flow_id,
            tuple(tracker.steps) if tracker else (),
            tuple(tracker.history) if tracker else (),
            tracker.current if tracker else None,
        )
        updates = Observable()

        def on_step(changed_fsm, label: str) -> None:
            if changed_fsm.id == flow_id:
                updates.emit(label)

        unsub = _subscribe_list(self.smm.changes, on_step)
        return DataFeed(snapshot, updates, dispose=unsub)

    # start_flow is special-cased by the server (permissioning + flow
    # handle wiring); it is not a plain @rpc_method.
    def start_flow(self, flow_tag: str, kwargs: dict) -> FlowStateMachine:
        logic = construct_logic(flow_tag, kwargs)
        return self.smm.start_flow(logic)


# ---------------------------------------------------------------------------
# server


class RPCServer:
    """Dispatches RpcRequests onto the ops object; captures returned
    feeds/flows and streams them as observations (RPCServer.kt:46-80)."""

    # a client whose outbound journal backs up past this many frames is
    # presumed dead and reaped (the Artemis-notification reaping role,
    # RPCServer.kt:67-73 — our fabric has no disconnect signal, so
    # backlog pressure is the detector)
    MAX_CLIENT_BACKLOG = 10_000
    _BACKLOG_PROBE_EVERY = 64

    def __init__(
        self,
        ops: CordaRPCOpsImpl,
        messaging: MessagingService,
        user_service: RPCUserService,
        client_backlog: Optional[Callable[[str], int]] = None,
    ):
        self._ops = ops
        self._messaging = messaging
        self._users = user_service
        self._backlog = client_backlog
        self._next_obs = 0
        # (client_address, observable_id) -> dispose fn
        self._subs: dict[tuple[str, int], Callable[[], None]] = {}
        self._deferred: list[Callable[[], None]] = []
        self._obs_since_probe: dict[str, int] = {}
        messaging.add_handler(TOPIC_RPC_REQUEST, self._on_request)
        messaging.add_handler(TOPIC_RPC_UNSUBSCRIBE, self._on_unsubscribe)

    # -- request dispatch ----------------------------------------------------

    def _on_request(self, msg: Message) -> None:
        try:
            req = ser.decode(msg.payload)
        except Exception:
            # Malformed payloads (or argument objects whose validation
            # raises during decode) must not crash the message pump; with
            # no decodable req_id there is nothing to correlate a reply
            # to, so log and drop.
            import logging

            logging.getLogger("corda_tpu.rpc").warning(
                "dropping undecodable RPC request from %s", msg.sender
            )
            return
        if not isinstance(req, RpcRequest):
            return
        try:
            value = self._dispatch(req, msg.sender)
            reply = RpcReply(req.req_id, True, value, None, None)
        except Exception as e:
            reply = RpcReply(
                req.req_id, False, None, type(e).__name__, str(e)
            )
        self._messaging.send(TOPIC_RPC_REPLY, ser.encode(reply), msg.sender)
        # flow results for already-finished flows must trail the reply
        flush, self._deferred = self._deferred, []
        for fn in flush:
            fn()

    def _dispatch(self, req: RpcRequest, client: str) -> Any:
        user = self._users.authenticate(req.username, req.password)
        if user is None:
            raise RpcPermissionError("unknown user or bad password")
        if req.method == "start_flow":
            flow_tag, snapshot = req.args
            if not self._users.can_start_flow(user, flow_tag):
                raise RpcPermissionError(
                    f"user {user.username!r} may not start {flow_tag}"
                )
            # capture progress from BEFORE the flow is created: the
            # state machine may run it to completion inline, and labels
            # emitted during that run must still reach the client
            buffered: list[tuple[Any, str]] = []
            capture = lambda fsm, label: buffered.append((fsm, label))  # noqa: E731
            self._ops.smm.changes.append(capture)
            try:
                fsm = self._ops.start_flow(flow_tag, dict(snapshot))
            finally:
                self._ops.smm.changes.remove(capture)
            return self._flow_handle(
                fsm,
                client,
                early_labels=[lb for f, lb in buffered if f.id == fsm.id],
            )
        fn = getattr(self._ops, req.method, None)
        if fn is None or not getattr(fn, "_rpc_exposed", False):
            raise RpcPermissionError(f"no such RPC method {req.method!r}")
        result = fn(*req.args)
        if isinstance(result, DataFeed):
            return self._feed_handle(result, client)
        return result

    # -- handle wiring -------------------------------------------------------

    def _fresh_obs_id(self) -> int:
        self._next_obs += 1
        return self._next_obs

    def _client_backpressure(self, client: str) -> bool:
        """True if the client's outbound queue says it stopped consuming
        (probed every _BACKLOG_PROBE_EVERY observations)."""
        if self._backlog is None:
            return False
        n = self._obs_since_probe.get(client, 0) + 1
        self._obs_since_probe[client] = n
        if n % self._BACKLOG_PROBE_EVERY:
            return False
        return self._backlog(client) > self.MAX_CLIENT_BACKLOG

    def _feed_handle(self, feed: DataFeed, client: str) -> FeedHandle:
        obs_id = self._fresh_obs_id()

        def forward(item: Any) -> None:
            if self._client_backpressure(client):
                import logging

                logging.getLogger("corda_tpu.rpc").warning(
                    "reaping subscriptions of backed-up client %s", client
                )
                self.close_client(client)
                return
            self._messaging.send(
                TOPIC_RPC_OBSERVATION,
                ser.encode(RpcObservation(obs_id, item)),
                client,
            )

        unsub = feed.updates.subscribe(forward)

        def dispose():
            unsub()
            feed.close()

        self._subs[(client, obs_id)] = dispose
        return FeedHandle(obs_id, feed.snapshot)

    def _flow_handle(
        self,
        fsm: FlowStateMachine,
        client: str,
        early_labels: Optional[list[str]] = None,
    ) -> FlowHandleWire:
        obs_id = self._fresh_obs_id()
        prog_id = self._fresh_obs_id()

        def send_label(label: str) -> None:
            self._messaging.send(
                TOPIC_RPC_OBSERVATION,
                ser.encode(RpcObservation(prog_id, label)),
                client,
            )

        for label in early_labels or []:
            # labels from the inline run flush after the reply so the
            # client has the handle before observations arrive
            self._deferred.append(lambda lb=label: send_label(lb))
        if not fsm.done:
            def on_step(step_fsm, label: str) -> None:
                if step_fsm.id == fsm.id:
                    send_label(label)

            unsub_prog = _subscribe_list(self._ops.smm.changes, on_step)
            self._subs[(client, prog_id)] = unsub_prog

        def send_result() -> None:
            if fsm.exception is not None:
                item = [
                    "err",
                    type(fsm.exception).__name__,
                    str(fsm.exception),
                ]
            else:
                item = ["ok", fsm.result]
            self._messaging.send(
                TOPIC_RPC_OBSERVATION,
                ser.encode(RpcObservation(obs_id, item)),
                client,
            )

        if fsm.done:
            # already finished (flows can complete synchronously during
            # start): stream the result right after the reply goes out
            self._deferred.append(send_result)
        else:

            def on_change(kind: str, done_fsm: FlowStateMachine) -> None:
                if kind == "removed" and done_fsm.id == fsm.id:
                    send_result()
                    unsub()
                    self._subs.pop((client, obs_id), None)
                    dispose_prog = self._subs.pop((client, prog_id), None)
                    if dispose_prog is not None:
                        dispose_prog()

            unsub = _subscribe_list(self._ops.smm.lifecycle, on_change)
            self._subs[(client, obs_id)] = unsub
        return FlowHandleWire(fsm.id, obs_id, prog_id)

    # -- unsubscription ------------------------------------------------------

    def _on_unsubscribe(self, msg: Message) -> None:
        try:
            req = ser.decode(msg.payload)
        except Exception:
            return   # malformed: drop, never crash the pump
        if not isinstance(req, RpcUnsubscribe):
            return
        dispose = self._subs.pop((msg.sender, req.observable_id), None)
        if dispose is not None:
            dispose()

    def close_client(self, client: str) -> None:
        """Drop every subscription a disconnected client holds (the
        reference reaps via Artemis management notifications)."""
        for key in [k for k in self._subs if k[0] == client]:
            self._subs.pop(key)()

    @property
    def subscription_count(self) -> int:
        return len(self._subs)


# ---------------------------------------------------------------------------
# client


class RpcFuture:
    """Pump-driven future: resolves when the reply/observation arrives
    (delivery happens inside the caller's pump loop)."""

    def __init__(self):
        self._done = False
        self._value: Any = None
        self._error: Optional[RpcError] = None

    def _resolve(self, value: Any) -> None:
        self._done = True
        self._value = value

    def _fail(self, err: RpcError) -> None:
        self._done = True
        self._error = err

    @property
    def done(self) -> bool:
        return self._done

    def get(self) -> Any:
        if not self._done:
            raise RuntimeError("RPC call still pending — pump the fabric")
        if self._error is not None:
            raise self._error
        return self._value


def _ctor_kwargs_of(logic) -> dict:
    """Read a flow instance's constructor arguments back off its
    attributes; loud error when __init__ doesn't store a parameter
    under its own name (the server re-runs the constructor)."""
    import inspect

    init = type(logic).__init__
    if init is object.__init__:
        return {}   # no explicit constructor: a no-arg flow
    sig = inspect.signature(init)
    kwargs = {}
    for name, param in list(sig.parameters.items())[1:]:
        if param.kind in (
            inspect.Parameter.VAR_POSITIONAL, inspect.Parameter.VAR_KEYWORD
        ):
            raise TypeError(
                f"{type(logic).__name__}.__init__ uses *args/**kwargs; "
                f"start it via start_flow(FlowClass, **kwargs) instead"
            )
        if not hasattr(logic, name):
            raise TypeError(
                f"{type(logic).__name__} does not store __init__ param "
                f"{name!r} as an attribute; start it via "
                f"start_flow(FlowClass, **kwargs) instead"
            )
        kwargs[name] = getattr(logic, name)
    return kwargs


class ReplayObservable(Observable):
    """Observable that replays everything already emitted to late
    subscribers — progress labels often arrive in the same pump round
    as the flow handle itself, before the caller can subscribe."""

    def __init__(self):
        super().__init__()
        self._history: list = []

    def subscribe(self, cb):
        for item in list(self._history):
            cb(item)
        return super().subscribe(cb)

    def emit(self, item) -> None:
        self._history.append(item)
        super().emit(item)


@dataclass
class FlowHandle:
    """Client-side handle: flow id + result future + progress-label
    stream (CordaRPCOps FlowHandle / FlowProgressHandle)."""

    flow_id: bytes
    result: RpcFuture
    progress: Optional[Observable] = None


class RPCClient:
    """Client endpoint: proxy-style method calls + observable demux
    (RPCClientProxyHandler.kt). One instance per (endpoint, server)."""

    def __init__(
        self,
        messaging: MessagingService,
        server_address: str,
        username: str,
        password: str,
    ):
        self._messaging = messaging
        self._server = server_address
        self._username = username
        self._password = password
        self._next_req = 0
        self._pending: dict[int, RpcFuture] = {}
        self._observables: dict[int, Observable] = {}
        self._flow_futures: dict[int, RpcFuture] = {}
        self._flow_progress: dict[int, int] = {}   # result obs -> prog obs
        messaging.add_handler(TOPIC_RPC_REPLY, self._on_reply)
        messaging.add_handler(TOPIC_RPC_OBSERVATION, self._on_observation)

    # -- calls ---------------------------------------------------------------

    def call(self, method: str, *args) -> RpcFuture:
        self._next_req += 1
        req = RpcRequest(
            self._next_req, self._username, self._password, method, tuple(args)
        )
        fut = RpcFuture()
        self._pending[req.req_id] = fut
        self._messaging.send(TOPIC_RPC_REQUEST, ser.encode(req), self._server)
        return fut

    def start_flow(self, logic_or_class, **kwargs) -> RpcFuture:
        """Start a flow; resolves to a FlowHandle. Accepts a flow CLASS
        (or tag string) plus constructor kwargs, or a flow INSTANCE —
        decomposed into (class tag, constructor kwargs) by reading each
        __init__ parameter back off the instance (the FlowLogicRef
        move, FlowLogicRef.kt: the server re-runs the constructor, so
        flows started this way must store parameters under their own
        names — the standard pattern)."""
        if isinstance(logic_or_class, str):
            return self.call("start_flow", logic_or_class, kwargs)
        if isinstance(logic_or_class, type):
            return self.call(
                "start_flow", _class_tag(logic_or_class), kwargs
            )
        logic = logic_or_class
        if kwargs:
            raise TypeError("pass kwargs with a class/tag, not an instance")
        return self.call(
            "start_flow", _class_tag(type(logic)), _ctor_kwargs_of(logic)
        )

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        def method(*args) -> RpcFuture:
            return self.call(name, *args)

        return method

    # -- inbound -------------------------------------------------------------

    def _on_reply(self, msg: Message) -> None:
        if msg.sender != self._server:
            return
        reply = ser.decode(msg.payload)
        fut = self._pending.pop(reply.req_id, None)
        if fut is None:
            return
        if not reply.ok:
            fut._fail(RpcError(reply.error_tag, reply.error_message))
            return
        fut._resolve(self._materialise(reply.value))

    def _materialise(self, value: Any) -> Any:
        if isinstance(value, FeedHandle):
            updates = Observable()
            self._observables[value.observable_id] = updates
            obs_id = value.observable_id
            return DataFeed(
                value.snapshot,
                updates,
                dispose=lambda: self._unsubscribe(obs_id),
            )
        if isinstance(value, FlowHandleWire):
            fut = RpcFuture()
            self._flow_futures[value.result_observable_id] = fut
            progress = None
            if value.progress_observable_id is not None:
                progress = ReplayObservable()
                self._observables[value.progress_observable_id] = progress
                self._flow_progress[value.result_observable_id] = (
                    value.progress_observable_id
                )
            return FlowHandle(value.flow_id, fut, progress)
        return value

    def _unsubscribe(self, obs_id: int) -> None:
        self._observables.pop(obs_id, None)
        self._messaging.send(
            TOPIC_RPC_UNSUBSCRIBE,
            ser.encode(RpcUnsubscribe(obs_id)),
            self._server,
        )

    def _on_observation(self, msg: Message) -> None:
        if msg.sender != self._server:
            return
        obs = ser.decode(msg.payload)
        flow_fut = self._flow_futures.pop(obs.observable_id, None)
        if flow_fut is not None:
            # the flow is over: drop its progress stream too, or a
            # long-lived client leaks one ReplayObservable per flow
            prog_id = self._flow_progress.pop(obs.observable_id, None)
            if prog_id is not None:
                self._observables.pop(prog_id, None)
            status = obs.item[0]
            if status == "ok":
                flow_fut._resolve(obs.item[1])
            else:
                flow_fut._fail(RpcError(obs.item[1], obs.item[2]))
            return
        stream = self._observables.get(obs.observable_id)
        if stream is not None:
            stream.emit(obs.item)
