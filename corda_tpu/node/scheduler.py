"""Scheduler service: time-triggered flows from SchedulableState outputs.

Reference: `NodeSchedulerService` (node/.../services/events/
NodeSchedulerService.kt:43) watches vault outputs implementing
`SchedulableState` (core/.../contracts/Structures.kt), wakes at the
earliest `nextScheduledActivity`, and launches the requested flow via a
`FlowLogicRef`; `ScheduledActivityObserver` (node/.../services/events/
ScheduledActivityObserver.kt) feeds it from vault update streams.

Design differences from the reference (deliberate, TPU-host idiomatic):
- The schedule is *derived state*: it is rebuilt from the vault's
  unconsumed states at startup instead of persisted separately, so a
  crash can never leave the schedule out of sync with the ledger (the
  reference persists a requery table and replays it).
- The core is deterministic and pump-driven (`tick()`), matching the
  MockNetwork Ring-3 testing model; the real node wraps it in a
  background thread (`start()`/`stop()`).
"""

from __future__ import annotations

import heapq
import importlib
import logging
import threading
from ..utils import locks
from typing import Callable, Optional

from ..core.contracts import ScheduledActivity, SchedulableState, StateRef

log = logging.getLogger("corda_tpu.scheduler")


def flow_from_ref(flow_tag: str, flow_args: tuple):
    """Instantiate a flow from its class tag + constructor args.

    The FlowLogicRef discipline (core/.../flows/FlowLogicRef.kt): a
    scheduled activity names a flow *class* and fully-serializable
    constructor arguments; we re-run the constructor, we never pickle
    live flow objects into states.
    """
    parts = flow_tag.split(".")
    mod = None
    for i in range(len(parts) - 1, 0, -1):
        try:
            mod = importlib.import_module(".".join(parts[:i]))
            break
        except ImportError:
            continue
    if mod is None:
        raise ValueError(f"cannot import scheduled flow {flow_tag!r}")
    obj = mod
    for part in parts[i:]:
        obj = getattr(obj, part)
    return obj(*flow_args)


class NodeSchedulerService:
    """Watches the vault for SchedulableStates and launches their flows
    when due.

    `flow_starter(logic)` is the SMM's start_flow (the reference invokes
    via `ServiceHubInternal.startFlow` with `FlowInitiator.Scheduled`).

    Delivery is AT-LEAST-ONCE: a crash between flow start and state
    consumption re-fires the activity on restart (rebuild_from_vault
    sees the state unconsumed), and the reference has the same window.
    Scheduled flows must therefore re-check their trigger state on
    entry (see HeartbeatFlow's state_and_ref guard); a racing duplicate
    is ultimately stopped by the notary's double-spend check.
    """

    RETRY_BACKOFF_MICROS = 5_000_000

    def __init__(
        self,
        services,
        flow_starter: Callable[[object], object],
        *,
        flow_factory: Callable[[str, tuple], object] = flow_from_ref,
    ):
        self._services = services
        self._flow_starter = flow_starter
        self._flow_factory = flow_factory
        self._lock = locks.make_rlock("NodeSchedulerService._lock")
        self._scheduled: dict[StateRef, ScheduledActivity] = {}
        # min-heap of (scheduled_at, seq, ref); stale entries are lazily
        # discarded against _scheduled (the reference recomputes earliest
        # on every mutation; a heap keeps tick() O(due · log n))
        self._heap: list[tuple[int, int, StateRef]] = []
        self._seq = 0
        self._unsubscribe: Optional[Callable[[], None]] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False
        self._stop_evt = threading.Event()
        vault = services.vault
        vault.updates.append(self._on_vault_update)
        self._unsubscribe = lambda: (
            vault.updates.remove(self._on_vault_update)
            if self._on_vault_update in vault.updates
            else None
        )
        self.rebuild_from_vault()

    # -- schedule maintenance ----------------------------------------------

    def rebuild_from_vault(self) -> None:
        """Derive the full schedule from unconsumed vault states (crash
        recovery: the vault IS the persistent schedule)."""
        with self._lock:
            self._scheduled.clear()
            self._heap.clear()
            for sar in self._services.vault.unconsumed_states():
                self._consider(sar.ref, sar.state.data)

    def _consider(self, ref: StateRef, state) -> None:
        if not isinstance(state, SchedulableState):
            return
        try:
            activity = state.next_scheduled_activity(ref)
        except Exception:
            log.exception("next_scheduled_activity failed for %s", ref)
            return
        if activity is None:
            return
        with self._lock:
            self._scheduled[ref] = activity
            self._seq += 1
            heapq.heappush(self._heap, (activity.scheduled_at, self._seq, ref))

    def _on_vault_update(self, update) -> None:
        with self._lock:
            for sar in update.consumed:
                self._scheduled.pop(sar.ref, None)
        for sar in update.produced:
            self._consider(sar.ref, sar.state.data)
        # a new earliest activity must wake the sleeper early
        if self._thread is not None:
            self._stop_evt.set()

    # -- querying -----------------------------------------------------------

    def next_wakeup_micros(self) -> Optional[int]:
        """Earliest pending activity time, or None when idle."""
        return self._peek_next()

    def pending_count(self) -> int:
        return len(self._scheduled)

    # -- execution ----------------------------------------------------------

    def tick(self) -> int:
        """Launch every activity due at the current clock. Returns the
        number of flows started. Deterministic: ties launch in
        scheduling order. A flow that cannot be constructed or started
        stays scheduled and retries after RETRY_BACKOFF_MICROS (the
        state is still unconsumed — dropping it would silently desync
        the schedule from the vault)."""
        now = self._services.clock.now_micros()
        started = 0
        while True:
            with self._lock:
                if not self._heap or self._heap[0][0] > now:
                    return started
                at, _, ref = heapq.heappop(self._heap)
                activity = self._scheduled.get(ref)
                if activity is None or activity.scheduled_at != at:
                    continue  # consumed or rescheduled since queueing
            try:
                logic = self._flow_factory(
                    activity.flow_tag, activity.flow_args
                )
                self._flow_starter(logic)
            except Exception:
                log.exception(
                    "scheduled flow %s failed to launch; retrying in %dus",
                    activity.flow_tag,
                    self.RETRY_BACKOFF_MICROS,
                )
                retry = ScheduledActivity(
                    activity.flow_tag,
                    activity.flow_args,
                    now + self.RETRY_BACKOFF_MICROS,
                )
                with self._lock:
                    # only re-arm if the state wasn't consumed meanwhile
                    if self._scheduled.get(ref) is activity:
                        self._scheduled[ref] = retry
                        self._seq += 1
                        heapq.heappush(
                            self._heap, (retry.scheduled_at, self._seq, ref)
                        )
                continue
            with self._lock:
                if self._scheduled.get(ref) is activity:
                    del self._scheduled[ref]
            started += 1

    # -- background driver (real node) --------------------------------------

    def start(self, poll_micros: int = 200_000) -> None:
        """Run tick() on a background thread, sleeping until the next
        activity (or poll_micros, whichever is sooner)."""
        if self._thread is not None:
            return
        self._stop_evt.clear()
        self._running = True

        def loop():
            while self._running:
                self.tick()
                nxt = self._peek_next()
                now = self._services.clock.now_micros()
                wait = poll_micros if nxt is None else max(0, nxt - now)
                self._stop_evt.wait(min(wait, poll_micros) / 1e6)
                self._stop_evt.clear()

        self._thread = threading.Thread(
            target=loop, name="corda-scheduler", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._running = False
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def _peek_next(self) -> Optional[int]:
        with self._lock:
            while self._heap:
                at, _, ref = self._heap[0]
                activity = self._scheduled.get(ref)
                if activity is None or activity.scheduled_at != at:
                    heapq.heappop(self._heap)
                    continue
                return at
            return None
