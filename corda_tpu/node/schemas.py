"""CorDapp-registered vault schemas — the MappedSchema analogue.

Reference: `MappedSchema`/`PersistentState` let a CorDapp declare an
ORM projection of its states (core/.../schemas/PersistentTypes.kt);
`HibernateObserver` persists the projection on every vault update
(node/.../services/schema/) and `HibernateQueryCriteriaParser` accepts
custom-column criteria against it (VaultCustomQueryCriteria).

Here a schema is a declarative table: name, columns (sqlite types) and
a pure `project(state_data) -> {column: value}` function. The
persistent vault writes one row per produced state into the schema's
own table (keyed by StateRef, joined against vault_states for status),
and `CustomColumnCriteria` (vault_query.py) compiles to a row-value
subquery in SQL or evaluates `project` on the fly in memory — both
backends answer identically, same as the built-in columns.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

_SQL_TYPES = {"TEXT", "INTEGER", "REAL", "BLOB"}


@dataclass(frozen=True)
class MappedSchema:
    """A CorDapp's declared projection of one state family."""

    name: str                                  # e.g. "cash.v1"
    version: int
    table: str                                 # sqlite table name
    columns: tuple[tuple[str, str], ...]       # (column, sqlite type)
    applies_to: type                           # state data class
    project: Callable[[Any], dict]             # state -> {column: value}

    def __post_init__(self):
        if not self.table.replace("_", "").isalnum():
            raise ValueError(f"unsafe table name {self.table!r}")
        for col, typ in self.columns:
            if not col.replace("_", "").isalnum():
                raise ValueError(f"unsafe column name {col!r}")
            if typ.upper() not in _SQL_TYPES:
                raise ValueError(f"unknown sqlite type {typ!r} for {col!r}")

    def ddl(self) -> str:
        cols = ", ".join(f"{c} {t}" for c, t in self.columns)
        return (
            f"CREATE TABLE IF NOT EXISTS {self.table} ("
            "ref_tx BLOB NOT NULL, ref_index INTEGER NOT NULL, "
            f"{cols}, PRIMARY KEY (ref_tx, ref_index))"
        )

    def row_values(self, state_data) -> tuple:
        proj = self.project(state_data)
        unknown = set(proj) - {c for c, _ in self.columns}
        if unknown:
            raise ValueError(
                f"projection of {type(state_data).__name__} produced "
                f"undeclared columns {sorted(unknown)}"
            )
        return tuple(proj.get(c) for c, _ in self.columns)


_SCHEMA_REGISTRY: dict[str, MappedSchema] = {}


def register_schema(schema: MappedSchema) -> None:
    """Install a schema process-wide (the CorDapp-scan analogue: call
    from the cordapp module, next to register_contract)."""
    existing = _SCHEMA_REGISTRY.get(schema.name)
    if existing is not None and existing != schema:
        raise ValueError(f"schema {schema.name!r} already registered")
    _SCHEMA_REGISTRY[schema.name] = schema


def schema_by_name(name: str) -> MappedSchema:
    s = _SCHEMA_REGISTRY.get(name)
    if s is None:
        raise KeyError(f"unknown schema {name!r}")
    return s


def registered_schemas() -> tuple[MappedSchema, ...]:
    return tuple(_SCHEMA_REGISTRY.values())


def schemas_for(state_data) -> list[MappedSchema]:
    return [
        s
        for s in _SCHEMA_REGISTRY.values()
        if isinstance(state_data, s.applies_to)
    ]
