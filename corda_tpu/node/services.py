"""ServiceHub and the in-memory node services.

Reference: the `ServiceHub` facade (core/.../node/ServiceHub.kt:45-60 —
vault, keyManagement, identity, attachments, validatedTransactions,
transactionVerifierService, clock, networkMapCache) and its node-side
implementations (SURVEY §2.8). These in-memory implementations are the
Ring-2/Ring-3 substrate (reference: testing/node/MockServices.kt) and
double as the storage interface the sqlite-backed Phase-3 services
implement.
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

from ..core import serialization as ser
from ..utils import locks
from ..core.contracts import (
    Attachment,
    CommandWithParties,
    StateAndRef,
    StateRef,
    TransactionState,
)
from ..core.identity import AnonymousParty, Party
from ..core.transactions import (
    LedgerTransaction,
    SignedTransaction,
    TransactionVerificationError,
    WireTransaction,
)
from ..crypto import composite as comp
from ..crypto import schemes
from ..crypto.batch_verifier import (
    BatchSignatureVerifier,
    default_verifier,
)
from ..crypto.hashes import SecureHash
from ..crypto.tx_signature import (
    TransactionSignature,
    sign_tx_id,
    sign_tx_ids,
)


# ---------------------------------------------------------------------------
# clock


class Clock:
    """Integer-microsecond clock (determinism: no floats on consensus
    paths; reference TimeWindow uses Instants)."""

    def now_micros(self) -> int:
        import time

        return time.time_ns() // 1_000


class TestClock(Clock):
    """Settable clock for Ring-2/3 tests (reference: TestClock.kt)."""

    def __init__(self, start_micros: int = 1_700_000_000_000_000):
        self._now = start_micros

    def now_micros(self) -> int:
        return self._now

    def advance(self, micros: int) -> None:
        self._now += micros

    def set(self, micros: int) -> None:
        self._now = micros


def _safe_notify(cb, item) -> None:
    """Observer failures must not abort ledger recording: a subscriber
    bug aborting record_transactions would roll back the DB rows while
    the in-memory caches keep them — permanent memory/disk divergence.
    Matches the reference's Rx semantics (onNext errors don't undo the
    vault write)."""
    import logging

    try:
        cb(item)
    except Exception:
        logging.getLogger("corda_tpu.vault").exception(
            "ledger observer raised; continuing"
        )


# ---------------------------------------------------------------------------
# storage services


class TransactionStorage:
    """Validated-transaction store (reference: DBTransactionStorage).
    Observers fire on first record — the SMM's waitForLedgerCommit and
    the vault hang off this."""

    def __init__(self):
        self._txs: dict[SecureHash, SignedTransaction] = {}
        self.observers: list[Callable[[SignedTransaction], None]] = []

    def get(self, tx_id: SecureHash) -> Optional[SignedTransaction]:
        return self._txs.get(tx_id)

    def add(self, stx: SignedTransaction) -> bool:
        """Returns True if newly added (idempotent on re-record)."""
        if not self.add_quiet(stx):
            return False
        self.fire_observers(stx)
        return True

    def add_quiet(self, stx: SignedTransaction) -> bool:
        """Store without firing observers — record_transactions defers
        observer side effects until the vault has fully persisted, so a
        disk failure can unwind with no observer having seen the tx."""
        if stx.id in self._txs:
            return False
        self._txs[stx.id] = stx
        return True

    def fire_observers(self, stx: SignedTransaction) -> None:
        for cb in list(self.observers):
            _safe_notify(cb, stx)

    def _forget(self, tx_id: SecureHash) -> None:
        """Undo of add_quiet when a later step of the record fails."""
        self._txs.pop(tx_id, None)

    def __contains__(self, tx_id: SecureHash) -> bool:
        return tx_id in self._txs

    def all(self) -> list[SignedTransaction]:
        return list(self._txs.values())

    def count(self) -> int:
        """O(1) — dashboards must not copy the whole store to count it."""
        return len(self._txs)


class AttachmentStorage:
    """Content-addressed blob store (reference: NodeAttachmentService)."""

    def __init__(self):
        self._blobs: dict[SecureHash, bytes] = {}

    def import_attachment(self, data: bytes) -> SecureHash:
        att = Attachment.of(data)
        self._blobs.setdefault(att.id, data)
        return att.id

    def open_attachment(self, att_id: SecureHash) -> Optional[Attachment]:
        data = self._blobs.get(att_id)
        return None if data is None else Attachment(att_id, data)

    def __contains__(self, att_id: SecureHash) -> bool:
        return att_id in self._blobs


class CheckpointStorage:
    """Flow checkpoint store (reference: DBCheckpointStorage.kt:18)."""

    def __init__(self):
        self._checkpoints: dict[bytes, bytes] = {}

    def add(self, flow_id: bytes, record: bytes) -> None:
        self._checkpoints[flow_id] = record

    def remove(self, flow_id: bytes) -> None:
        self._checkpoints.pop(flow_id, None)

    def all(self) -> list[tuple[bytes, bytes]]:
        return sorted(self._checkpoints.items())


# ---------------------------------------------------------------------------
# key management & identity


class KeyManagementService:
    """Holds this node's signing keys; mints fresh (anonymous) keys
    (reference: node/.../services/keys/PersistentKeyManagementService)."""

    def __init__(self, *initial_keys: schemes.KeyPair, rng=None):
        import random as _random

        self._keys: dict[schemes.PublicKey, schemes.PrivateKey] = {
            kp.public: kp.private for kp in initial_keys
        }
        self._rng = rng or _random.Random()

    @property
    def keys(self) -> set[schemes.PublicKey]:
        return set(self._keys)

    def fresh_key(
        self, scheme_id: int = schemes.DEFAULT_SCHEME
    ) -> schemes.PublicKey:
        kp = schemes.generate_keypair(
            scheme_id, seed=self._rng.getrandbits(256)
        )
        self._keys[kp.public] = kp.private
        return kp.public

    def register_keypair(self, kp: schemes.KeyPair) -> None:
        """Install an externally-provisioned key (a notary cluster's
        shared service key, distributed out of band)."""
        self._keys[kp.public] = kp.private

    def sign(self, tx_id: SecureHash, key: schemes.PublicKey) -> TransactionSignature:
        priv = self._keys.get(key)
        if priv is None:
            raise KeyError(f"no private key for {key}")
        return sign_tx_id(priv, tx_id)

    def sign_batch(
        self, tx_ids: list[SecureHash], key: schemes.PublicKey
    ) -> list[TransactionSignature]:
        """One Merkle-batch signature fanned out per tx id (the
        batching notary's reply-signing path — see
        tx_signature.sign_tx_ids)."""
        priv = self._keys.get(key)
        if priv is None:
            raise KeyError(f"no private key for {key}")
        return sign_tx_ids(priv, tx_ids)

    def sign_bytes(self, data: bytes, key: schemes.PublicKey) -> bytes:
        """Raw scheme signature over arbitrary bytes (identity binds,
        registrations — NOT transactions, which go through sign())."""
        priv = self._keys.get(key)
        if priv is None:
            raise KeyError(f"no private key for {key}")
        return priv.sign(data)

    def our_first_key_for(self, candidates: Iterable) -> Optional[schemes.PublicKey]:
        """First leaf of any candidate key that we control."""
        for k in candidates:
            for leaf in comp.leaves_of(k):
                if leaf in self._keys:
                    return leaf
        return None


class IdentityService:
    """party <-> key registry (reference: InMemoryIdentityService)."""

    def __init__(self, *parties: Party):
        self._by_key: dict[bytes, Party] = {}
        self._by_name: dict[str, Party] = {}
        for p in parties:
            self.register(p)

    def register(self, party: Party) -> None:
        self._by_key[_key_fp(party.owning_key)] = party
        self._by_name[party.name] = party

    def register_anonymous(self, anonymous, well_known: Party) -> None:
        """Record that an anonymous key belongs to a well-known party
        (confidential identities — the mapping TransactionKeyFlow
        exchanges; reference: IdentityService.registerAnonymousIdentity).
        Refuses to REBIND a key already mapped to a different party —
        silently overwriting would let a counterparty hijack someone
        else's identity resolution on this node."""
        fp = _key_fp(anonymous.owning_key)
        existing = self._by_key.get(fp)
        if existing is not None and existing != well_known:
            raise ValueError(
                f"key already registered to {existing}; refusing rebind "
                f"to {well_known}"
            )
        self._by_key[fp] = well_known

    def party_from_key(self, key) -> Optional[Party]:
        return self._by_key.get(_key_fp(key))

    def party_from_name(self, name: str) -> Optional[Party]:
        return self._by_name.get(name)

    def well_known_party(self, party) -> Optional[Party]:
        """Resolve an AnonymousParty/Party to its well-known identity."""
        if isinstance(party, Party):
            return party
        if isinstance(party, AnonymousParty):
            return self.party_from_key(party.owning_key)
        return None

    def all_parties(self) -> list[Party]:
        return list(self._by_name.values())


def _key_fp(key) -> bytes:
    return key.fingerprint()


# ---------------------------------------------------------------------------
# network map cache


@ser.serializable
@dataclass(frozen=True)
class NodeInfo:
    """A node's advertised identity + address (reference:
    core/.../node/NodeInfo.kt). `address` is the peer's fabric address
    (its unique peer name — message targets everywhere). On the DCN
    fabric, `host`/`port`/`tls_fingerprint` tell bridges where to dial
    and which self-signed TLS cert to pin; the network map is how they
    are learned (the reference distributes cert chains the same way)."""

    address: str
    legal_identity: Party
    advertised_services: tuple[str, ...] = ()
    host: Optional[str] = None
    port: int = 0
    tls_fingerprint: Optional[bytes] = None
    # distributed notaries: the shared service identity this member
    # serves (reference: ServiceInfo with a cluster-wide notary
    # identity; notary-demo Raft/BFT clusters). Transactions name the
    # cluster party as their notary; any member answers for it.
    cluster_identity: Optional[Party] = None
    # the node's web-gateway port (None = no gateway): how peers reach
    # GET /health for the cluster-wide rollup (utils/health.py
    # ClusterHealth) — advertised through the network map like the
    # fabric port, never consensus input
    web_port: Optional[int] = None

    @property
    def notary_identity(self) -> Party:
        return self.legal_identity


SERVICE_NOTARY = "corda.notary.simple"
SERVICE_NOTARY_VALIDATING = "corda.notary.validating"
SERVICE_NETWORK_MAP = "corda.network_map"


@dataclass(frozen=True)
class MapChange:
    """One network-map delta (reference: NetworkMapCache.MapChange —
    Added/Removed/Modified)."""

    kind: str                 # "added" | "removed"
    info: NodeInfo


ser.serializable(MapChange)


class NetworkMapCache:
    """Peer directory (reference: InMemoryNetworkMapCache). The Phase-3
    network-map *service* feeds this over the fabric; Ring-3 tests fill
    it directly. Observers receive MapChange deltas — removals too, or
    feed consumers would route to dead addresses forever."""

    def __init__(self):
        self._nodes: dict[str, NodeInfo] = {}
        # cluster party name -> member infos (in arrival order)
        self._clusters: dict[str, list[NodeInfo]] = {}
        self._cluster_parties: dict[str, Party] = {}
        self._rr: dict[str, int] = {}   # round-robin cursor per cluster
        self.observers: list[Callable[[MapChange], None]] = []
        # liveness for the explorer's network view: name -> micros of
        # the last map sighting (registration/push). Stamped only when
        # a clock is wired (ServiceHub does) — the cache itself stays
        # clock-free for bare test fills
        self.last_seen: dict[str, int] = {}
        self.clock_fn: Optional[Callable[[], int]] = None

    def add_node(self, info: NodeInfo) -> None:
        self._nodes[info.legal_identity.name] = info
        if self.clock_fn is not None:
            self.last_seen[info.legal_identity.name] = self.clock_fn()
        if info.cluster_identity is not None:
            cname = info.cluster_identity.name
            members = self._clusters.setdefault(cname, [])
            members[:] = [
                m
                for m in members
                if m.legal_identity.name != info.legal_identity.name
            ] + [info]
            self._cluster_parties[cname] = info.cluster_identity
        for cb in list(self.observers):
            _safe_notify(cb, MapChange("added", info))

    def remove_node(self, info: NodeInfo) -> None:
        removed = self._nodes.pop(info.legal_identity.name, None)
        self.last_seen.pop(info.legal_identity.name, None)
        if removed is not None:
            for cname, members in list(self._clusters.items()):
                members[:] = [
                    m
                    for m in members
                    if m.legal_identity.name != info.legal_identity.name
                ]
                if not members:
                    del self._clusters[cname]
                    self._cluster_parties.pop(cname, None)
            for cb in list(self.observers):
                _safe_notify(cb, MapChange("removed", removed))

    def address_of(self, party: Party) -> Optional[str]:
        """Message-level address resolution. For a cluster party this is
        deliberately STICKY (first member): sessions are multi-message,
        and rotating here would scatter one session's messages across
        members. Load balancing lives in cluster_members(), which
        rotates its starting member per call — flows that understand
        clusters (NotaryFlow) address members directly."""
        info = self._nodes.get(party.name)
        if info is not None:
            return info.address
        members = self._clusters.get(party.name)
        if members:
            return members[0].address
        return None

    def node_of(self, party: Party) -> Optional[NodeInfo]:
        return self._nodes.get(party.name)

    def node_by_name(self, name: str) -> Optional[NodeInfo]:
        return self._nodes.get(name)

    def notary_identities(self) -> list[Party]:
        singles = [
            n.legal_identity
            for n in self._nodes.values()
            if n.cluster_identity is None
            and any(s.startswith("corda.notary") for s in n.advertised_services)
        ]
        clusters = [
            self._cluster_parties[cname]
            for cname, members in self._clusters.items()
            if any(
                s.startswith("corda.notary")
                for m in members
                for s in m.advertised_services
            )
        ]
        return singles + clusters

    def is_validating_notary(self, party: Party) -> bool:
        info = self._nodes.get(party.name)
        if info is not None:
            return SERVICE_NOTARY_VALIDATING in info.advertised_services
        members = self._clusters.get(party.name, [])
        return any(
            SERVICE_NOTARY_VALIDATING in m.advertised_services
            for m in members
        )

    def cluster_members(self, party: Party) -> list[NodeInfo]:
        """Members of a cluster service, rotated per call so successive
        callers start at different members (the load-balancing role of
        the reference's shared notary queues)."""
        members = list(self._clusters.get(party.name, ()))
        if not members:
            return members
        i = self._rr.get(party.name, 0) % len(members)
        self._rr[party.name] = i + 1
        return members[i:] + members[:i]

    def all_nodes(self) -> list[NodeInfo]:
        return list(self._nodes.values())


# ---------------------------------------------------------------------------
# vault


@dataclass
class VaultUpdate:
    """One ledger delta seen by this node (reference: Vault.Update)."""

    consumed: list[StateAndRef]
    produced: list[StateAndRef]


# Vault updates stream over RPC feeds (CordaRPCOps.vaultTrackBy), so
# they need a wire form; mutable lists round-trip as lists.
ser.register_custom(
    VaultUpdate,
    "VaultUpdate",
    lambda u: [list(u.consumed), list(u.produced)],
    lambda v: VaultUpdate(list(v[0]), list(v[1])),
)


class Observable:
    """Minimal push stream (the Rx Observable role in DataFeed —
    reference returns rx.Observable from trackBy/CordaRPCOps feeds)."""

    def __init__(self):
        self._subscribers: list[Callable[[Any], None]] = []

    def subscribe(self, cb: Callable[[Any], None]) -> Callable[[], None]:
        self._subscribers.append(cb)

        def unsubscribe():
            if cb in self._subscribers:
                self._subscribers.remove(cb)

        return unsubscribe

    def emit(self, item: Any) -> None:
        for cb in list(self._subscribers):
            _safe_notify(cb, item)   # one bad subscriber can't starve the rest


@dataclass
class DataFeed:
    """Snapshot + updates stream (core/.../messaging/DataFeed).
    `dispose()` detaches the feed from its source (the reference leans
    on Rx unsubscribe + GC reaping, RPCClientProxyHandler.kt:37-68)."""

    snapshot: Any
    updates: Observable
    dispose: Optional[Callable[[], None]] = None

    def close(self) -> None:
        if self.dispose is not None:
            self.dispose()


class VaultService:
    """Tracks our unconsumed states; streams updates; soft-locks states
    for in-flight spends (reference: NodeVaultService.kt +
    VaultSoftLockManager)."""

    def __init__(self, services: "ServiceHub"):
        self._services = services
        self._unconsumed: dict[StateRef, TransactionState] = {}
        self._consumed: dict[StateRef, TransactionState] = {}
        self._soft_locks: dict[StateRef, bytes] = {}   # ref -> lock id
        self._recorded_at: dict[StateRef, int] = {}
        self.updates: list[Callable[[VaultUpdate], None]] = []

    # -- ingestion ----------------------------------------------------------

    def notify(self, wtx: WireTransaction) -> None:
        """Apply a recorded transaction: consume our inputs, add our
        relevant outputs (NodeVaultService.notifyAll)."""
        consumed = []
        for ref in wtx.inputs:
            ts = self._unconsumed.pop(ref, None)
            if ts is not None:
                self._consumed[ref] = ts
                self._soft_locks.pop(ref, None)
                consumed.append(StateAndRef(ts, ref))
        produced = []
        my_keys = self._services.key_management.keys
        now = self._services.clock.now_micros()
        for i, ts in enumerate(wtx.outputs):
            if self._is_relevant(ts, my_keys):
                ref = StateRef(wtx.id, i)
                self._unconsumed[ref] = ts
                self._recorded_at[ref] = now
                produced.append(StateAndRef(ts, ref))
        if consumed or produced:
            update = VaultUpdate(consumed, produced)
            # persistence hook first and NOT error-shielded: a failed
            # disk write must abort the record — and unwind the map
            # mutations above so memory never runs ahead of disk and a
            # retry of record_transactions isn't silently a no-op
            try:
                self._on_delta(update)
            except BaseException:
                for sar in consumed:
                    self._unconsumed[sar.ref] = sar.state
                    self._consumed.pop(sar.ref, None)
                for sar in produced:
                    self._unconsumed.pop(sar.ref, None)
                    self._recorded_at.pop(sar.ref, None)
                raise
            for cb in list(self.updates):
                _safe_notify(cb, update)

    def _on_delta(self, update: VaultUpdate) -> None:
        """Subclass hook: persist one vault delta (no-op in memory)."""

    @staticmethod
    def _is_relevant(ts: TransactionState, my_keys: set) -> bool:
        for participant in ts.data.participants:
            for leaf in comp.leaves_of(_owning_key_of(participant)):
                if leaf in my_keys:
                    return True
        return False

    # -- queries ------------------------------------------------------------

    def unconsumed_states(self, cls=None) -> list[StateAndRef]:
        out = []
        for ref, ts in self._unconsumed.items():
            if cls is None or isinstance(ts.data, cls):
                out.append(StateAndRef(ts, ref))
        return out

    def state_and_ref(self, ref: StateRef) -> Optional[StateAndRef]:
        """Look up one unconsumed state by ref (None if spent/unknown)."""
        ts = self._unconsumed.get(ref)
        return StateAndRef(ts, ref) if ts is not None else None

    def consumed_states(self, cls=None) -> list[StateAndRef]:
        return [
            StateAndRef(ts, ref)
            for ref, ts in self._consumed.items()
            if cls is None or isinstance(ts.data, cls)
        ]

    # -- query DSL ----------------------------------------------------------

    def _query_rows(self):
        from .vault_query import CONSUMED, UNCONSUMED, row_of

        rows = []
        for ref, ts in self._unconsumed.items():
            rows.append(
                row_of(
                    StateAndRef(ts, ref),
                    UNCONSUMED,
                    self._recorded_at.get(ref, 0),
                )
            )
        for ref, ts in self._consumed.items():
            rows.append(
                row_of(
                    StateAndRef(ts, ref),
                    CONSUMED,
                    self._recorded_at.get(ref, 0),
                )
            )
        return rows

    def query_by(self, criteria, paging=None, sorting=None):
        """VaultService.queryBy (VaultService.kt:157): criteria AST →
        Page. The in-memory vault evaluates criteria as predicates; the
        persistent vault compiles the same AST to SQL."""
        from .vault_query import PageSpecification, Sort, run_in_memory

        return run_in_memory(
            self._query_rows(),
            criteria,
            paging or PageSpecification(),
            sorting or Sort(),
        )

    def track_by(self, criteria, paging=None, sorting=None) -> "DataFeed":
        """VaultService.trackBy: consistent snapshot + stream of future
        updates whose states match the criteria."""
        snapshot = self.query_by(criteria, paging, sorting)
        feed = Observable()

        def on_update(update: VaultUpdate) -> None:
            from .vault_query import UNCONSUMED, row_of

            now = self._services.clock.now_micros()
            # Consumed states are matched as if still live: the feed
            # reports consumption of states that were IN the tracked
            # set (reference trackBy semantics) — projecting them as
            # CONSUMED would always fail status=UNCONSUMED criteria.
            consumed = [
                s
                for s in update.consumed
                if criteria.matches(row_of(s, UNCONSUMED, now))
            ]
            produced = [
                s
                for s in update.produced
                if criteria.matches(row_of(s, UNCONSUMED, now))
            ]
            if consumed or produced:
                feed.emit(VaultUpdate(consumed, produced))

        self.updates.append(on_update)
        return DataFeed(
            snapshot,
            feed,
            dispose=lambda: (
                self.updates.remove(on_update)
                if on_update in self.updates
                else None
            ),
        )

    # -- coin selection -----------------------------------------------------

    def unconsumed_states_for_spending(
        self,
        amount_quantity: int,
        lock_id: bytes,
        cls=None,
        predicate: Callable[[TransactionState], bool] = lambda ts: True,
        quantity_of: Callable[[TransactionState], int] = None,
    ) -> list[StateAndRef]:
        """Greedy coin selection with soft-locking (reference:
        NodeVaultService.unconsumedStatesForSpending)."""
        if quantity_of is None:
            quantity_of = lambda ts: ts.data.amount.quantity  # noqa: E731
        picked, total = [], 0
        for ref, ts in sorted(
            self._unconsumed.items(), key=lambda kv: str(kv[0])
        ):
            if cls is not None and not isinstance(ts.data, cls):
                continue
            # ANY live lock excludes the coin — including this flow's
            # own: a second spend in the same flow must not re-select
            # coins its first spend already committed to (replay never
            # re-selects, it reuses the journaled picks, so self-lock
            # re-selection is never needed)
            if self._soft_locks.get(ref) is not None:
                continue
            if not predicate(ts):
                continue
            picked.append(StateAndRef(ts, ref))
            total += quantity_of(ts)
            if total >= amount_quantity:
                break
        if total < amount_quantity:
            # nothing to release: the picked coins were never locked,
            # and dropping the whole lock_id here would free an EARLIER
            # spend's in-flight locks in the same flow
            raise InsufficientBalanceError(amount_quantity - total)
        for sar in picked:
            self._soft_locks[sar.ref] = lock_id
        return picked

    def release_soft_locks(self, lock_id: bytes) -> None:
        self._soft_locks = {
            r: l for r, l in self._soft_locks.items() if l != lock_id
        }

    def soft_lock(self, refs: Iterable[StateRef], lock_id: bytes) -> None:
        """Re-assert locks over a journaled coin selection after a
        checkpoint replay (locks are process-local; the selection itself
        is journaled so replay never re-runs it — see finance/cash.py)."""
        for ref in refs:
            if ref in self._unconsumed:
                self._soft_locks[ref] = lock_id


class InsufficientBalanceError(Exception):
    def __init__(self, shortfall: int):
        self.shortfall = shortfall
        super().__init__(f"short {shortfall} units")


# Registered with the canonical codec so a journaled selection failure
# replays after restart with its attributes intact (statemachine.py
# record() error journaling).
ser.register_custom(
    InsufficientBalanceError,
    "InsufficientBalanceError",
    lambda e: e.shortfall,
    lambda v: InsufficientBalanceError(v),
)


def _owning_key_of(participant):
    """Participants may be keys or parties."""
    return getattr(participant, "owning_key", participant)


# ---------------------------------------------------------------------------
# transaction verifier service (the offload seam)


class _Future:
    """Tiny synchronous future (the SPI is future-shaped so the out-of-
    process pool in Phase 4 can slot in: OutOfProcessTransaction-
    VerifierService.kt:19-73). Completion is condition-signalled so a
    pump-less waiter parks on `wait(timeout)` and wakes the instant the
    pump thread resolves it — no polling sleep in the await loop."""

    def __init__(self):
        self._cond = locks.make_condition("_Future._cond")
        self._done = False
        self._exc: Optional[BaseException] = None

    def set_result(self) -> None:
        with self._cond:
            self._done = True
            self._cond.notify_all()

    def set_exception(self, exc: BaseException) -> None:
        with self._cond:
            self._exc = exc
            self._done = True
            self._cond.notify_all()

    @property
    def done(self) -> bool:
        return self._done

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until resolved (or `timeout` seconds); True when the
        future completed. The completing thread notifies, so there is
        no busy-wait — pump-owning callers keep pumping instead (the
        pump itself delivers the completion)."""
        with self._cond:
            return self._cond.wait_for(lambda: self._done, timeout)

    def result(self) -> None:
        if not self._done:
            raise RuntimeError("verification still pending")
        if self._exc is not None:
            raise self._exc


class TransactionVerifierService:
    """SPI: verify(ltx) -> future (reference: core/.../node/services/
    TransactionVerifierService.kt:9-15)."""

    # True when verify()'s future is already resolved on return (the
    # in-memory service). Async implementations (the out-of-process
    # pool) resolve via the message pump — a caller ON the pump thread
    # (the batching notary's flush) must not block on them.
    synchronous = False

    def verify(self, ltx: LedgerTransaction) -> _Future:
        raise NotImplementedError

    def verify_many(self, ltxs: list[LedgerTransaction]) -> list[_Future]:
        """Batch entry point (no reference analogue — its verification
        is per-tx on thread pools). Implementations that can check a
        whole batch in one pass override this; the default preserves
        per-tx dispatch semantics."""
        return [self.verify(ltx) for ltx in ltxs]


class InMemoryTransactionVerifierService(TransactionVerifierService):
    """Runs contract verification inline (reference: InMemoryTransaction-
    VerifierService.kt:10-14 — thread pool there; synchronous here, the
    fabric pump provides concurrency)."""

    synchronous = True
    # the notary's object-less fast sweep may bypass this service:
    # verify_many below IS the same grouped contract sweep, so the
    # decisions are identical and no custom SPI is being skipped
    fast_sweep_ok = True

    def verify(self, ltx: LedgerTransaction) -> _Future:
        f = _Future()
        try:
            ltx.verify()
            f.set_result()
        except Exception as e:
            f.set_exception(e)
        return f

    def verify_many(self, ltxs: list[LedgerTransaction]) -> list[_Future]:
        """One grouped-by-contract pass over the whole batch
        (core/batch_verify.py) — the notary flush's contract phase."""
        from ..core.batch_verify import verify_ledger_batch

        futs = []
        for err in verify_ledger_batch(ltxs):
            f = _Future()
            if err is None:
                f.set_result()
            else:
                f.set_exception(err)
            futs.append(f)
        return futs


# ---------------------------------------------------------------------------
# the hub


class ServiceHub:
    """Facade over every node service (ServiceHub.kt:45-60)."""

    def __init__(
        self,
        my_info: NodeInfo,
        key_management: KeyManagementService,
        identity: IdentityService,
        network_map_cache: Optional[NetworkMapCache] = None,
        clock: Optional[Clock] = None,
        batch_verifier: Optional[BatchSignatureVerifier] = None,
        db=None,
        validated_transactions: Optional[TransactionStorage] = None,
        attachments: Optional[AttachmentStorage] = None,
        checkpoint_storage: Optional[CheckpointStorage] = None,
        vault_factory: Optional[Callable[["ServiceHub"], VaultService]] = None,
    ):
        self.my_info = my_info
        self.key_management = key_management
        self.identity = identity
        self.network_map_cache = network_map_cache or NetworkMapCache()
        self.clock = clock or Clock()
        if self.network_map_cache.clock_fn is None:
            self.network_map_cache.clock_fn = self.clock.now_micros
        self.db = db   # NodeDatabase for persistent hubs, else None
        self.validated_transactions = (
            validated_transactions or TransactionStorage()
        )
        self.attachments = attachments or AttachmentStorage()
        self.checkpoint_storage = checkpoint_storage or CheckpointStorage()
        self.vault = (vault_factory or VaultService)(self)
        self.transaction_verifier = InMemoryTransactionVerifierService()
        self._batch_verifier = batch_verifier
        # @corda_service instances, filled by cordapp.install_cordapp_services
        self.cordapp_services: dict = {}

    @property
    def batch_verifier(self) -> BatchSignatureVerifier:
        """The TPU signature-verification SPI for this node."""
        return self._batch_verifier or default_verifier()

    def cordapp_service(self, cls):
        """This node's instance of a @corda_service class (reference:
        ServiceHub.cordaService(Class), AbstractNode.kt:226-279)."""
        svc = self.cordapp_services.get(cls)
        if svc is None:
            raise KeyError(
                f"no @corda_service {cls.__name__} installed on this node"
            )
        return svc

    # -- recording ----------------------------------------------------------

    def record_transactions(self, stxs: Iterable[SignedTransaction]) -> None:
        """Store validated transactions + notify the vault (reference:
        ServiceHub.recordTransactions -> NodeVaultService.notifyAll).
        On a persistent hub the whole record — tx rows, vault rows, and
        any checkpoints written by observers resuming waiting flows —
        lands in ONE database transaction, so a crash can never leave a
        stored tx whose vault effects are missing."""
        import contextlib

        ctx = self.db.transaction() if self.db else contextlib.nullcontext()
        with ctx:
            for stx in stxs:
                if self.validated_transactions.add_quiet(stx):
                    try:
                        self.vault.notify(stx.wtx)
                    except BaseException:
                        # disk failure: unwind memory too, so a retry
                        # re-runs the whole record instead of no-opping
                        self.validated_transactions._forget(stx.id)
                        raise
                    self.validated_transactions.fire_observers(stx)

    # -- resolution ---------------------------------------------------------

    def resolve_transaction(self, wtx: WireTransaction) -> LedgerTransaction:
        """WireTransaction -> LedgerTransaction: resolve input refs from
        storage, signers to parties, attachment ids to blobs
        (WireTransaction.toLedgerTransaction, WireTransaction.kt:60)."""
        return self._ledger_tx_from_resolved(
            wtx, self._resolve_input_states(wtx)
        )

    def _resolve_input_states(self, wtx: WireTransaction) -> list:
        """Input StateRefs -> their TransactionStates, from storage."""
        txs_get = self.validated_transactions.get
        resolved = []
        for ref in wtx.inputs:
            stx = txs_get(ref.txhash)
            if stx is None:
                raise TransactionResolutionError(ref.txhash)
            outs = stx.wtx.outputs
            if ref.index >= len(outs):
                raise TransactionResolutionError(ref.txhash)
            resolved.append(outs[ref.index])
        return resolved

    def _ledger_tx_from_resolved(
        self, wtx: WireTransaction, resolved_states: list
    ) -> LedgerTransaction:
        inputs = [
            StateAndRef(ts, ref)
            for ts, ref in zip(resolved_states, wtx.inputs)
        ]
        party_from_key = self.identity.party_from_key
        commands = []
        for cmd in wtx.commands:
            signers = cmd.signers
            parties = [
                p for p in map(party_from_key, signers) if p is not None
            ]
            commands.append(
                CommandWithParties(signers, tuple(parties), cmd.value)
            )
        attachments = []
        for att_id in wtx.attachments:
            att = self.attachments.open_attachment(att_id)
            if att is None:
                raise AttachmentResolutionError(att_id)
            attachments.append(att)
        return LedgerTransaction(
            inputs=tuple(inputs),
            outputs=wtx.outputs,
            commands=tuple(commands),
            attachments=tuple(attachments),
            notary=wtx.notary,
            time_window=wtx.time_window,
            id=wtx.id,
        )

    def resolve_verify_batch(self, stxs: list, spi=None) -> tuple:
        """Batched resolution + contract verification — the notary
        flush's host hot path (round-4 verdict #1). Returns
        (errs, deferred): one entry per transaction — None on
        acceptance or the exception the resolve-then-verify path would
        raise — plus {index: LedgerTransaction} for transactions whose
        (peer-supplied, sandboxed) attachment code must not run until
        their signatures are known-good.

        The OBJECT-LESS fast path: a transaction with no attachments,
        no replacement command, and every touched contract registered
        with a `verify_fields` hook is resolved and checked straight
        from its wire pieces — no StateAndRef / CommandWithParties /
        LedgerTransaction is ever built. That construction was ~11 of
        the ~35 us/tx serving cost at depth 16384, for objects the
        asset sweep immediately re-flattened into field lists.
        Decision AND message identity with the LedgerTransaction path
        is fuzz-checked in tests/test_batch_verify.py.

        `spi`: a SYNCHRONOUS TransactionVerifierService to honour for
        the non-fast transactions (the notary's SPI seam). The fast
        path bypasses it only when the service opts in
        (`fast_sweep_ok`, set by the in-memory service whose
        verify_many is the same grouped sweep)."""
        from ..core.batch_verify import (
            uses_attachment_code,
            verify_ledger_batch,
        )
        from ..core.contracts import ContractViolation, contract_by_name
        from ..core.replacement import has_replacement_command

        errs: list = [None] * len(stxs)
        deferred: dict[int, LedgerTransaction] = {}
        ltxs: list[LedgerTransaction] = []
        ltx_idx: list[int] = []
        allow_fast = spi is None or getattr(spi, "fast_sweep_ok", False)
        handlers: dict[str, Any] = {}   # contract name -> hook | None
        resolve_inputs = self._resolve_input_states
        for i, stx in enumerate(stxs):
            wtx = stx.wtx
            try:
                resolved = resolve_inputs(wtx)
            except Exception as e:   # noqa: BLE001 - per-tx outcome
                errs[i] = e
                continue
            outputs = wtx.outputs
            commands = wtx.commands
            names = None
            fast = (
                allow_fast
                and not wtx.attachments
                and not has_replacement_command(commands)
            )
            if fast:
                nameset = {ts.contract for ts in outputs}
                nameset.update(ts.contract for ts in resolved)
                names = sorted(nameset)
                for name in names:
                    hook = handlers.get(name, False)
                    if hook is False:
                        try:
                            hook = getattr(
                                contract_by_name(name), "verify_fields",
                                None,
                            )
                        except ContractViolation:
                            hook = None   # attachment-carried contract
                        handlers[name] = hook
                    if hook is None:
                        fast = False
                        break
            if fast:
                in_datas = [ts.data for ts in resolved]
                out_datas = [ts.data for ts in outputs]
                try:
                    # sorted-name order, first failure wins — exactly
                    # LedgerTransaction.verify's contract order
                    for name in names:
                        handlers[name](commands, in_datas, out_datas)
                except Exception as e:   # noqa: BLE001 - per-tx outcome
                    errs[i] = e
                continue
            try:
                ltx = self._ledger_tx_from_resolved(wtx, resolved)
            except Exception as e:   # noqa: BLE001 - per-tx outcome
                errs[i] = e
                continue
            if uses_attachment_code(ltx):
                deferred[i] = ltx
            else:
                ltxs.append(ltx)
                ltx_idx.append(i)
        if ltxs:
            if spi is not None:
                for i, fut in zip(ltx_idx, spi.verify_many(ltxs)):
                    try:
                        fut.result()
                    except Exception as e:   # noqa: BLE001 - per-tx
                        errs[i] = e
            else:
                for i, e in zip(ltx_idx, verify_ledger_batch(ltxs)):
                    errs[i] = e
        return errs, deferred

    # -- signing ------------------------------------------------------------

    def sign_initial_transaction(self, builder, *keys) -> SignedTransaction:
        """Build + sign with our keys (default: legal identity key)."""
        wtx = builder.to_wire_transaction()
        use = list(keys) or [self.my_info.legal_identity.owning_key]
        sigs = tuple(self.key_management.sign(wtx.id, k) for k in use)
        return SignedTransaction(wtx, sigs)

    def add_signature(self, stx: SignedTransaction, key=None) -> SignedTransaction:
        k = key or self.my_info.legal_identity.owning_key
        return stx.with_additional_signature(
            self.key_management.sign(stx.id, k)
        )


class TransactionResolutionError(TransactionVerificationError):
    def __init__(self, tx_id):
        self.tx_id = tx_id
        super().__init__(f"cannot resolve {tx_id}")


class AttachmentResolutionError(TransactionVerificationError):
    def __init__(self, att_id):
        self.att_id = att_id
        super().__init__(f"missing attachment {att_id}")
