"""Billion-state uniqueness store: segmented commit log + mmap index.

The notary's committed-state registry rebuilt for the set sizes the
ROADMAP names ("millions of users" -> 10^8 committed states): per-shard
sqlite tables pay a B-tree probe per ref and a full table scan per
count, and their file set can't ride the cluster state-transfer
endpoint. This store is an LSM-shaped replacement behind ONE facade:

    CommitLogStateStore          one partition's registry on disk
    ShardedCommitLogUniquenessProvider
                                 the provider the notary planes mount

Layout (one directory per partition)::

    MANIFEST             json {gen, through_segment, count} — atomic
                         rename commits a compaction; everything else
                         is interpreted THROUGH it on boot
    snapshot-<G>.dat     folded records for segments 0..through
    snapshot-<G>.idx     mmap open-addressing hash index over the
                         snapshot: (state-ref -> consumer tx), linear
                         probing, load factor <= 0.5
    segment-<N>.log      append-only record log; highest N is the
                         ACTIVE segment, lower ones are sealed

Write path = the PR 9 WAL discipline (group commit): a whole flush of
rows lands as one write+fsync on the active segment, then the memtable
(the in-memory view of every record newer than the snapshot) absorbs
them. Probe path = memtable hit first, then ONE sorted index sweep over
the mmap for the misses (`prior_consumers_many`), replacing per-ref
point probes — the probe batch is shaped exactly like the verify
batch, so this API is the seam the device-side hash-probe pre-filter
(SZKP-style, arXiv:2408.05890) will consume.

Compaction folds the sealed segments into the next snapshot generation
(snapshot write -> index publish -> manifest swap, each step fsync +
atomic rename), then unlinks the folded segments. A crash at ANY point
leaves either the old manifest (old segments still authoritative;
orphan snapshot files are swept on boot) or the new one (stale
segments are swept on boot) — the CrashScheduleExplorer enumerates
kill points at every one of these boundaries via the `boundary`
callback. Sealed segments must be CRC-clean on boot (a doctored byte
raises StateStoreCorruption); only the active segment may carry a torn
tail, which recovery truncates.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import zlib
from typing import Callable, Iterator, Optional

from ..core.contracts import StateRef
from ..crypto.hashes import SecureHash
from ..utils import locks
from .notary import ShardedUniquenessProvider

# record: ref_tx(32) ref_index(4 BE) consumer(32) req_len(2 BE)
# requester(utf-8) crc32(4 BE, over everything before it)
_REC_FIXED = struct.Struct(">32sI32sH")
_CRC = struct.Struct(">I")
_IDX_MAGIC = b"CTPSIDX1"
_IDX_HEADER = struct.Struct(">8sQQ")
_IDX_SLOT = struct.Struct(">32sI32s")          # ref_tx, ref_index, consumer
_FREE_INDEX = 0xFFFFFFFF                       # empty-slot marker
_MANIFEST = "MANIFEST"

# durability boundaries the crash-schedule explorer kills at — every
# op fires the boundary callback pre and post
BOUNDARY_OPS = (
    "segment_append",
    "segment_seal",
    "snapshot_write",
    "index_publish",
    "compaction_swap",
)


class StateStoreCorruption(Exception):
    """A sealed segment or snapshot failed its integrity check: sealed
    files were fsynced before the seal, so a bad CRC is doctoring or
    media failure, never a torn write — refuse to serve over it."""


def _encode_record(ref: StateRef, consumer: bytes, requester: str) -> bytes:
    req = requester.encode("utf-8")
    if ref.index >= _FREE_INDEX:
        raise ValueError(f"state-ref index {ref.index} out of range")
    body = _REC_FIXED.pack(ref.txhash.bytes_, ref.index, consumer, len(req))
    body += req
    return body + _CRC.pack(zlib.crc32(body))


def _iter_records(buf: bytes, *, strict: bool, source: str):
    """Yield (offset_after, ref, consumer, requester) for each record.
    strict=True raises StateStoreCorruption on ANY damage (sealed
    segments, snapshots); strict=False stops at the first torn record
    (the active segment's tail) and the caller truncates there."""
    off, n = 0, len(buf)
    while off < n:
        end = off + _REC_FIXED.size
        if end > n:
            if strict:
                raise StateStoreCorruption(f"{source}: truncated header")
            return
        ref_tx, ref_index, consumer, req_len = _REC_FIXED.unpack_from(
            buf, off
        )
        end += req_len + _CRC.size
        if end > n:
            if strict:
                raise StateStoreCorruption(f"{source}: truncated record")
            return
        body = buf[off:end - _CRC.size]
        (crc,) = _CRC.unpack_from(buf, end - _CRC.size)
        if zlib.crc32(body) != crc:
            if strict:
                raise StateStoreCorruption(f"{source}: crc mismatch")
            return
        requester = buf[off + _REC_FIXED.size:end - _CRC.size].decode(
            "utf-8"
        )
        yield end, StateRef(SecureHash(ref_tx), ref_index), consumer, \
            requester
        off = end


def _slot_of(ref: StateRef, mask: int) -> int:
    h = int.from_bytes(ref.txhash.bytes_[:8], "big")
    h ^= (ref.index + 1) * 0x9E3779B97F4A7C15      # avalanche the index
    return h & mask


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CommitLogStateStore:
    """One partition's committed-state registry: segmented commit log
    + snapshot with a memory-mapped open-addressing hash index + a
    memtable for the unfolded tail. Single-writer (the provider calls
    it under the partition condition); reads of `stats()` and the
    gauges take the same lock."""

    def __init__(
        self,
        path: str,
        *,
        segment_max_records: int = 65536,
        compact_min_segments: int = 4,
        fsync: bool = True,
        boundary: Optional[Callable[[str, str], None]] = None,
    ):
        self.path = path
        self.segment_max_records = max(1, segment_max_records)
        self.compact_min_segments = max(1, compact_min_segments)
        self._fsync = fsync
        self.boundary = boundary
        self._lock = locks.make_rlock("CommitLogStateStore._lock")
        self._mem: dict[StateRef, tuple[bytes, str]] = {}
        self._idx_map: Optional[mmap.mmap] = None
        self._idx_file = None
        self._idx_slots = 0
        self._idx_mask = 0
        self._snap_count = 0
        self._gen = 0
        self._through = -1
        self._active_no = 0
        self._active_records = 0
        self._active_fh = None
        self._segment_records: dict[int, int] = {}
        self.compactions = 0
        self.appends = 0
        self.probes = 0
        self.index_probes = 0
        os.makedirs(path, exist_ok=True)
        self._recover()

    # -- boundary ---------------------------------------------------------

    def _boundary(self, op: str, when: str) -> None:
        if self.boundary is not None:
            self.boundary(op, when)

    # -- paths ------------------------------------------------------------

    def _segment_path(self, n: int) -> str:
        return os.path.join(self.path, f"segment-{n:08d}.log")

    def _snapshot_path(self, gen: int, ext: str) -> str:
        return os.path.join(self.path, f"snapshot-{gen:08d}.{ext}")

    def _write_atomic(self, path: str, data: bytes) -> None:
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            if self._fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, path)
        if self._fsync:
            _fsync_dir(self.path)

    # -- recovery ---------------------------------------------------------

    def _recover(self) -> None:
        manifest = os.path.join(self.path, _MANIFEST)
        if os.path.exists(manifest):
            with open(manifest, "rb") as fh:
                meta = json.loads(fh.read().decode("utf-8"))
            self._gen = int(meta["gen"])
            self._through = int(meta["through_segment"])
            self._snap_count = int(meta["count"])
        # sweep anything the manifest does not vouch for: orphan
        # snapshot generations (crash before the swap) and segments
        # already folded into the snapshot (crash after it)
        segs = []
        for name in sorted(os.listdir(self.path)):
            full = os.path.join(self.path, name)
            if name.endswith(".tmp"):
                os.unlink(full)
            elif name.startswith("snapshot-"):
                gen = int(name.split("-")[1].split(".")[0])
                if gen != self._gen:
                    os.unlink(full)
            elif name.startswith("segment-"):
                no = int(name.split("-")[1].split(".")[0])
                if no <= self._through:
                    os.unlink(full)
                else:
                    segs.append(no)
        if self._gen > 0:
            self._open_index()
        # replay the unfolded tail into the memtable: every segment
        # except the highest is SEALED (strict CRC); the highest may
        # carry a torn tail from a crash mid-append — truncate it
        segs.sort()
        for pos, no in enumerate(segs):
            p = self._segment_path(no)
            with open(p, "rb") as fh:
                buf = fh.read()
            sealed = pos < len(segs) - 1
            good = 0
            count = 0
            for end, ref, consumer, requester in _iter_records(
                buf, strict=sealed, source=os.path.basename(p)
            ):
                self._apply(ref, consumer, requester)
                good, count = end, count + 1
            if not sealed and good < len(buf):
                with open(p, "r+b") as fh:
                    fh.truncate(good)
            self._segment_records[no] = count
        self._active_no = segs[-1] if segs else self._through + 1
        self._active_records = self._segment_records.get(self._active_no, 0)
        self._active_fh = open(self._segment_path(self._active_no), "ab")
        self._segment_records.setdefault(self._active_no, 0)
        if self._active_records >= self.segment_max_records:
            self._seal()

    def _open_index(self) -> None:
        p = self._snapshot_path(self._gen, "idx")
        self._idx_file = open(p, "rb")
        head = self._idx_file.read(_IDX_HEADER.size)
        if len(head) != _IDX_HEADER.size:
            raise StateStoreCorruption(f"{p}: truncated index header")
        magic, slots, count = _IDX_HEADER.unpack(head)
        if magic != _IDX_MAGIC or slots & (slots - 1):
            raise StateStoreCorruption(f"{p}: bad index header")
        expect = _IDX_HEADER.size + slots * _IDX_SLOT.size
        if os.fstat(self._idx_file.fileno()).st_size != expect:
            raise StateStoreCorruption(f"{p}: index size mismatch")
        self._idx_map = mmap.mmap(
            self._idx_file.fileno(), 0, access=mmap.ACCESS_READ
        )
        self._idx_slots = slots
        self._idx_mask = slots - 1
        if count != self._snap_count:
            raise StateStoreCorruption(f"{p}: index count mismatch")

    def _apply(self, ref: StateRef, consumer: bytes, requester: str) -> None:
        """First-wins fold (the sqlite layer's INSERT OR IGNORE)."""
        if ref in self._mem or self._index_lookup(ref) is not None:
            return
        self._mem[ref] = (consumer, requester)

    # -- probes -----------------------------------------------------------

    def _index_lookup(self, ref: StateRef) -> Optional[bytes]:
        if self._idx_map is None:
            return None
        self.index_probes += 1
        slot = _slot_of(ref, self._idx_mask)
        base = _IDX_HEADER.size
        for _ in range(self._idx_slots):
            off = base + slot * _IDX_SLOT.size
            ref_tx, ref_index, consumer = _IDX_SLOT.unpack_from(
                self._idx_map, off
            )
            if ref_index == _FREE_INDEX:
                return None
            if ref_index == ref.index and ref_tx == ref.txhash.bytes_:
                return consumer
            slot = (slot + 1) & self._idx_mask
        return None

    def prior_consumer(self, ref: StateRef) -> Optional[SecureHash]:
        with self._lock:
            self.probes += 1
            hit = self._mem.get(ref)
            if hit is not None:
                return SecureHash(hit[0])
            raw = self._index_lookup(ref)
            return SecureHash(raw) if raw is not None else None

    def prior_consumers_many(self, refs) -> dict[StateRef, SecureHash]:
        """Batched membership probe: memtable hits first, then ONE
        sweep over the mmap index for the misses, visited in slot
        order (sequential page access instead of a random walk) — the
        sweep that replaces per-ref point probes per flush."""
        out: dict[StateRef, SecureHash] = {}
        with self._lock:
            self.probes += len(refs)
            misses = []
            for ref in refs:
                hit = self._mem.get(ref)
                if hit is not None:
                    out[ref] = SecureHash(hit[0])
                elif self._idx_map is not None:
                    misses.append((_slot_of(ref, self._idx_mask), ref))
            misses.sort(key=lambda t: t[0])
            for _slot, ref in misses:
                raw = self._index_lookup(ref)
                if raw is not None:
                    out[ref] = SecureHash(raw)
        return out

    # -- writes -----------------------------------------------------------

    def commit_rows(self, rows) -> int:
        """Group-commit a flush worth of (StateRef, consumer
        SecureHash, requester str) rows: ONE write + fsync on the
        active segment, then the memtable absorbs them. Idempotent —
        already-committed refs are skipped (first wins), so a
        re-driven cross-member commit replays safely. Returns the
        number of NEW states."""
        with self._lock:
            fresh = []
            payload = bytearray()
            for ref, consumer, requester in rows:
                cbytes = consumer.bytes_ if isinstance(
                    consumer, SecureHash
                ) else consumer
                if ref in self._mem or self._index_lookup(ref) is not None:
                    continue
                payload += _encode_record(ref, cbytes, requester)
                fresh.append((ref, cbytes, requester))
            if not fresh:
                return 0
            self._boundary("segment_append", "pre")
            self._active_fh.write(payload)
            self._active_fh.flush()
            if self._fsync:
                os.fsync(self._active_fh.fileno())
            for ref, cbytes, requester in fresh:
                self._mem[ref] = (cbytes, requester)
            self._active_records += len(fresh)
            self._segment_records[self._active_no] = self._active_records
            self.appends += len(fresh)
            self._boundary("segment_append", "post")
            if self._active_records >= self.segment_max_records:
                self._seal()
                if self.sealed_segments >= self.compact_min_segments:
                    self.compact()
            return len(fresh)

    def _seal(self) -> None:
        """Close the active segment (fsynced — from here on a bad CRC
        is corruption, not a torn tail) and open the next."""
        self._active_fh.flush()
        if self._fsync:
            os.fsync(self._active_fh.fileno())
        self._boundary("segment_seal", "pre")
        self._active_fh.close()
        self._active_no += 1
        self._active_records = 0
        self._segment_records[self._active_no] = 0
        self._active_fh = open(self._segment_path(self._active_no), "ab")
        if self._fsync:
            _fsync_dir(self.path)
        self._boundary("segment_seal", "post")

    # -- compaction -------------------------------------------------------

    @property
    def sealed_segments(self) -> int:
        return sum(1 for n in self._segment_records if n < self._active_no)

    def maintain(self) -> bool:
        """Compaction walk for the node's pump tick: fold when enough
        sealed segments piled up. Returns True when a fold ran."""
        with self._lock:
            if self.sealed_segments >= self.compact_min_segments:
                self.compact()
                return True
            return False

    def compact(self, force: bool = False) -> None:
        """Fold every sealed segment into the next snapshot
        generation: write the record file, publish the index, swap the
        manifest (each step its own fsync + atomic rename = its own
        crash boundary), then unlink what the new manifest no longer
        references. force=True also seals a non-empty active segment
        first so the fold covers everything committed so far."""
        with self._lock:
            if force and self._active_records:
                self._seal()
            through = self._active_no - 1
            if through <= self._through and not force:
                return
            records = bytearray()
            count = 0
            for ref, consumer, requester in self._snapshot_records():
                records += _encode_record(ref, consumer, requester)
                count += 1
            for no in sorted(self._segment_records):
                if no >= self._active_no:
                    continue
                with open(self._segment_path(no), "rb") as fh:
                    buf = fh.read()
                for _end, ref, consumer, requester in _iter_records(
                    buf, strict=True,
                    source=os.path.basename(self._segment_path(no)),
                ):
                    if self._index_lookup(ref) is None:
                        records += _encode_record(ref, consumer, requester)
                        count += 1
            gen = self._gen + 1
            self._boundary("snapshot_write", "pre")
            self._write_atomic(self._snapshot_path(gen, "dat"),
                               bytes(records))
            self._boundary("snapshot_write", "post")
            self._boundary("index_publish", "pre")
            self._write_atomic(self._snapshot_path(gen, "idx"),
                               self._build_index(bytes(records), count))
            self._boundary("index_publish", "post")
            self._boundary("compaction_swap", "pre")
            self._write_atomic(
                os.path.join(self.path, _MANIFEST),
                json.dumps(
                    {"gen": gen, "through_segment": through,
                     "count": count}
                ).encode("utf-8"),
            )
            # the manifest rename IS the commit point: everything after
            # is sweeping files the new manifest no longer references
            old_gen = self._gen
            self._gen, self._through, self._snap_count = gen, through, count
            self._close_index()
            self._open_index()
            self._mem = {
                ref: v for ref, v in self._mem.items()
                if self._index_lookup(ref) is None
            }
            for no in list(self._segment_records):
                if no <= through:
                    os.unlink(self._segment_path(no))
                    del self._segment_records[no]
            if old_gen > 0:
                for ext in ("dat", "idx"):
                    p = self._snapshot_path(old_gen, ext)
                    if os.path.exists(p):
                        os.unlink(p)
            self.compactions += 1
            self._boundary("compaction_swap", "post")

    def _build_index(self, records: bytes, count: int) -> bytes:
        slots = 8
        while slots < 2 * max(count, 1):
            slots <<= 1
        table = bytearray(
            _IDX_SLOT.size * slots
        )
        free = _IDX_SLOT.pack(b"\0" * 32, _FREE_INDEX, b"\0" * 32)
        for s in range(slots):
            table[s * _IDX_SLOT.size:(s + 1) * _IDX_SLOT.size] = free
        mask = slots - 1
        for _end, ref, consumer, _req in _iter_records(
            records, strict=True, source="snapshot"
        ):
            slot = _slot_of(ref, mask)
            while True:
                off = slot * _IDX_SLOT.size
                (_tx, idx, _c) = _IDX_SLOT.unpack_from(table, off)
                if idx == _FREE_INDEX:
                    table[off:off + _IDX_SLOT.size] = _IDX_SLOT.pack(
                        ref.txhash.bytes_, ref.index, consumer
                    )
                    break
                slot = (slot + 1) & mask
        return _IDX_HEADER.pack(_IDX_MAGIC, slots, count) + bytes(table)

    def _snapshot_records(self):
        if self._gen == 0:
            return
        with open(self._snapshot_path(self._gen, "dat"), "rb") as fh:
            buf = fh.read()
        for _end, ref, consumer, requester in _iter_records(
            buf, strict=True, source="snapshot"
        ):
            yield ref, consumer, requester

    def _close_index(self) -> None:
        if self._idx_map is not None:
            self._idx_map.close()
            self._idx_map = None
        if self._idx_file is not None:
            self._idx_file.close()
            self._idx_file = None
        self._idx_slots = self._idx_mask = 0

    # -- views ------------------------------------------------------------

    @property
    def committed_count(self) -> int:
        """O(1): the snapshot count rides the manifest, the memtable
        holds only refs NOT in the snapshot — no scan anywhere."""
        return self._snap_count + len(self._mem)

    def items(self) -> Iterator[tuple[StateRef, SecureHash]]:
        with self._lock:
            for ref, consumer, _req in self._snapshot_records():
                yield ref, SecureHash(consumer)
            for ref, (consumer, _req) in list(self._mem.items()):
                yield ref, SecureHash(consumer)

    def stats(self) -> dict:
        with self._lock:
            return {
                "generation": self._gen,
                "through_segment": self._through,
                "active_segment": self._active_no,
                "active_records": self._active_records,
                "sealed_segments": self.sealed_segments,
                "snapshot_states": self._snap_count,
                "memtable_states": len(self._mem),
                "committed_states": self.committed_count,
                "index_slots": self._idx_slots,
                "compactions": self.compactions,
                "appends": self.appends,
                "probes": self.probes,
                "index_probes": self.index_probes,
            }

    # -- state transfer ---------------------------------------------------

    def snapshot_files(self) -> list[tuple[str, bytes]]:
        """The durable file set a joiner pulls over the cluster
        state-transfer endpoint: manifest + snapshot pair + the
        unfolded segments — installing them reproduces this store
        bit-for-bit."""
        with self._lock:
            self._active_fh.flush()
            if self._fsync:
                os.fsync(self._active_fh.fileno())
            out = []
            names = [_MANIFEST] if self._gen else []
            if self._gen:
                names += [
                    os.path.basename(self._snapshot_path(self._gen, ext))
                    for ext in ("dat", "idx")
                ]
            names += [
                os.path.basename(self._segment_path(no))
                for no in sorted(self._segment_records)
            ]
            for name in names:
                p = os.path.join(self.path, name)
                if os.path.exists(p):
                    with open(p, "rb") as fh:
                        out.append((name, fh.read()))
            return out

    def install_snapshot_files(self, files) -> None:
        """Replace this store's contents with a transferred file set
        (joiner bootstrap). Refuses over a non-empty store."""
        with self._lock:
            if self.committed_count:
                raise ValueError(
                    "install_snapshot_files over a non-empty store"
                )
            self._active_fh.close()
            for name in os.listdir(self.path):
                os.unlink(os.path.join(self.path, name))
            for name, data in files:
                if os.sep in name or name.startswith("."):
                    raise ValueError(f"bad transfer filename {name!r}")
                with open(os.path.join(self.path, name), "wb") as fh:
                    fh.write(data)
                    fh.flush()
                    if self._fsync:
                        os.fsync(fh.fileno())
            if self._fsync:
                _fsync_dir(self.path)
            self._close_index()
            self._mem.clear()
            self._segment_records.clear()
            self._gen, self._through, self._snap_count = 0, -1, 0
            self._active_no = self._active_records = 0
            self._recover()

    def close(self) -> None:
        with self._lock:
            if self._active_fh is not None:
                self._active_fh.close()
                self._active_fh = None
            self._close_index()


class ShardedCommitLogUniquenessProvider(ShardedUniquenessProvider):
    """The commit-log store mounted behind the sharded provider's
    storage seam — the SAME two-phase reserve→commit, partition
    primitives (`prior_consumer`/`write_partition`) and `commit_many`
    semantics as the sqlite subclass, so the batching, sharded and
    distributed notary planes all select it with nothing but the
    `notary_state_store=commitlog` knob. One CommitLogStateStore per
    partition under `<path>/gen-<g>/shard-<k>`; a shard-count retune
    is a MIGRATION exactly like the sqlite layer's: fold every
    committed row into a fresh generation of shard directories, then
    one atomic LAYOUT rename commits the switch."""

    _LAYOUT = "LAYOUT"

    def __init__(
        self,
        path: str,
        n_shards: int = 1,
        record_decisions: bool = False,
        *,
        segment_max_records: int = 65536,
        compact_min_segments: int = 4,
        fsync: bool = True,
    ):
        super().__init__(n_shards, record_decisions)
        self.path = path
        self._opts = dict(
            segment_max_records=segment_max_records,
            compact_min_segments=compact_min_segments,
            fsync=fsync,
        )
        self._fsync = fsync
        os.makedirs(path, exist_ok=True)
        self._layout_gen = self._ensure_layout()
        self._stores = [
            CommitLogStateStore(self._shard_path(k), **self._opts)
            for k in range(self.n_shards)
        ]

    def _shard_path(self, k: int, gen: Optional[int] = None) -> str:
        g = self._layout_gen if gen is None else gen
        return os.path.join(self.path, f"gen-{g:04d}", f"shard-{k}")

    def _ensure_layout(self) -> int:
        layout_p = os.path.join(self.path, self._LAYOUT)
        stored = None
        if os.path.exists(layout_p):
            with open(layout_p, "rb") as fh:
                stored = json.loads(fh.read().decode("utf-8"))
        if stored is not None and stored["shards"] == self.n_shards:
            self._sweep_layout_orphans(stored["gen"])
            return stored["gen"]
        gen = (stored["gen"] + 1) if stored is not None else 0
        if stored is not None:
            # re-shard migration: every committed row re-routes into
            # the new partition layout — a ref probed on the wrong
            # shard would silently miss the commit that conflicts it
            old = [
                CommitLogStateStore(
                    os.path.join(
                        self.path, f"gen-{stored['gen']:04d}",
                        f"shard-{k}",
                    ),
                    **self._opts,
                )
                for k in range(stored["shards"])
            ]
            routed: dict[int, list] = {}
            for store in old:
                for ref, consumer, requester in store._snapshot_records():
                    routed.setdefault(self.shard_of(ref), []).append(
                        (ref, consumer, requester)
                    )
                for ref, (consumer, requester) in store._mem.items():
                    routed.setdefault(self.shard_of(ref), []).append(
                        (ref, consumer, requester)
                    )
                store.close()
            for k in range(self.n_shards):
                dst = CommitLogStateStore(
                    self._shard_path(k, gen), **self._opts
                )
                rows = routed.get(k)
                if rows:
                    dst.commit_rows(
                        [(r, SecureHash(c), q) for r, c, q in rows]
                    )
                    dst.compact(force=True)
                dst.close()
        else:
            for k in range(self.n_shards):
                os.makedirs(self._shard_path(k, gen), exist_ok=True)
        # the LAYOUT rename commits the migration: written before the
        # new generation is complete, a crash would boot over empty
        # shard dirs and silently forget every committed state
        tmp = layout_p + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(json.dumps(
                {"shards": self.n_shards, "gen": gen}
            ).encode("utf-8"))
            fh.flush()
            if self._fsync:
                os.fsync(fh.fileno())
        os.replace(tmp, layout_p)
        if self._fsync:
            _fsync_dir(self.path)
        self._layout_gen = gen
        self._sweep_layout_orphans(gen)
        return gen

    def _sweep_layout_orphans(self, gen: int) -> None:
        import shutil

        for name in os.listdir(self.path):
            if name.startswith("gen-") and name != f"gen-{gen:04d}":
                shutil.rmtree(os.path.join(self.path, name),
                              ignore_errors=True)

    # -- storage backend overrides (called under the partition cond) ------

    def _prior_consumer(self, shard: int, ref: StateRef):
        return self._stores[shard].prior_consumer(ref)

    def _prior_consumers_many(self, shard: int, refs):
        return self._stores[shard].prior_consumers_many(refs)

    def _write_shard(self, shard: int, refs, tx_id, requester) -> None:
        self._stores[shard].commit_rows(
            [(ref, tx_id, requester.name) for ref in refs]
        )

    def _write_rows(self, shard: int, rows) -> None:
        self._stores[shard].commit_rows(
            [(ref, tx_id, requester.name) for ref, tx_id, requester in rows]
        )

    # -- views ------------------------------------------------------------

    @property
    def committed_count(self) -> int:
        return sum(s.committed_count for s in self._stores)

    @property
    def committed(self) -> dict:
        out: dict = {}
        for store in self._stores:
            out.update(store.items())
        return out

    def partition_depth(self, shard: int) -> int:
        return self._stores[shard].committed_count

    def stats(self) -> dict:
        shards = [s.stats() for s in self._stores]
        return {
            "backend": "commitlog",
            "shards": self.n_shards,
            "layout_generation": self._layout_gen,
            "committed_states": sum(
                s["committed_states"] for s in shards
            ),
            "snapshot_states": sum(s["snapshot_states"] for s in shards),
            "memtable_states": sum(s["memtable_states"] for s in shards),
            "segments": sum(
                s["sealed_segments"] + 1 for s in shards
            ),
            "compactions": sum(s["compactions"] for s in shards),
            "probes": sum(s["probes"] for s in shards),
            "appends": sum(s["appends"] for s in shards),
            "per_shard": shards,
        }

    # -- maintenance / transfer / lifecycle -------------------------------

    def maintain(self) -> int:
        """Compaction walk across the partitions (the node pump drives
        this) — returns how many folded."""
        return sum(1 for s in self._stores if s.maintain())

    def compact_all(self) -> None:
        for s in self._stores:
            s.compact(force=True)

    def snapshot_files(self) -> dict[int, list[tuple[str, bytes]]]:
        return {
            k: self._stores[k].snapshot_files()
            for k in range(self.n_shards)
        }

    def install_snapshot_files(self, per_shard) -> None:
        for k, files in per_shard.items():
            self._stores[int(k)].install_snapshot_files(files)

    def set_boundary(
        self, cb: Optional[Callable[[str, str], None]]
    ) -> None:
        """Wire the crash-schedule explorer's kill points into every
        partition store's durability boundaries."""
        for s in self._stores:
            s.boundary = cb

    def close(self) -> None:
        for s in self._stores:
            s.close()


def migrate_sqlite_state(
    db, provider: ShardedCommitLogUniquenessProvider
) -> int:
    """One-way boot migration sqlite -> commitlog: stream every
    committed row out of the legacy `notary_commits` table and any
    `notary_commits_s<k>` partition tables into the commit-log
    provider, fold, then clear the sqlite rows. Idempotent until the
    final clear (commit_rows skips already-present refs), so a crash
    between the fold and the clear simply re-migrates on next boot —
    the sqlite clear is LAST for exactly that reason. Returns the
    number of rows migrated."""
    import sqlite3

    from .persistence import (
        PersistentKVStore,
        ShardedPersistentUniquenessProvider,
    )

    meta = PersistentKVStore(
        db, ShardedPersistentUniquenessProvider._META_SPACE
    )
    stored = meta.get(b"shards")
    tables = ["notary_commits"]
    if stored:
        tables += [
            f"notary_commits_s{k}"
            for k in range(int.from_bytes(stored, "big"))
        ]
    moved = 0
    cleared = []
    for table in tables:
        try:
            rows = db.query(
                f"SELECT ref_tx, ref_index, consumer, requester"
                f" FROM {table}"
            )
        except sqlite3.OperationalError:
            continue
        cleared.append(table)
        if not rows:
            continue
        by_shard: dict[int, list] = {}
        for ref_tx, ref_index, consumer, requester in rows:
            ref = StateRef(SecureHash(bytes(ref_tx)), ref_index)
            by_shard.setdefault(provider.shard_of(ref), []).append(
                (ref, SecureHash(bytes(consumer)), requester)
            )
        for k, batch in by_shard.items():
            moved += provider._stores[k].commit_rows(batch)
    if moved:
        provider.compact_all()
    if cleared:
        with db.transaction() as conn:
            for table in cleared:
                conn.execute(f"DELETE FROM {table}")
    return moved
