"""Vault query DSL: criteria AST compiled to SQL or an in-memory filter.

Reference: the `QueryCriteria` hierarchy (core/.../node/services/vault/
QueryCriteria.kt:23 — VaultQueryCriteria, LinearStateQueryCriteria,
FungibleAssetQueryCriteria, And/Or composition), paging + sorting
(`PageSpecification`, `Sort`), the `VaultService.queryBy/trackBy` API
(core/.../node/services/VaultService.kt:157, CordaRPCOps.vaultQueryBy
CordaRPCOps.kt:92), and `HibernateQueryCriteriaParser` (node/.../vault/
HibernateQueryCriteriaParser.kt) which turns the AST into JPA SQL.

Here every criterion compiles BOTH ways from one definition:
`sql()` emits a WHERE fragment over the denormalised `vault_states`
table (persistence.py), `matches()` evaluates against live rows — so
the in-memory Ring-2/3 vault and the sqlite vault answer identically,
and tests assert exactly that.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

from ..core import serialization as ser
from ..core.contracts import StateAndRef, UniqueIdentifier
from ..crypto import composite as comp

# -- status enum -------------------------------------------------------------

UNCONSUMED = "UNCONSUMED"
CONSUMED = "CONSUMED"
ALL = "ALL"

_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a is not None and a > b,
    ">=": lambda a, b: a is not None and a >= b,
    "<": lambda a, b: a is not None and a < b,
    "<=": lambda a, b: a is not None and a <= b,
}
_SQL_OPS = {"==": "=", "!=": "<>", ">": ">", ">=": ">=", "<": "<", "<=": "<="}


@dataclass(frozen=True)
class ColumnPredicate:
    """op ∈ {==, !=, >, >=, <, <=} applied to a comparable column."""

    op: str
    value: Any

    def __post_init__(self):
        if self.op not in _OPS:
            raise ValueError(f"unknown comparison op {self.op!r}")


# -- row model ---------------------------------------------------------------


@dataclass(frozen=True)
class VaultRow:
    """The queryable projection of one vault state — what the sqlite
    table stores per row and what the in-memory vault synthesises on
    the fly (the MappedSchema projection, PersistentTypes.kt)."""

    state_and_ref: StateAndRef
    status: str                       # UNCONSUMED | CONSUMED
    contract_tag: str
    notary_name: Optional[str]
    quantity: Optional[int]
    product: Optional[str]
    issuer_name: Optional[str]
    linear_id: Optional[bytes]
    participant_fps: tuple[bytes, ...]
    recorded_at: int


def row_of(sar: StateAndRef, status: str, recorded_at: int) -> VaultRow:
    """Project a StateAndRef into its queryable row (in-memory path)."""
    data = sar.state.data
    amount = getattr(data, "amount", None)
    quantity = product = issuer = None
    if amount is not None:
        quantity = getattr(amount, "quantity", None)
        token = getattr(amount, "token", None)
        product = token
        if token is not None and hasattr(token, "issuer"):
            issuer = token.issuer.party.name
            product = token.product
        product = None if product is None else str(product)
    lid = getattr(data, "linear_id", None)
    lid_b = None
    if lid is not None:
        lid_b = lid if isinstance(lid, bytes) else ser.encode(lid)
    from .services import _owning_key_of

    fps = []
    for p in data.participants:
        for leaf in comp.leaves_of(_owning_key_of(p)):
            fps.append(leaf.fingerprint())
    return VaultRow(
        state_and_ref=sar,
        status=status,
        contract_tag=type(data).__name__,
        notary_name=sar.state.notary.name if sar.state.notary else None,
        quantity=quantity,
        product=product,
        issuer_name=issuer,
        linear_id=lid_b,
        participant_fps=tuple(fps),
        recorded_at=recorded_at,
    )


# -- criteria AST ------------------------------------------------------------


class QueryCriteria:
    """Base: composable with & and | (QueryCriteria.kt and/or)."""

    status: str = UNCONSUMED

    def __and__(self, other: "QueryCriteria") -> "QueryCriteria":
        return And(self, other)

    def __or__(self, other: "QueryCriteria") -> "QueryCriteria":
        return Or(self, other)

    # each criterion implements:
    def matches(self, row: VaultRow) -> bool:
        raise NotImplementedError

    def sql(self) -> tuple[str, list]:
        """(where_fragment, params) over vault_states AS v."""
        raise NotImplementedError


def _status_match(status: str, row: VaultRow) -> bool:
    return status == ALL or row.status == status


def _status_sql(status: str) -> tuple[str, list]:
    if status == ALL:
        return "1=1", []
    return "v.status = ?", [0 if status == UNCONSUMED else 1]


@dataclass(frozen=True)
class VaultQueryCriteria(QueryCriteria):
    """General criteria (QueryCriteria.VaultQueryCriteria): status,
    state types, notary, recording-time window."""

    status: str = UNCONSUMED
    contract_state_types: Optional[tuple] = None   # classes or tag strings
    notary_names: Optional[tuple[str, ...]] = None
    recorded_between: Optional[tuple[int, int]] = None   # [from, until) µs

    def _tags(self) -> Optional[list[str]]:
        if self.contract_state_types is None:
            return None
        return [
            t if isinstance(t, str) else t.__name__
            for t in self.contract_state_types
        ]

    def matches(self, row: VaultRow) -> bool:
        if not _status_match(self.status, row):
            return False
        tags = self._tags()
        if tags is not None and row.contract_tag not in tags:
            return False
        if self.notary_names is not None and row.notary_name not in self.notary_names:
            return False
        if self.recorded_between is not None:
            lo, hi = self.recorded_between
            if not (lo <= row.recorded_at < hi):
                return False
        return True

    def sql(self) -> tuple[str, list]:
        frags, params = [], []
        s, p = _status_sql(self.status)
        frags.append(s)
        params += p
        tags = self._tags()
        if tags is not None:
            frags.append(
                f"v.contract_tag IN ({','.join('?' * len(tags))})"
            )
            params += tags
        if self.notary_names is not None:
            frags.append(f"v.notary IN ({','.join('?' * len(self.notary_names))})")
            params += list(self.notary_names)
        if self.recorded_between is not None:
            frags.append("v.recorded_at >= ? AND v.recorded_at < ?")
            params += list(self.recorded_between)
        return " AND ".join(frags), params


@dataclass(frozen=True)
class FungibleAssetQueryCriteria(QueryCriteria):
    """Fungible-schema criteria (QueryCriteria.FungibleAssetQuery-
    Criteria): quantity comparisons, product, issuer, participant."""

    status: str = UNCONSUMED
    quantity: Optional[ColumnPredicate] = None
    product: Optional[str] = None
    issuer_names: Optional[tuple[str, ...]] = None
    participant_key: Optional[Any] = None   # PublicKey/CompositeKey

    def matches(self, row: VaultRow) -> bool:
        if not _status_match(self.status, row):
            return False
        if row.quantity is None:
            return False
        if self.quantity is not None and not _OPS[self.quantity.op](
            row.quantity, self.quantity.value
        ):
            return False
        if self.product is not None and row.product != self.product:
            return False
        if self.issuer_names is not None and row.issuer_name not in self.issuer_names:
            return False
        if self.participant_key is not None:
            fps = {
                leaf.fingerprint()
                for leaf in comp.leaves_of(self.participant_key)
            }
            if not fps & set(row.participant_fps):
                return False
        return True

    def sql(self) -> tuple[str, list]:
        frags, params = [], []
        s, p = _status_sql(self.status)
        frags.append(s)
        params += p
        frags.append("v.quantity IS NOT NULL")
        if self.quantity is not None:
            frags.append(f"v.quantity {_SQL_OPS[self.quantity.op]} ?")
            params.append(self.quantity.value)
        if self.product is not None:
            frags.append("v.token = ?")
            params.append(self.product)
        if self.issuer_names is not None:
            frags.append(f"v.issuer IN ({','.join('?' * len(self.issuer_names))})")
            params += list(self.issuer_names)
        if self.participant_key is not None:
            fps = [
                leaf.fingerprint()
                for leaf in comp.leaves_of(self.participant_key)
            ]
            frags.append(
                "EXISTS (SELECT 1 FROM vault_parts vp WHERE"
                " vp.ref_tx = v.ref_tx AND vp.ref_index = v.ref_index"
                f" AND vp.fingerprint IN ({','.join('?' * len(fps))}))"
            )
            params += fps
        return " AND ".join(frags), params


@dataclass(frozen=True)
class LinearStateQueryCriteria(QueryCriteria):
    """Linear-schema criteria (QueryCriteria.LinearStateQueryCriteria):
    match by linear id thread / external id."""

    status: str = UNCONSUMED
    linear_ids: Optional[tuple[UniqueIdentifier, ...]] = None
    external_ids: Optional[tuple[str, ...]] = None

    def _encoded_ids(self) -> Optional[list[bytes]]:
        if self.linear_ids is None:
            return None
        return [ser.encode(lid) for lid in self.linear_ids]

    def matches(self, row: VaultRow) -> bool:
        if not _status_match(self.status, row):
            return False
        if row.linear_id is None:
            return False
        ids = self._encoded_ids()
        if ids is not None and row.linear_id not in ids:
            return False
        if self.external_ids is not None:
            try:
                lid = ser.decode(row.linear_id)
            except ser.SerializationError:
                return False   # raw-bytes linear ids carry no external id
            if (
                not isinstance(lid, UniqueIdentifier)
                or lid.external_id not in self.external_ids
            ):
                return False
        return True

    def sql(self) -> tuple[str, list]:
        frags, params = [], []
        s, p = _status_sql(self.status)
        frags.append(s)
        params += p
        frags.append("v.linear_id IS NOT NULL")
        ids = self._encoded_ids()
        if ids is not None:
            frags.append(f"v.linear_id IN ({','.join('?' * len(ids))})")
            params += ids
        if self.external_ids is not None:
            # external id has no dedicated column: match candidate rows
            # in SQL, refine in Python (the parser's custom-criteria
            # fallback path).
            pass
        return " AND ".join(frags), params

    def needs_refine(self) -> bool:
        return self.external_ids is not None


@dataclass(frozen=True)
class And(QueryCriteria):
    left: QueryCriteria
    right: QueryCriteria

    def matches(self, row: VaultRow) -> bool:
        return self.left.matches(row) and self.right.matches(row)

    def sql(self) -> tuple[str, list]:
        ls, lp = self.left.sql()
        rs, rp = self.right.sql()
        return f"({ls}) AND ({rs})", lp + rp


@dataclass(frozen=True)
class CustomColumnCriteria(QueryCriteria):
    """Criterion over a CorDapp-registered MappedSchema column
    (VaultCustomQueryCriteria, QueryCriteria.kt + the custom-column
    branch of HibernateQueryCriteriaParser.kt).

    SQL path: row-value subquery into the schema's own table; in-memory
    path: run the schema's `project` on the live state. States the
    schema does not apply to never match.
    """

    schema_name: str
    column: str
    predicate: ColumnPredicate
    status: str = UNCONSUMED

    def _schema(self):
        from .schemas import schema_by_name

        return schema_by_name(self.schema_name)

    def matches(self, row: VaultRow) -> bool:
        schema = self._schema()
        # keep backend parity: the SQL path raises on an unknown
        # column, so the in-memory path must too (not return False).
        # Validated once per criteria (matches runs per vault row).
        if not self.__dict__.get("_column_ok"):
            if self.column not in {c for c, _ in schema.columns}:
                raise ValueError(
                    f"schema {schema.name!r} has no column {self.column!r}"
                )
            object.__setattr__(self, "_column_ok", True)
        if not _status_match(self.status, row):
            return False
        data = row.state_and_ref.state.data
        if not isinstance(data, schema.applies_to):
            return False
        value = schema.project(data).get(self.column)
        if value is None:
            # SQL three-valued logic: NULL never satisfies any
            # comparison (incl. <>), and both backends must agree
            return False
        return _OPS[self.predicate.op](value, self.predicate.value)

    def sql(self) -> tuple[str, list]:
        schema = self._schema()
        if self.column not in {c for c, _ in schema.columns}:
            raise ValueError(
                f"schema {schema.name!r} has no column {self.column!r}"
            )
        ss, sp = _status_sql(self.status)
        frag = (
            f"({ss}) AND (v.ref_tx, v.ref_index) IN "
            f"(SELECT ref_tx, ref_index FROM {schema.table}"
            f" WHERE {self.column} {_SQL_OPS[self.predicate.op]} ?)"
        )
        return frag, sp + [self.predicate.value]


@dataclass(frozen=True)
class Or(QueryCriteria):
    left: QueryCriteria
    right: QueryCriteria

    def matches(self, row: VaultRow) -> bool:
        return self.left.matches(row) or self.right.matches(row)

    def sql(self) -> tuple[str, list]:
        ls, lp = self.left.sql()
        rs, rp = self.right.sql()
        return f"({ls}) OR ({rs})", lp + rp


def _needs_refine(criteria: QueryCriteria) -> bool:
    if isinstance(criteria, LinearStateQueryCriteria):
        return criteria.needs_refine()
    if isinstance(criteria, (And, Or)):
        return _needs_refine(criteria.left) or _needs_refine(criteria.right)
    return False


# -- paging & sorting --------------------------------------------------------


@dataclass(frozen=True)
class PageSpecification:
    """1-based pages (QueryCriteria.kt PageSpecification)."""

    page_number: int = 1
    page_size: int = 200

    def __post_init__(self):
        if self.page_number < 1 or self.page_size < 1:
            raise ValueError("bad page spec")


_SORT_COLUMNS = {
    "recorded_at": ("v.recorded_at", lambda r: r.recorded_at),
    "quantity": ("v.quantity", lambda r: r.quantity or 0),
    "contract_tag": ("v.contract_tag", lambda r: r.contract_tag),
    "ref": (
        "v.ref_tx, v.ref_index",
        lambda r: (r.state_and_ref.ref.txhash.bytes_, r.state_and_ref.ref.index),
    ),
}


@dataclass(frozen=True)
class Sort:
    column: str = "ref"
    descending: bool = False

    def __post_init__(self):
        if self.column not in _SORT_COLUMNS:
            raise ValueError(
                f"unsortable column {self.column!r}; "
                f"choose from {sorted(_SORT_COLUMNS)}"
            )


@dataclass(frozen=True)
class Page:
    """One result page + the total row count before paging
    (Vault.Page: states + totalStatesAvailable)."""

    states: tuple[StateAndRef, ...]
    total_states_available: int


# -- execution ---------------------------------------------------------------


def run_in_memory(
    rows: list[VaultRow],
    criteria: QueryCriteria,
    paging: PageSpecification = PageSpecification(),
    sorting: Sort = Sort(),
) -> Page:
    hits = [r for r in rows if criteria.matches(r)]
    _, key = _SORT_COLUMNS[sorting.column]
    hits.sort(key=key, reverse=sorting.descending)
    lo = (paging.page_number - 1) * paging.page_size
    page = hits[lo : lo + paging.page_size]
    return Page(tuple(r.state_and_ref for r in page), len(hits))


def run_sql(
    db,
    criteria: QueryCriteria,
    paging: PageSpecification = PageSpecification(),
    sorting: Sort = Sort(),
) -> Page:
    """Execute over the vault_states table (persistence.py schema). When
    a criterion needs Python refinement (e.g. external ids), rows are
    refined before paging so page boundaries stay correct."""
    where, params = criteria.sql()
    order_col, _ = _SORT_COLUMNS[sorting.column]
    direction = "DESC" if sorting.descending else "ASC"
    order = ", ".join(
        f"{c} {direction}" for c in order_col.split(", ")
    )
    base = (
        "SELECT v.ref_tx, v.ref_index, v.state, v.status, v.contract_tag,"
        " v.notary, v.quantity, v.token, v.issuer, v.linear_id,"
        " v.recorded_at FROM vault_states v"
        f" WHERE {where} ORDER BY {order}"
    )
    refine = _needs_refine(criteria)
    if not refine:
        lo = (paging.page_number - 1) * paging.page_size
        rows = db.query(base + " LIMIT ? OFFSET ?", (*params, paging.page_size, lo))
        total = db.query(
            f"SELECT COUNT(*) FROM vault_states v WHERE {where}", tuple(params)
        )[0][0]
        return Page(tuple(_sar_of(r) for r in rows), total)
    raw = db.query(base, tuple(params))
    # participant fingerprints only materialise if the criteria tree can
    # read them, and then in one batched query — not one per row
    fps_map = (
        _fps_map(db, [(bytes(r[0]), r[1]) for r in raw])
        if _needs_fps(criteria)
        else {}
    )
    vrows = [_vault_row_of(r, fps_map) for r in raw]
    hits = [v for v in vrows if criteria.matches(v)]
    lo = (paging.page_number - 1) * paging.page_size
    page = hits[lo : lo + paging.page_size]
    return Page(tuple(v.state_and_ref for v in page), len(hits))


def _needs_fps(criteria: QueryCriteria) -> bool:
    if isinstance(criteria, FungibleAssetQueryCriteria):
        return criteria.participant_key is not None
    if isinstance(criteria, (And, Or)):
        return _needs_fps(criteria.left) or _needs_fps(criteria.right)
    return False


def _fps_map(db, refs: list[tuple[bytes, int]]) -> dict:
    out: dict = {r: [] for r in refs}
    CHUNK = 100
    for i in range(0, len(refs), CHUNK):
        chunk = refs[i : i + CHUNK]
        where = " OR ".join("(ref_tx=? AND ref_index=?)" for _ in chunk)
        params = [x for ref in chunk for x in ref]
        for tx, idx, fp in db.query(
            f"SELECT ref_tx, ref_index, fingerprint FROM vault_parts"
            f" WHERE {where}",
            tuple(params),
        ):
            out[(bytes(tx), idx)].append(bytes(fp))
    return out


def _sar_of(r) -> StateAndRef:
    from ..core.contracts import StateRef
    from ..crypto.hashes import SecureHash

    return StateAndRef(
        ser.decode(bytes(r[2])), StateRef(SecureHash(bytes(r[0])), r[1])
    )


def _vault_row_of(r, fps_map: dict) -> VaultRow:
    sar = _sar_of(r)
    return VaultRow(
        state_and_ref=sar,
        status=UNCONSUMED if r[3] == 0 else CONSUMED,
        contract_tag=r[4],
        notary_name=r[5],
        quantity=r[6],
        product=r[7],
        issuer_name=r[8],
        linear_id=None if r[9] is None else bytes(r[9]),
        participant_fps=tuple(fps_map.get((bytes(r[0]), r[1]), ())),
        recorded_at=r[10],
    )


# ---------------------------------------------------------------------------
# wire registration — criteria travel over RPC (CordaRPCOps.vaultQueryBy
# takes the criteria AST from the client; the reference serializes the
# QueryCriteria object graph over Kryo/AMQP)

for _cls in (
    ColumnPredicate,
    FungibleAssetQueryCriteria,
    LinearStateQueryCriteria,
    And,
    Or,
    PageSpecification,
    Sort,
    Page,
):
    ser.serializable(_cls)

# VaultQueryCriteria may hold Python classes in contract_state_types;
# they normalise to tag strings on the wire (the SQL compiler and
# matcher treat both identically).
ser.register_custom(
    VaultQueryCriteria,
    "VaultQueryCriteria",
    lambda c: [
        c.status,
        None if c.contract_state_types is None else c._tags(),
        None if c.notary_names is None else list(c.notary_names),
        None if c.recorded_between is None else list(c.recorded_between),
    ],
    lambda v: VaultQueryCriteria(
        v[0],
        None if v[1] is None else tuple(v[1]),
        None if v[2] is None else tuple(v[2]),
        None if v[3] is None else tuple(v[3]),
    ),
)
