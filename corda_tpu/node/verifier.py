"""Out-of-process transaction verification — the north-star offload seam.

Reference architecture (SURVEY §2.6): `TransactionVerifierService` SPI
(core/.../node/services/TransactionVerifierService.kt:9-15) with an
out-of-process implementation that keeps a nonce→future handle map and
ships serialized transactions onto a `verifier.requests` queue
(node/.../transactions/OutOfProcessTransactionVerifierService.kt:19-73,
node-api/.../VerifierApi.kt:10-59); standalone workers attach to the
broker, consume requests, verify, and reply to a per-node response
queue (verifier/src/main/kotlin/net/corda/verifier/Verifier.kt:38-111).
Workers scale horizontally — the queue load-balances across however
many are attached (docs/source/out-of-process-verification.rst).

TPU-first redesign: the reference seam offloads *contract execution*
only (signatures are checked on the node JVM first,
SignedTransaction.kt:143-149). Here the worker is where the TPU lives,
so a request may also carry the `SignedTransaction`, and the worker
drains ALL signature checks across every request in its queue into ONE
`BatchSignatureVerifier.verify_batch` call — the queue → pad/bucket →
single jitted dispatch → scatter-results serving path (SURVEY §7
Phase 4). Store-and-forward: requests sent before any worker attaches
are buffered and flushed on the first `verifier.ready`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..core import serialization as ser
from ..core.transactions import LedgerTransaction, SignedTransaction
from ..crypto.batch_verifier import BatchSignatureVerifier, default_verifier
from ..utils.metrics import MetricRegistry
from . import messaging as msglib
from .services import TransactionVerifierService, _Future

TOPIC_READY = "verifier.ready"


# ---------------------------------------------------------------------------
# wire API (reference: node-api/.../VerifierApi.kt:10-59)


@ser.serializable
@dataclass(frozen=True)
class TxVerificationRequest:
    """One transaction to verify.

    `ltx` is the resolved transaction (contract execution input); when
    `stx` is present the worker additionally batch-verifies its attached
    signatures on the TPU — the redesign's widening of the reference
    seam (which ships only the LedgerTransaction)."""

    nonce: int
    ltx: LedgerTransaction
    response_address: str
    stx: Optional[SignedTransaction] = None


@ser.serializable
@dataclass(frozen=True)
class TxVerificationResponse:
    """Worker's reply: error is None on success, else `Type: message`
    (reference ships the serialized Throwable)."""

    nonce: int
    error: Optional[str] = None


@ser.serializable
@dataclass(frozen=True)
class WorkerReady:
    """Worker attach announcement (the Artemis analogue is the broker
    seeing a consumer on `verifier.requests`; our point-to-point fabric
    makes attachment an explicit message). Over the TCP fabric the
    worker advertises its own listen address so the node's resolver can
    open the request bridge back to it; in-memory fabrics leave
    host/port empty."""

    worker: str
    host: str = ""
    port: int = 0


# ---------------------------------------------------------------------------
# node side


class VerificationFailedError(Exception):
    """Worker reported the transaction invalid."""


class OutOfProcessTransactionVerifierService(TransactionVerifierService):
    """Nonce→future handle map over the message fabric.

    Reference: OutOfProcessTransactionVerifierService.kt:19-73 — same
    dropwizard metric set: duration timer, success/failure meters,
    in-flight gauge (:34-46). Futures complete on the node's message
    pump thread when the matching response arrives.
    """

    def __init__(
        self,
        messaging: msglib.MessagingService,
        metrics: Optional[MetricRegistry] = None,
        register_peer=None,   # Callable[[str, host, port], None] for TCP fabrics
        allowed_workers: Optional[set[str]] = None,
    ):
        self._messaging = messaging
        self._register_peer = register_peer
        # JAAS-role analogue (reference: NodeLoginModule's "verifier"
        # role, ArtemisMessagingServer.kt): only these authenticated
        # peer names may join the pool; None admits any authenticated
        # peer (dev mode).
        self._allowed_workers = allowed_workers
        self._pending: dict[int, list] = {}   # nonce -> [fut, t0, worker]
        self._workers: list[str] = []
        self._rr = 0
        self._buffer: list[TxVerificationRequest] = []
        self._nonce = 0
        self.metrics = metrics or MetricRegistry()
        self._duration = self.metrics.timer(
            "TransactionVerifierService.Verification.Duration"
        )
        self._success = self.metrics.meter(
            "TransactionVerifierService.Verification.Success"
        )
        self._failure = self.metrics.meter(
            "TransactionVerifierService.Verification.Failure"
        )
        self.metrics.gauge(
            "TransactionVerifierService.VerificationsInFlight",
            lambda: len(self._pending),
        )
        messaging.add_handler(msglib.TOPIC_VERIFIER_RES, self._on_response)
        messaging.add_handler(TOPIC_READY, self._on_ready)

    # -- SPI ---------------------------------------------------------------

    def verify(
        self, ltx: LedgerTransaction, stx: Optional[SignedTransaction] = None
    ) -> _Future:
        """Ship `ltx` (and optionally the signature batch) to a worker.
        The returned future completes when the response message is
        pumped; callers in flows should re-check it per pump cycle."""
        import time

        self._nonce += 1
        nonce = self._nonce
        fut = _Future()
        self._pending[nonce] = [fut, time.perf_counter(), None]
        req = TxVerificationRequest(
            nonce, ltx, self._messaging.my_address, stx
        )
        self._dispatch(req)
        return fut

    def wait(self, fut: _Future, timeout: float = 30.0) -> None:
        """Pump the fabric until `fut` completes, then raise/return its
        outcome. ONLY for callers that own the pump (the notary batch
        loop, tools, tests) — never from inside a flow handler, which
        already runs on the pump thread. Flow-side integration suspends
        the flow on the future instead (statemachine wait-for-future);
        until that is wired, hub.transaction_verifier stays in-memory
        and this service is driven by dedicated call sites, mirroring
        how the reference gates the choice behind config.verifierType
        (NodeMessagingClient.kt:116-118).

        Pump-less fabrics (the response handler fires on another
        thread) park on the future's condition variable with the
        remaining deadline — woken the instant the completion lands,
        instead of the old 10 ms poll-sleep spin."""
        import time

        pump = getattr(self._messaging, "pump", None)
        deadline = time.monotonic() + timeout
        while not fut.done:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            if pump is not None:
                pump(block=True, timeout=min(0.1, remaining))
            else:
                fut.wait(remaining)
        fut.result()

    @property
    def worker_count(self) -> int:
        return len(self._workers)

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    # -- internals ---------------------------------------------------------

    def _dispatch(self, req: TxVerificationRequest) -> None:
        if not self._workers:
            self._buffer.append(req)   # store-and-forward until attach
            return
        worker = self._workers[self._rr % len(self._workers)]
        self._rr += 1
        entry = self._pending.get(req.nonce)
        if entry is not None:
            entry[2] = worker   # bind nonce to its worker for auth below
        self._messaging.send(
            msglib.TOPIC_VERIFIER_REQ, ser.encode(req), worker
        )

    def _on_ready(self, msg: msglib.Message) -> None:
        ready = ser.decode(msg.payload)
        # The advertised worker name MUST be the fabric-authenticated
        # sender: a peer can only attach as itself, never claim another
        # node's name (prevents peer-table poisoning via register_peer
        # and pool-joining under a stolen identity).
        if ready.worker != msg.sender:
            return
        if (
            self._allowed_workers is not None
            and ready.worker not in self._allowed_workers
        ):
            return
        if ready.host and self._register_peer is not None:
            self._register_peer(ready.worker, ready.host, ready.port)
        if ready.worker not in self._workers:
            self._workers.append(ready.worker)
        buffered, self._buffer = self._buffer, []
        for req in buffered:
            self._dispatch(req)

    def _on_response(self, msg: msglib.Message) -> None:
        import time

        res: TxVerificationResponse = ser.decode(msg.payload)
        entry = self._pending.get(res.nonce)
        if entry is None:
            return   # duplicate / unknown (at-least-once upstream)
        fut, t0, worker = entry
        if worker is None or msg.sender != worker:
            return   # only the worker this nonce was dispatched to may answer
        del self._pending[res.nonce]
        self._duration.update(time.perf_counter() - t0)
        if res.error is None:
            self._success.mark()
            fut.set_result()
        else:
            self._failure.mark()
            fut.set_exception(VerificationFailedError(res.error))


# ---------------------------------------------------------------------------
# worker side


def request_ingest_pipeline(**kw):
    """An IngestPipeline configured for TxVerificationRequest frames:
    the envelope decodes in the pool, and the batched Merkle-id /
    staging stages run on the carried SignedTransaction (None for
    contract-only requests)."""
    from .ingest import IngestPipeline

    return IngestPipeline(extract=lambda req: req.stx, **kw)


class VerifierWorker:
    """Standalone verification worker (reference: Verifier.kt:38-111).

    Handles `verifier.requests`: rebuilds nothing (the request is fully
    resolved), batch-verifies every attached signature across ALL queued
    requests in one `verify_batch` dispatch, runs contract verification,
    and replies per-request. With `batch_window=0` each message is
    processed as it is pumped; a positive window lets the fabric deliver
    several requests first so one TPU dispatch covers them all — the
    batching-notary serving path shares this drain.

    With an `ingest` pipeline (node/ingest.py) attached, the worker is
    the OutOfProcessTransactionVerifierService pool's pipelined end:
    request frames route through the fabric's ring seam
    (messaging.add_ring) into the sharded decode pool — decode of the
    NEXT delivery round overlaps this round's verify dispatch — each
    round's transaction ids come from the batched Merkle-id stage, and
    `drain` consumes the PRE-STAGED signature requests the pipeline
    memoised at decode time instead of re-staging them here. A
    malformed frame is dropped and metered (Verifier.Failed) in its
    slot; the rest of the round proceeds.
    """

    def __init__(
        self,
        messaging: msglib.MessagingService,
        node_address: str,
        batch_verifier: Optional[BatchSignatureVerifier] = None,
        metrics: Optional[MetricRegistry] = None,
        batch_window: int = 0,
        advertised_address: Optional[tuple[str, int]] = None,
        ingest=None,               # Optional[corda_tpu.node.ingest.IngestPipeline]
        ingest_window: int = 8192,
        clock=None,                # node-clock source for deadline expiry;
        #                            None = wall clock (production workers —
        #                            deadlines are minted on wall-clock
        #                            nodes); simulated-time rigs MUST pass
        #                            the TestClock that minted theirs
        health=None,               # Optional[utils.health.HealthMonitor]:
        #                            registers a `verifier.drain` heartbeat
        #                            the drain loop beats (progress =
        #                            requests answered, queue depth = ring
        #                            + handler backlog) so a wedged drain
        #                            thread trips the watchdog
        perf=None,                 # Optional[utils.perf.PerfPlane]: the
        #                            worker's verified-request counter
        #                            becomes an in-process rate history
        #                            key, and an ingest pipeline built
        #                            with the same plane reports its
        #                            stage seconds there
    ):
        self._messaging = messaging
        self._verifier = batch_verifier or default_verifier()
        self._clock = clock
        self._batch_window = batch_window
        self._queue: list[TxVerificationRequest] = []
        # handler-fed frames awaiting the ingest pipeline, as
        # (payload, trace header, deadline header) so propagated trace
        # contexts survive into the pipeline's per-frame spans and
        # expired requests shed pre-decode
        self._raw: list[tuple[bytes, Optional[tuple], Optional[int]]] = []
        self.metrics = metrics or MetricRegistry()
        self._verified = self.metrics.meter("Verifier.Verified")
        self._failed = self.metrics.meter("Verifier.Failed")
        # deadline-expired frames dropped pre-decode (QoS sheds are not
        # failures: the sender stopped wanting the answer)
        self._shed = self.metrics.meter("Verifier.Shed")
        self._batch_sizes = self.metrics.histogram("Verifier.BatchSize")
        self._ingest = ingest
        self._ring = None
        if ingest is not None:
            from .ingest import IngestRing

            try:
                ring = IngestRing(depth=ingest_window)
                # metrics: ring depth / high-water / parked gauges on
                # this worker's registry (messaging.register_ring_gauges)
                messaging.add_ring(
                    msglib.TOPIC_VERIFIER_REQ, ring, metrics=self.metrics
                )
                self._ring = ring
            except NotImplementedError:
                # fabric has no ring seam: the handler path below still
                # feeds the pipeline via self._raw
                pass
        if perf is not None:
            perf.watch_rate(
                "verifier_worker_verified_per_sec",
                lambda: self._verified.count,
            )
            if ingest is not None and getattr(ingest, "perf", None) is None:
                ingest.perf = perf
        self._heartbeat = None
        if health is not None:
            self._heartbeat = health.heartbeat(
                "verifier.drain",
                queue_depth=lambda: len(self._queue)
                + len(self._raw)
                + (len(self._ring) if self._ring is not None else 0),
            )
            if self._ring is not None:
                # ring saturation / parked-frame growth alerting over
                # the backpressure seam (the gauges made it visible on
                # /metrics; this makes it PAGE)
                parked = getattr(self._messaging, "parked_count", None)
                health.watch_ring(
                    msglib.TOPIC_VERIFIER_REQ,
                    lambda: len(self._ring),
                    self._ring.depth,
                    parked_fn=(
                        (lambda: parked(msglib.TOPIC_VERIFIER_REQ))
                        if parked is not None else None
                    ),
                )
        messaging.add_handler(msglib.TOPIC_VERIFIER_REQ, self._on_request)
        # announce attachment so buffered requests flush to us; over TCP
        # the advertised address lets the node bridge back
        host, port = advertised_address or ("", 0)
        messaging.send(
            TOPIC_READY,
            ser.encode(WorkerReady(messaging.my_address, host, port)),
            node_address,
        )

    def _on_request(self, msg: msglib.Message) -> None:
        if self._ingest is not None:
            self._raw.append((msg.payload, msg.trace, msg.deadline))
            if len(self._raw) > self._batch_window:
                self.drain()
            return
        self._queue.append(ser.decode(msg.payload))
        if len(self._queue) > self._batch_window:
            self.drain()

    def _pull_ingested(self) -> None:
        """Move every waiting frame through the ingest pipeline into
        the request queue: ring frames first (fabric fast path), then
        handler-fed raw payloads. Each frame's propagated trace header
        (Message.trace) rides into the pipeline so the worker's ingest
        spans join the sender's trace, and its deadline header rides
        too so an expired request sheds PRE-DECODE (node/qos.py) —
        the worker never spends CTS/verify work on a request whose
        node-side future already timed out."""
        payloads: list[bytes] = []
        traces: list = []
        deadlines: list = []
        if self._ring is not None:
            for m in self._ring.drain():
                payloads.append(m.payload)
                traces.append(m.trace)
                deadlines.append(getattr(m, "deadline", None))
            # frames parked while the ring was full re-enter it for the
            # next drain — the backpressure release valve
            retry = getattr(self._messaging, "retry_parked", None)
            if retry is not None:
                retry(msglib.TOPIC_VERIFIER_REQ)
        if self._raw:
            for payload, trace, deadline in self._raw:
                payloads.append(payload)
                traces.append(trace)
                deadlines.append(deadline)
            self._raw = []
        if not payloads:
            return
        from .qos import DeadlineExpired

        for e in self._ingest.ingest(
            payloads,
            trace_parents=traces,
            deadlines=deadlines,
            now_micros=(
                self._clock.now_micros() if self._clock is not None else None
            ),
        ):
            if isinstance(e.error, DeadlineExpired):
                self._shed.mark()     # shed, not failed: QoS drop
                continue
            if e.error is not None:
                self._failed.mark()   # malformed frame: its slot only
                continue
            self._queue.append(e.obj)

    def drain(self) -> int:
        """Process every queued request; one signature-batch dispatch
        covers all of them. Returns how many were processed."""
        if self._ingest is not None:
            self._pull_ingested()
        pending, self._queue = self._queue, []
        if not pending:
            if self._heartbeat is not None:
                self._heartbeat.beat()
            return 0
        sig_reqs, spans = [], []
        for req in pending:
            if req.stx is not None:
                rs = req.stx.signature_requests()
                spans.append((len(sig_reqs), len(rs)))
                sig_reqs.extend(rs)
            else:
                spans.append((0, 0))
        self._batch_sizes.update(len(sig_reqs))
        batch_error: Optional[str] = None
        sig_ok: list[bool] = []
        try:
            sig_ok = self._verifier.verify_batch(sig_reqs) if sig_reqs else []
        except Exception as e:
            # a failed batch dispatch (device lost, kernel error) must
            # still answer every queued request — silence would leave
            # all node-side futures hanging forever
            batch_error = f"VerifierDispatchError: {type(e).__name__}: {e}"
        # the signature gate runs FIRST (sig results are already on
        # the host here): a request with invalid signatures must not
        # reach contract execution at all — the contract phase can run
        # attachment-carried sandboxed code, and executing it for a
        # transaction nobody signed is free attack surface
        sig_errs: list[Optional[Exception]] = []
        for req, (off, n) in zip(pending, spans):
            err: Optional[Exception] = None
            if batch_error is None and req.stx is not None:
                try:
                    req.stx.raise_on_invalid(sig_ok[off : off + n])
                except Exception as e:  # noqa: BLE001 - reported per req
                    err = e
            sig_errs.append(err)
        # contract phase: grouped-by-contract across the sig-valid
        # requests (core/batch_verify.py) — the same sweep the
        # batching notary uses. Guarded: pending is already detached
        # from self._queue, so an escaping exception would strand
        # every node-side future.
        contract_errs: list[Optional[Exception]] = [None] * len(pending)
        live = [
            i for i, e in enumerate(sig_errs)
            if batch_error is None and e is None
        ]
        if live:
            from ..core.batch_verify import verify_ledger_batch

            try:
                batch = verify_ledger_batch([pending[i].ltx for i in live])
                for i, cerr in zip(live, batch):
                    contract_errs[i] = cerr
            except Exception as e:  # noqa: BLE001 - answer, don't strand
                for i in live:
                    contract_errs[i] = e
        for req, serr, cerr in zip(pending, sig_errs, contract_errs):
            error = batch_error
            if error is None:
                e = serr or cerr
                if e is not None:
                    error = f"{type(e).__name__}: {e}"
            if error is None:
                self._verified.mark()
            else:
                self._failed.mark()
            self._messaging.send(
                msglib.TOPIC_VERIFIER_RES,
                ser.encode(TxVerificationResponse(req.nonce, error)),
                req.response_address,
            )
        if self._heartbeat is not None:
            self._heartbeat.beat(progress=len(pending))
        return len(pending)


# ---------------------------------------------------------------------------
# standalone worker process (reference: Verifier.main, Verifier.kt:50-88)


def main(argv: Optional[list[str]] = None) -> None:
    """`python -m corda_tpu.node.verifier --name w1 --node nodeA
    --node-host 127.0.0.1 --node-port 10001 --db /tmp/w1.db`

    Connects a fabric endpoint to the requesting node, announces
    readiness, and pumps forever — the process-level analogue of the
    reference's standalone verifier jar.
    """
    import argparse
    import sys

    from ..crypto import schemes
    from ..crypto.batch_verifier import CpuBatchVerifier, TpuBatchVerifier
    from .fabric import FabricEndpoint, PeerAddress
    from .persistence import NodeDatabase

    p = argparse.ArgumentParser(description="out-of-process verifier worker")
    p.add_argument("--name", required=True)
    p.add_argument("--node", required=True, help="requesting node's name")
    p.add_argument("--node-host", default="127.0.0.1")
    p.add_argument("--node-port", type=int, required=True)
    p.add_argument("--db", required=True)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument(
        "--cpu", action="store_true", help="use the CPU reference verifier"
    )
    p.add_argument("--batch-window", type=int, default=0)
    p.add_argument(
        "--ingest-shards",
        type=int,
        default=0,
        help="enable the pipelined wire-ingest path with this many "
        "decode shards (0 = per-message decode, the default)",
    )
    p.add_argument(
        "--profile-hz",
        type=float,
        default=0.0,
        help="continuous sampling-profiler rate over this worker's "
        "threads (utils/perf.py; 0 = off). Folded stacks are written "
        "to --profile-out on shutdown",
    )
    p.add_argument(
        "--profile-out",
        default="",
        help="where the folded collapsed stacks land on shutdown "
        "(flamegraph.pl format; default <db>.folded)",
    )
    p.add_argument(
        "--app",
        action="append",
        default=[],
        help="contract module(s) to import so their states/commands are "
        "codec-registered (the AttachmentsClassLoader analogue — the "
        "reference worker classloads contract code from attachments, "
        "AttachmentsClassLoader.kt:23)",
    )
    args = p.parse_args(argv)

    import importlib

    for mod in args.app or ["corda_tpu.finance"]:
        importlib.import_module(mod)

    keypair = schemes.generate_keypair(
        seed=args.seed if args.seed is not None else 1
    )
    db = NodeDatabase(args.db)
    node_addr = PeerAddress(args.node_host, args.node_port, None)
    ep = FabricEndpoint(
        args.name,
        keypair,
        db,
        resolve=lambda peer: node_addr if peer == args.node else None,
    )
    ep.start()
    verifier = CpuBatchVerifier() if args.cpu else TpuBatchVerifier()
    ingest = (
        request_ingest_pipeline(shards=args.ingest_shards)
        if args.ingest_shards
        else None
    )
    # the production worker watches itself: the drain heartbeat +
    # ring rule live on a real HealthMonitor ticked by the pump loop,
    # so a wedged drain is visible in-process (and on the worker's
    # registry as Health.* gauges), not only when node-side futures
    # start timing out
    from ..utils.health import HealthMonitor
    from ..utils.perf import PerfPlane, PerfPolicy

    health = HealthMonitor()
    # the production worker attributes itself too: kernel
    # compile-vs-execute accounting (the TPU verifier records into the
    # plane's process-default), drain-rate history, and — with
    # --profile-hz — continuous folded-stack profiling of the pump /
    # decode-pool threads
    perf = PerfPlane(policy=PerfPolicy(profile_hz=args.profile_hz or 19.0))
    health.watch_perf(perf)
    if args.profile_hz:
        perf.profiler.start()
    worker = VerifierWorker(
        ep,
        args.node,
        batch_verifier=verifier,
        batch_window=args.batch_window,
        advertised_address=("127.0.0.1", ep.listen_port),
        ingest=ingest,
        health=health,
        perf=perf,
    )
    try:
        while True:
            ep.pump(block=True, timeout=1.0)
            worker.drain()
            health.tick()
            perf.tick()
    except KeyboardInterrupt:
        pass
    finally:
        perf.profiler.stop()
        if args.profile_hz and perf.profiler.samples:
            # the capture must land somewhere retrievable — the worker
            # CLI has no web gateway to serve /profile from
            out_path = args.profile_out or (args.db + ".folded")
            try:
                with open(out_path, "w") as f:
                    f.write(perf.profiler.collapsed() + "\n")
                print(f"profile: folded stacks -> {out_path}",
                      file=sys.stderr)
            except OSError as e:
                print(f"profile: could not write {out_path}: {e}",
                      file=sys.stderr)
        ep.stop()
        db.close()


if __name__ == "__main__":
    main()
