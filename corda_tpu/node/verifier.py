"""Out-of-process transaction verification — the north-star offload seam.

Reference architecture (SURVEY §2.6): `TransactionVerifierService` SPI
(core/.../node/services/TransactionVerifierService.kt:9-15) with an
out-of-process implementation that keeps a nonce→future handle map and
ships serialized transactions onto a `verifier.requests` queue
(node/.../transactions/OutOfProcessTransactionVerifierService.kt:19-73,
node-api/.../VerifierApi.kt:10-59); standalone workers attach to the
broker, consume requests, verify, and reply to a per-node response
queue (verifier/src/main/kotlin/net/corda/verifier/Verifier.kt:38-111).
Workers scale horizontally — the queue load-balances across however
many are attached (docs/source/out-of-process-verification.rst).

TPU-first redesign: the reference seam offloads *contract execution*
only (signatures are checked on the node JVM first,
SignedTransaction.kt:143-149). Here the worker is where the TPU lives,
so a request may also carry the `SignedTransaction`, and the worker
drains ALL signature checks across every request in its queue into ONE
`BatchSignatureVerifier.verify_batch` call — the queue → pad/bucket →
single jitted dispatch → scatter-results serving path (SURVEY §7
Phase 4). Store-and-forward: requests sent before any worker attaches
are buffered and flushed on the first `verifier.ready`.
"""

from __future__ import annotations

import random
import threading
from ..utils import locks
import time
from dataclasses import dataclass, replace
from typing import Optional

from ..core import serialization as ser
from ..core.transactions import LedgerTransaction, SignedTransaction
from ..crypto.batch_verifier import BatchSignatureVerifier, default_verifier
from ..utils.metrics import MetricRegistry
from . import messaging as msglib
from .services import TransactionVerifierService, _Future

TOPIC_READY = "verifier.ready"


# ---------------------------------------------------------------------------
# wire API (reference: node-api/.../VerifierApi.kt:10-59)


@ser.serializable
@dataclass(frozen=True)
class TxVerificationRequest:
    """One transaction to verify.

    `ltx` is the resolved transaction (contract execution input); when
    `stx` is present the worker additionally batch-verifies its attached
    signatures on the TPU — the redesign's widening of the reference
    seam (which ships only the LedgerTransaction).

    `attempt` is the node-side dispatch incarnation of this nonce: a
    re-dispatch after a worker loss or timeout bumps it, the worker
    echoes it back, and the answer path only accepts the CURRENT
    incarnation — the at-least-once dedupe that lets the node safely
    re-send in-flight work to a survivor."""

    nonce: int
    ltx: LedgerTransaction
    response_address: str
    stx: Optional[SignedTransaction] = None
    attempt: int = 0


@ser.serializable
@dataclass(frozen=True)
class TxVerificationResponse:
    """Worker's reply: error is None on success, else `Type: message`
    (reference ships the serialized Throwable). `attempt` echoes the
    request's dispatch incarnation so a stale answer (computed by a
    worker the nonce was already re-dispatched away from) is rejected
    instead of racing the live one."""

    nonce: int
    error: Optional[str] = None
    attempt: int = 0


@ser.serializable
@dataclass(frozen=True)
class WorkerReady:
    """Worker attach announcement (the Artemis analogue is the broker
    seeing a consumer on `verifier.requests`; our point-to-point fabric
    makes attachment an explicit message). Over the TCP fabric the
    worker advertises its own listen address so the node's resolver can
    open the request bridge back to it; in-memory fabrics leave
    host/port empty."""

    worker: str
    host: str = ""
    port: int = 0


# ---------------------------------------------------------------------------
# node side


class VerificationFailedError(Exception):
    """Worker reported the transaction invalid."""


class VerificationTimeoutError(Exception):
    """The nonce's answer never arrived inside its deadline. Names the
    nonce, the worker it was last bound to and the elapsed time — the
    typed replacement for the old silent fall-through to a bare
    incomplete-future error."""

    def __init__(self, nonce: int, worker: Optional[str], elapsed_micros: int):
        self.nonce = nonce
        self.worker = worker
        self.elapsed_micros = elapsed_micros
        super().__init__(
            f"verification of nonce {nonce} timed out after "
            f"{elapsed_micros / 1e6:.3f}s (last bound to worker "
            f"{worker or '<none attached>'})"
        )


class WorkerLostError(Exception):
    """Every dispatch attempt for this nonce died with its worker: the
    pool lost the workers faster than redispatch could recover."""

    def __init__(self, nonce: int, workers: list, attempts: int):
        self.nonce = nonce
        self.workers = list(workers)
        self.attempts = attempts
        super().__init__(
            f"nonce {nonce} lost {attempts} dispatch attempt(s) to dead "
            f"workers {self.workers}"
        )


@dataclass(frozen=True)
class RedispatchPolicy:
    """Self-healing knobs for the out-of-process pool.

    `lease_micros` — a worker that has not re-announced `WorkerReady`
    within this window is considered dead and detached (its in-flight
    nonces re-dispatch to survivors). `attempt_timeout_micros` — one
    dispatch's answer deadline: past it the nonce re-dispatches (the
    bound worker may be alive but its answer lost, or it restarted
    within its lease), bumping the attempt so the late original answer
    is rejected. `request_timeout_micros` — the OVERALL per-nonce
    deadline; past it the future fails with a typed error instead of
    hanging. Redispatch after a worker LOSS waits a capped exponential
    backoff with +/- `backoff_jitter` (seeded, deterministic) so a
    flapping pool is not hammered in lockstep. `hedge_quantile` > 0
    additionally duplicates straggler nonces (older than that quantile
    of the observed duration histogram, floored at
    `hedge_min_micros`) onto a second worker — first valid answer
    wins."""

    lease_micros: int = 10_000_000
    attempt_timeout_micros: int = 5_000_000
    request_timeout_micros: int = 30_000_000
    backoff_base_micros: int = 100_000
    backoff_cap_micros: int = 2_000_000
    backoff_jitter: float = 0.25
    max_attempts: int = 4
    hedge_quantile: float = 0.0
    hedge_min_micros: int = 50_000


class _PendingVerify:
    """One in-flight nonce: its future, the full request (kept so a
    worker loss can re-dispatch it), the worker+attempt binding the
    answer path authenticates against, and the retry/hedge state the
    tick loop walks."""

    __slots__ = (
        "req", "fut", "t0", "enqueued_micros", "dispatched_micros",
        "worker", "attempt", "dispatches", "retry_at_micros",
        "hedged_to", "lost_workers",
    )

    def __init__(self, req, fut, t0: float, now_micros: int):
        self.req = req
        self.fut = fut
        self.t0 = t0
        self.enqueued_micros = now_micros
        self.dispatched_micros: Optional[int] = None
        self.worker: Optional[str] = None
        self.attempt = 0
        self.dispatches = 0
        self.retry_at_micros: Optional[int] = None
        self.hedged_to: Optional[str] = None
        self.lost_workers: list[str] = []


class OutOfProcessTransactionVerifierService(TransactionVerifierService):
    """Nonce→future handle map over the message fabric, self-healing.

    Reference: OutOfProcessTransactionVerifierService.kt:19-73 — same
    dropwizard metric set: duration timer, success/failure meters,
    in-flight gauge (:34-46). Futures complete on the node's message
    pump thread when the matching response arrives.

    Where the reference leans on the Artemis broker to rebalance
    consumers when a worker dies, this point-to-point port heals
    itself: workers hold LEASES renewed by periodic `WorkerReady`
    heartbeats (the worker's pump loop re-sends them); `tick()` —
    driven by the node pump — detaches lease-expired workers and
    re-dispatches their in-flight nonces to survivors with capped
    exponential backoff + jitter, answers are deduped by
    nonce→attempt binding (a stale incarnation's answer is rejected),
    stragglers can be hedged onto a second worker, and a nonce that
    exhausts its deadline fails with a typed
    VerificationTimeoutError/WorkerLostError instead of stranding.

    Threading model: on the in-process pump fabrics everything here
    runs on the pump thread, but NOT always — on pump-less fabrics the
    response/ready handlers fire on the fabric's receive thread, and
    `wait()` drives `tick()` from whichever thread owns the future. A
    single service lock therefore guards ALL pool state (`_pending`,
    `_workers`, `_leases`, `_buffer`, `_rr`, `_nonce`); the lock spans
    pure bookkeeping only — fabric sends, `register_peer` callbacks
    and future resolutions (whose done-callbacks run arbitrary code)
    are collected under the lock and performed AFTER it is released,
    so the pump-hot redispatch path never does I/O under the service
    lock and no callback can re-enter it (tools/lint blocking pass
    holds this line).
    """

    def __init__(
        self,
        messaging: msglib.MessagingService,
        metrics: Optional[MetricRegistry] = None,
        register_peer=None,   # Callable[[str, host, port], None] for TCP fabrics
        allowed_workers: Optional[set[str]] = None,
        clock=None,           # node clock for lease/timeout judgement;
        #                       None = wall micros (production). Rigs on
        #                       a TestClock MUST pass it.
        policy: Optional[RedispatchPolicy] = None,
    ):
        self._messaging = messaging
        self._register_peer = register_peer
        # JAAS-role analogue (reference: NodeLoginModule's "verifier"
        # role, ArtemisMessagingServer.kt): only these authenticated
        # peer names may join the pool; None admits any authenticated
        # peer (dev mode).
        self._allowed_workers = allowed_workers
        self._clock = clock
        self.policy = policy or RedispatchPolicy()
        self._rng = random.Random(0xFA17)   # jitter: seeded, deterministic
        # guards the pool state below; never held across a fabric
        # send, a register_peer callback or a future resolution
        self._lock = locks.make_lock(
            "OutOfProcessTransactionVerifierService._lock"
        )
        self._pending: dict[int, _PendingVerify] = {}
        self._workers: list[str] = []              # attach order (RR)
        self._leases: dict[str, int] = {}          # worker -> last-ready us
        self._incarnations: dict[str, int] = {}    # worker -> attach count
        self._rr = 0
        self._buffer: list[_PendingVerify] = []    # store-and-forward
        self._nonce = 0
        self._last_lost_micros: Optional[int] = None
        self.metrics = metrics or MetricRegistry()
        self._duration = self.metrics.timer(
            "TransactionVerifierService.Verification.Duration"
        )
        self._success = self.metrics.meter(
            "TransactionVerifierService.Verification.Success"
        )
        self._failure = self.metrics.meter(
            "TransactionVerifierService.Verification.Failure"
        )
        self._redispatched = self.metrics.meter("Verifier.Redispatched")
        self._hedged_meter = self.metrics.meter("Verifier.Hedged")
        self._workers_lost = self.metrics.meter("Verifier.WorkersLost")
        self.metrics.gauge(
            "TransactionVerifierService.VerificationsInFlight",
            lambda: len(self._pending),
        )
        # the previously-invisible pool state, as gauges next to the
        # duration histogram: live /metrics answers "is the pool
        # draining, buffering, or starved?" without a debugger
        self.metrics.gauge("Verifier.InFlight", lambda: len(self._pending))
        self.metrics.gauge("Verifier.Buffered", lambda: len(self._buffer))
        self.metrics.gauge("Verifier.Workers", lambda: len(self._workers))
        # transaction lifecycle ledger (utils/txstory.py): wired by
        # node.py / rigs; every dispatch / redispatch / hedge / answer
        # stamps a per-attempt event keyed by the transaction id —
        # the "per-attempt verify history" in GET /tx/<id>
        self.txstory = None
        messaging.add_handler(msglib.TOPIC_VERIFIER_RES, self._on_response)
        messaging.add_handler(TOPIC_READY, self._on_ready)

    def _story_tx(self, entry: "_PendingVerify") -> Optional[str]:
        ltx = getattr(entry.req, "ltx", None)
        tid = getattr(ltx, "id", None)
        return str(tid) if tid is not None else None

    def _now_micros(self) -> int:
        if self._clock is not None:
            return self._clock.now_micros()
        return time.time_ns() // 1_000

    # -- SPI ---------------------------------------------------------------

    def verify(
        self, ltx: LedgerTransaction, stx: Optional[SignedTransaction] = None
    ) -> _Future:
        """Ship `ltx` (and optionally the signature batch) to a worker.
        The returned future completes when the response message is
        pumped; callers in flows should re-check it per pump cycle."""
        fut = _Future()
        with self._lock:
            self._nonce += 1
            nonce = self._nonce
            fut.nonce = nonce   # wait() names it in its typed timeout
            req = TxVerificationRequest(
                nonce, ltx, self._messaging.my_address, stx
            )
            entry = _PendingVerify(
                req, fut, time.perf_counter(), self._now_micros()
            )
            self._pending[nonce] = entry
            send = self._dispatch_locked(entry)
        self._send_all((send,) if send else ())
        return fut

    def wait(self, fut: _Future, timeout: float = 30.0) -> None:
        """Pump the fabric until `fut` completes, then raise/return its
        outcome. ONLY for callers that own the pump (the notary batch
        loop, tools, tests) — never from inside a flow handler, which
        already runs on the pump thread. Flow-side integration suspends
        the flow on the future instead (statemachine wait-for-future);
        until that is wired, hub.transaction_verifier stays in-memory
        and this service is driven by dedicated call sites, mirroring
        how the reference gates the choice behind config.verifierType
        (NodeMessagingClient.kt:116-118).

        Pump-less fabrics (the response handler fires on another
        thread) park on the future's condition variable with the
        remaining deadline — woken the instant the completion lands.
        On deadline the wait raises a typed VerificationTimeoutError
        naming the nonce, its bound worker and the elapsed time —
        never `fut.result()` on an incomplete future, whose bare
        "still pending" error says nothing about WHAT timed out."""
        pump = getattr(self._messaging, "pump", None)
        t_start = time.monotonic()
        deadline = t_start + timeout
        while not fut.done:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            if pump is not None:
                pump(block=True, timeout=min(0.1, remaining))
                self.tick()
            else:
                fut.wait(remaining)
        if not fut.done:
            nonce = getattr(fut, "nonce", -1)
            entry = self._pending.get(nonce)
            raise VerificationTimeoutError(
                nonce,
                entry.worker if entry is not None else None,
                int((time.monotonic() - t_start) * 1e6),
            )
        fut.result()

    @property
    def worker_count(self) -> int:
        return len(self._workers)

    @property
    def in_flight(self) -> int:
        return len(self._pending)

    @property
    def buffered(self) -> int:
        return len(self._buffer)

    def incarnation_of(self, worker: str) -> int:
        """How many times `worker` has attached (0 = never seen)."""
        return self._incarnations.get(worker, 0)

    # -- self-healing ------------------------------------------------------

    def tick(self, now: Optional[int] = None) -> None:
        """One self-healing pass, driven by the node pump (or a test
        clock): expire worker leases (detaching the dead and
        re-dispatching their in-flight nonces), time out / retry
        pending nonces, and hedge stragglers. Bookkeeping happens
        under the service lock; the collected sends and failure
        resolutions run after it releases."""
        if now is None:
            now = self._now_micros()
        pol = self.policy
        sends: list[tuple] = []
        failures: list[tuple] = []   # (future, typed exception)
        # the hedge threshold reads the duration histogram's own lock —
        # taken before the service lock, never under it
        hedge_after = self._hedge_after_micros()
        with self._lock:
            # 1 — lease expiry: a worker silent past its lease is dead
            for worker in [
                w for w in self._workers
                if now - self._leases.get(w, now) > pol.lease_micros
            ]:
                self._detach_worker_locked(worker, now)
            # 2 — per-nonce deadlines, retries, hedging
            for nonce, entry in list(self._pending.items()):
                elapsed = now - entry.enqueued_micros
                if elapsed > pol.request_timeout_micros:
                    failures.append(self._fail_locked(nonce, entry, elapsed))
                    continue
                if entry.worker is None:
                    # unbound (its worker died, or it never had one):
                    # retry once the backoff passes and a worker exists
                    if (
                        self._workers
                        and (
                            entry.retry_at_micros is None
                            or now >= entry.retry_at_micros
                        )
                    ):
                        self._retry_or_fail_locked(
                            nonce, entry, elapsed,
                            entry.lost_workers, sends, failures,
                        )
                    continue
                if (
                    pol.attempt_timeout_micros
                    and entry.dispatched_micros is not None
                    and now - entry.dispatched_micros
                    > pol.attempt_timeout_micros
                ):
                    # the bound worker is (or looks) alive but this
                    # attempt's answer never came — lost frame, or a
                    # same-name restart inside the lease. Re-dispatch
                    # NOW (prefer a different worker); the attempt bump
                    # rejects the original answer if it limps in later.
                    self._retry_or_fail_locked(
                        nonce, entry, elapsed,
                        entry.lost_workers + [entry.worker],
                        sends, failures,
                    )
                    continue
                if (
                    hedge_after is not None
                    and entry.hedged_to is None
                    and len(self._workers) > 1
                    and entry.dispatched_micros is not None
                    and now - entry.dispatched_micros >= hedge_after
                ):
                    send = self._hedge_locked(entry)
                    if send:
                        sends.append(send)
        # failures FIRST: _fail_locked already removed these nonces
        # from _pending, so if a fabric send raised before resolution
        # the futures could never complete (late responses drop at the
        # `entry is None` guard) — typed-error delivery must not
        # depend on the sends succeeding
        for fut, exc in failures:
            fut.set_exception(exc)
        self._send_all(sends)

    def _retry_or_fail_locked(
        self, nonce, entry, elapsed, exclude, sends, failures
    ) -> None:
        """Re-dispatch one unbound / attempt-timed-out nonce — or fail
        it once its attempts are spent — collecting the send or the
        typed failure for the caller to perform after the lock
        releases."""
        if entry.dispatches >= self.policy.max_attempts:
            failures.append(self._fail_locked(nonce, entry, elapsed))
            return
        self._redispatched.mark()
        send = self._dispatch_locked(entry, exclude=exclude)
        if send:
            sends.append(send)

    def _hedge_after_micros(self) -> Optional[int]:
        pol = self.policy
        if pol.hedge_quantile <= 0:
            return None
        q = 0.0
        hist = getattr(self._duration, "histogram", None)
        if hist is not None and hist.count:
            q = float(hist.quantile(pol.hedge_quantile)) * 1e6
        return max(int(q), pol.hedge_min_micros)

    def _hedge_locked(self, entry: _PendingVerify) -> Optional[tuple]:
        """Duplicate a straggler onto a different worker, SAME attempt:
        either copy's answer is valid, the first one wins, the other is
        deduped by the nonce having left the pending map. Returns the
        send for the caller to perform outside the lock."""
        others = [w for w in self._workers if w != entry.worker]
        if not others:
            return None
        worker = others[self._rr % len(others)]
        self._rr += 1
        entry.hedged_to = worker
        self._hedged_meter.mark()
        if self.txstory is not None:
            tid = self._story_tx(entry)
            if tid is not None:
                self.txstory.record(
                    tid, "verify.hedge",
                    attempt=entry.attempt, worker=worker,
                    nonce=entry.req.nonce,
                )
        return (msglib.TOPIC_VERIFIER_REQ, entry.req, worker)

    def _detach_worker_locked(self, worker: str, now: int) -> None:
        self._workers.remove(worker)
        self._leases.pop(worker, None)
        self._workers_lost.mark()
        self._last_lost_micros = now
        pol = self.policy
        for entry in self._pending.values():
            touched = entry.worker == worker
            if entry.hedged_to == worker:
                entry.hedged_to = None
            if not touched:
                continue
            entry.worker = None
            entry.lost_workers.append(worker)
            retries = len(entry.lost_workers)
            backoff = min(
                pol.backoff_cap_micros,
                pol.backoff_base_micros * (1 << (retries - 1)),
            )
            jitter = 1.0 + pol.backoff_jitter * (2 * self._rng.random() - 1)
            entry.retry_at_micros = now + int(backoff * jitter)

    def _fail_locked(
        self, nonce: int, entry: _PendingVerify, elapsed: int
    ) -> tuple:
        """Remove a dead nonce under the lock; the caller resolves the
        returned (future, exception) AFTER releasing it — set_exception
        runs done-callbacks, which must never fire under the service
        lock."""
        del self._pending[nonce]
        if entry in self._buffer:
            self._buffer.remove(entry)
        self._failure.mark()
        if entry.lost_workers and entry.worker is None:
            exc: Exception = WorkerLostError(
                nonce, entry.lost_workers, entry.dispatches
            )
        else:
            exc = VerificationTimeoutError(nonce, entry.worker, elapsed)
        if self.txstory is not None:
            tid = self._story_tx(entry)
            if tid is not None:
                self.txstory.record(
                    tid, "verify.failed",
                    attempt=entry.attempt, nonce=nonce,
                    error=type(exc).__name__,
                )
        return entry.fut, exc

    def watch_health(self, monitor) -> None:
        """Register the `verifier.pool_degraded` rule on a
        HealthMonitor (utils/health.py): fires while work is waiting
        with NO live worker, or within one lease window of a worker
        loss — the pool is healing (or starved) and an operator should
        know before client timeouts say so."""
        from ..utils.health import AlertRule

        def check(now: int):
            starved = (
                not self._workers
                and (self._pending or self._buffer)
            )
            healing = (
                self._last_lost_micros is not None
                and now - self._last_lost_micros <= self.policy.lease_micros
            )
            return bool(starved or healing), {
                "workers": len(self._workers),
                "in_flight": len(self._pending),
                "buffered": len(self._buffer),
                "workers_lost": self._workers_lost.count,
                "redispatched": self._redispatched.count,
            }

        monitor.add_rule(
            AlertRule(
                "verifier.pool_degraded", check,
                for_micros=0, clear_for_micros=0,
            )
        )

    # -- internals ---------------------------------------------------------

    def _dispatch_locked(
        self, entry: _PendingVerify, exclude: Optional[list] = None
    ) -> Optional[tuple]:
        """Bind (or buffer) one entry under the service lock; returns
        the (topic, request, target) send for the caller to encode and
        perform after release, or None when the entry was buffered."""
        if not self._workers:
            if entry not in self._buffer:
                self._buffer.append(entry)   # store-and-forward
            return None
        candidates = (
            [w for w in self._workers if w not in exclude] if exclude else []
        ) or self._workers
        worker = candidates[self._rr % len(candidates)]
        self._rr += 1
        redispatch = bool(entry.dispatches)
        if redispatch:
            # a RE-dispatch is a new incarnation of the nonce: bump the
            # attempt so the previous worker's late answer is rejected
            entry.attempt += 1
            entry.req = replace(entry.req, attempt=entry.attempt)
        entry.worker = worker
        entry.hedged_to = None
        entry.dispatches += 1
        entry.dispatched_micros = self._now_micros()
        entry.retry_at_micros = None
        if self.txstory is not None:
            # per-attempt lifecycle events (memory-only append — safe
            # under the service lock): the story shows every worker
            # this nonce ever visited and why
            tid = self._story_tx(entry)
            if tid is not None:
                if redispatch:
                    self.txstory.record(
                        tid, "verify.redispatch",
                        attempt=entry.attempt, worker=worker,
                        nonce=entry.req.nonce,
                    )
                else:
                    self.txstory.record(
                        tid, "verify.dispatch",
                        attempt=entry.attempt, worker=worker,
                        nonce=entry.req.nonce,
                    )
        # capture the request REFERENCE under the lock (the frozen
        # dataclass is only ever replaced, never mutated, so encoding
        # can safely happen after release — full-tx serialization must
        # not serialize every other thread behind the service lock)
        return (msglib.TOPIC_VERIFIER_REQ, entry.req, worker)

    def _send_all(self, sends) -> None:
        for topic, req, target in sends:
            self._messaging.send(topic, ser.encode(req), target)

    def _on_ready(self, msg: msglib.Message) -> None:
        ready = ser.decode(msg.payload)
        # The advertised worker name MUST be the fabric-authenticated
        # sender: a peer can only attach as itself, never claim another
        # node's name (prevents peer-table poisoning via register_peer
        # and pool-joining under a stolen identity).
        if ready.worker != msg.sender:
            return
        if (
            self._allowed_workers is not None
            and ready.worker not in self._allowed_workers
        ):
            return
        if ready.host and self._register_peer is not None:
            # EVERY announcement refreshes the dial-back address, not
            # just the first: a worker that restarts on a new port
            # within its lease would otherwise keep renewing the lease
            # while dispatches bridge to its dead old address. The
            # callback reaches into the fabric's peer table — outside
            # the service lock, and BEFORE the worker is published
            # into _workers so a concurrent verify()/tick() can never
            # bind a nonce to a peer the fabric cannot resolve yet.
            self._register_peer(ready.worker, ready.host, ready.port)
        now = self._now_micros()
        sends: list[tuple] = []
        with self._lock:
            self._leases[ready.worker] = now   # heartbeat = lease renewal
            if ready.worker not in self._workers:
                self._workers.append(ready.worker)
                self._incarnations[ready.worker] = (
                    self._incarnations.get(ready.worker, 0) + 1
                )
                # fresh capacity: flush the store-and-forward buffer,
                # then give any orphaned in-flight nonce (its worker
                # died while the pool was empty) a home without
                # waiting for the next tick
                buffered, self._buffer = self._buffer, []
                for entry in buffered:
                    send = self._dispatch_locked(entry)
                    if send:
                        sends.append(send)
                for entry in self._pending.values():
                    if entry.worker is None and entry not in self._buffer:
                        if entry.dispatches:
                            self._redispatched.mark()
                        send = self._dispatch_locked(
                            entry, exclude=entry.lost_workers
                        )
                        if send:
                            sends.append(send)
        self._send_all(sends)

    def _on_response(self, msg: msglib.Message) -> None:
        res: TxVerificationResponse = ser.decode(msg.payload)
        with self._lock:
            entry = self._pending.get(res.nonce)
            if entry is None:
                return   # duplicate / already answered (at-least-once)
            if getattr(res, "attempt", 0) != entry.attempt:
                return   # stale incarnation: re-dispatched since
            if msg.sender not in (entry.worker, entry.hedged_to):
                return   # only the bound (or hedge) worker may answer
            del self._pending[res.nonce]
        # resolution outside the lock: set_result/set_exception run
        # done-callbacks (qos latency observers, span ends)
        self._duration.update(time.perf_counter() - entry.t0)
        if self.txstory is not None:
            tid = self._story_tx(entry)
            if tid is not None:
                self.txstory.record(
                    tid, "verify.done",
                    attempt=entry.attempt, worker=msg.sender,
                    nonce=res.nonce, ok=res.error is None,
                )
        if res.error is None:
            self._success.mark()
            entry.fut.set_result()
        else:
            self._failure.mark()
            entry.fut.set_exception(VerificationFailedError(res.error))


# ---------------------------------------------------------------------------
# worker side


def request_ingest_pipeline(**kw):
    """An IngestPipeline configured for TxVerificationRequest frames:
    the envelope decodes in the pool, and the batched Merkle-id /
    staging stages run on the carried SignedTransaction (None for
    contract-only requests)."""
    from .ingest import IngestPipeline

    return IngestPipeline(extract=lambda req: req.stx, **kw)


class VerifierWorker:
    """Standalone verification worker (reference: Verifier.kt:38-111).

    Handles `verifier.requests`: rebuilds nothing (the request is fully
    resolved), batch-verifies every attached signature across ALL queued
    requests in one `verify_batch` dispatch, runs contract verification,
    and replies per-request. With `batch_window=0` each message is
    processed as it is pumped; a positive window lets the fabric deliver
    several requests first so one TPU dispatch covers them all — the
    batching-notary serving path shares this drain.

    With an `ingest` pipeline (node/ingest.py) attached, the worker is
    the OutOfProcessTransactionVerifierService pool's pipelined end:
    request frames route through the fabric's ring seam
    (messaging.add_ring) into the sharded decode pool — decode of the
    NEXT delivery round overlaps this round's verify dispatch — each
    round's transaction ids come from the batched Merkle-id stage, and
    `drain` consumes the PRE-STAGED signature requests the pipeline
    memoised at decode time instead of re-staging them here. A
    malformed frame is dropped and metered (Verifier.Failed) in its
    slot; the rest of the round proceeds.
    """

    def __init__(
        self,
        messaging: msglib.MessagingService,
        node_address: str,
        batch_verifier: Optional[BatchSignatureVerifier] = None,
        metrics: Optional[MetricRegistry] = None,
        batch_window: int = 0,
        advertised_address: Optional[tuple[str, int]] = None,
        ingest=None,               # Optional[corda_tpu.node.ingest.IngestPipeline]
        ingest_window: int = 8192,
        heartbeat_micros: int = 2_000_000,   # WorkerReady re-announce
        #                            cadence (lease renewal on the node
        #                            side); 0 disables heartbeats
        clock=None,                # node-clock source for deadline expiry;
        #                            None = wall clock (production workers —
        #                            deadlines are minted on wall-clock
        #                            nodes); simulated-time rigs MUST pass
        #                            the TestClock that minted theirs
        health=None,               # Optional[utils.health.HealthMonitor]:
        #                            registers a `verifier.drain` heartbeat
        #                            the drain loop beats (progress =
        #                            requests answered, queue depth = ring
        #                            + handler backlog) so a wedged drain
        #                            thread trips the watchdog
        perf=None,                 # Optional[utils.perf.PerfPlane]: the
        #                            worker's verified-request counter
        #                            becomes an in-process rate history
        #                            key, and an ingest pipeline built
        #                            with the same plane reports its
        #                            stage seconds there
    ):
        self._messaging = messaging
        self._verifier = batch_verifier or default_verifier()
        self._clock = clock
        self._batch_window = batch_window
        self._queue: list[TxVerificationRequest] = []
        # handler-fed frames awaiting the ingest pipeline, as
        # (payload, trace header, deadline header) so propagated trace
        # contexts survive into the pipeline's per-frame spans and
        # expired requests shed pre-decode
        self._raw: list[tuple[bytes, Optional[tuple], Optional[int]]] = []
        self.metrics = metrics or MetricRegistry()
        self._verified = self.metrics.meter("Verifier.Verified")
        self._failed = self.metrics.meter("Verifier.Failed")
        # deadline-expired frames dropped pre-decode (QoS sheds are not
        # failures: the sender stopped wanting the answer)
        self._shed = self.metrics.meter("Verifier.Shed")
        self._batch_sizes = self.metrics.histogram("Verifier.BatchSize")
        self._ingest = ingest
        self._ring = None
        if ingest is not None:
            from .ingest import IngestRing

            try:
                ring = IngestRing(depth=ingest_window)
                # metrics: ring depth / high-water / parked gauges on
                # this worker's registry (messaging.register_ring_gauges)
                messaging.add_ring(
                    msglib.TOPIC_VERIFIER_REQ, ring, metrics=self.metrics
                )
                self._ring = ring
            except NotImplementedError:
                # fabric has no ring seam: the handler path below still
                # feeds the pipeline via self._raw
                pass
        if perf is not None:
            perf.watch_rate(
                "verifier_worker_verified_per_sec",
                lambda: self._verified.count,
            )
            if ingest is not None and getattr(ingest, "perf", None) is None:
                ingest.perf = perf
        self._heartbeat = None
        if health is not None:
            self._heartbeat = health.heartbeat(
                "verifier.drain",
                queue_depth=lambda: len(self._queue)
                + len(self._raw)
                + (len(self._ring) if self._ring is not None else 0),
            )
            if self._ring is not None:
                # ring saturation / parked-frame growth alerting over
                # the backpressure seam (the gauges made it visible on
                # /metrics; this makes it PAGE)
                parked = getattr(self._messaging, "parked_count", None)
                health.watch_ring(
                    msglib.TOPIC_VERIFIER_REQ,
                    lambda: len(self._ring),
                    self._ring.depth,
                    parked_fn=(
                        (lambda: parked(msglib.TOPIC_VERIFIER_REQ))
                        if parked is not None else None
                    ),
                )
        messaging.add_handler(msglib.TOPIC_VERIFIER_REQ, self._on_request)
        # announce attachment so buffered requests flush to us; over TCP
        # the advertised address lets the node bridge back. The SAME
        # announcement doubles as the lease heartbeat: the pump loop
        # re-sends it every `heartbeat_micros` (maybe_heartbeat), and a
        # node that stops hearing it detaches us and re-dispatches our
        # in-flight work to a survivor.
        self._node_address = node_address
        self._advertised = advertised_address or ("", 0)
        self._heartbeat_micros = heartbeat_micros
        self._last_ready_micros = self._now_micros()
        self._send_ready()

    def _now_micros(self) -> int:
        if self._clock is not None:
            return self._clock.now_micros()
        import time

        return time.time_ns() // 1_000

    def _send_ready(self) -> None:
        host, port = self._advertised
        self._messaging.send(
            TOPIC_READY,
            ser.encode(WorkerReady(self._messaging.my_address, host, port)),
            self._node_address,
        )

    def maybe_heartbeat(self, now: Optional[int] = None) -> bool:
        """Re-announce WorkerReady when the heartbeat cadence is due
        (lease renewal). Called from the drain/pump loop; returns True
        when a heartbeat was sent."""
        if not self._heartbeat_micros:
            return False
        if now is None:
            now = self._now_micros()
        if now - self._last_ready_micros < self._heartbeat_micros:
            return False
        self._last_ready_micros = now
        self._send_ready()
        return True

    def _on_request(self, msg: msglib.Message) -> None:
        if self._ingest is not None:
            self._raw.append((msg.payload, msg.trace, msg.deadline))
            if len(self._raw) > self._batch_window:
                self.drain()
            return
        self._queue.append(ser.decode(msg.payload))
        if len(self._queue) > self._batch_window:
            self.drain()

    def _pull_ingested(self) -> None:
        """Move every waiting frame through the ingest pipeline into
        the request queue: ring frames first (fabric fast path), then
        handler-fed raw payloads. Each frame's propagated trace header
        (Message.trace) rides into the pipeline so the worker's ingest
        spans join the sender's trace, and its deadline header rides
        too so an expired request sheds PRE-DECODE (node/qos.py) —
        the worker never spends CTS/verify work on a request whose
        node-side future already timed out."""
        payloads: list[bytes] = []
        traces: list = []
        deadlines: list = []
        if self._ring is not None:
            for m in self._ring.drain():
                payloads.append(m.payload)
                traces.append(m.trace)
                deadlines.append(getattr(m, "deadline", None))
            # frames parked while the ring was full re-enter it for the
            # next drain — the backpressure release valve
            retry = getattr(self._messaging, "retry_parked", None)
            if retry is not None:
                retry(msglib.TOPIC_VERIFIER_REQ)
        if self._raw:
            for payload, trace, deadline in self._raw:
                payloads.append(payload)
                traces.append(trace)
                deadlines.append(deadline)
            self._raw = []
        if not payloads:
            return
        from .qos import DeadlineExpired

        for e in self._ingest.ingest(
            payloads,
            trace_parents=traces,
            deadlines=deadlines,
            now_micros=(
                self._clock.now_micros() if self._clock is not None else None
            ),
        ):
            if isinstance(e.error, DeadlineExpired):
                self._shed.mark()     # shed, not failed: QoS drop
                continue
            if e.error is not None:
                self._failed.mark()   # malformed frame: its slot only
                continue
            self._queue.append(e.obj)

    def drain(self) -> int:
        """Process every queued request; one signature-batch dispatch
        covers all of them. Returns how many were processed."""
        self.maybe_heartbeat()
        if self._ingest is not None:
            self._pull_ingested()
        pending, self._queue = self._queue, []
        if not pending:
            if self._heartbeat is not None:
                self._heartbeat.beat()
            return 0
        sig_reqs, spans = [], []
        for req in pending:
            if req.stx is not None:
                rs = req.stx.signature_requests()
                spans.append((len(sig_reqs), len(rs)))
                sig_reqs.extend(rs)
            else:
                spans.append((0, 0))
        self._batch_sizes.update(len(sig_reqs))
        batch_error: Optional[str] = None
        sig_ok: list[bool] = []
        try:
            sig_ok = self._verifier.verify_batch(sig_reqs) if sig_reqs else []
        except Exception as e:
            # a failed batch dispatch (device lost, kernel error) must
            # still answer every queued request — silence would leave
            # all node-side futures hanging forever
            batch_error = f"VerifierDispatchError: {type(e).__name__}: {e}"
        # the signature gate runs FIRST (sig results are already on
        # the host here): a request with invalid signatures must not
        # reach contract execution at all — the contract phase can run
        # attachment-carried sandboxed code, and executing it for a
        # transaction nobody signed is free attack surface
        sig_errs: list[Optional[Exception]] = []
        for req, (off, n) in zip(pending, spans):
            err: Optional[Exception] = None
            if batch_error is None and req.stx is not None:
                try:
                    req.stx.raise_on_invalid(sig_ok[off : off + n])
                except Exception as e:  # noqa: BLE001 - reported per req
                    err = e
            sig_errs.append(err)
        # contract phase: grouped-by-contract across the sig-valid
        # requests (core/batch_verify.py) — the same sweep the
        # batching notary uses. Guarded: pending is already detached
        # from self._queue, so an escaping exception would strand
        # every node-side future.
        contract_errs: list[Optional[Exception]] = [None] * len(pending)
        live = [
            i for i, e in enumerate(sig_errs)
            if batch_error is None and e is None
        ]
        if live:
            from ..core.batch_verify import verify_ledger_batch

            try:
                batch = verify_ledger_batch([pending[i].ltx for i in live])
                for i, cerr in zip(live, batch):
                    contract_errs[i] = cerr
            except Exception as e:  # noqa: BLE001 - answer, don't strand
                for i in live:
                    contract_errs[i] = e
        for req, serr, cerr in zip(pending, sig_errs, contract_errs):
            error = batch_error
            if error is None:
                e = serr or cerr
                if e is not None:
                    error = f"{type(e).__name__}: {e}"
            if error is None:
                self._verified.mark()
            else:
                self._failed.mark()
            self._messaging.send(
                msglib.TOPIC_VERIFIER_RES,
                ser.encode(
                    TxVerificationResponse(
                        req.nonce, error, getattr(req, "attempt", 0)
                    )
                ),
                req.response_address,
            )
        if self._heartbeat is not None:
            self._heartbeat.beat(progress=len(pending))
        return len(pending)


# ---------------------------------------------------------------------------
# standalone worker process (reference: Verifier.main, Verifier.kt:50-88)


def main(argv: Optional[list[str]] = None) -> None:
    """`python -m corda_tpu.node.verifier --name w1 --node nodeA
    --node-host 127.0.0.1 --node-port 10001 --db /tmp/w1.db`

    Connects a fabric endpoint to the requesting node, announces
    readiness, and pumps forever — the process-level analogue of the
    reference's standalone verifier jar.
    """
    import argparse
    import sys

    from ..crypto import schemes
    from ..crypto.batch_verifier import CpuBatchVerifier, TpuBatchVerifier
    from .fabric import FabricEndpoint, PeerAddress
    from .persistence import NodeDatabase

    p = argparse.ArgumentParser(description="out-of-process verifier worker")
    p.add_argument("--name", required=True)
    p.add_argument("--node", required=True, help="requesting node's name")
    p.add_argument("--node-host", default="127.0.0.1")
    p.add_argument("--node-port", type=int, required=True)
    p.add_argument("--db", required=True)
    p.add_argument("--seed", type=int, default=None)
    p.add_argument(
        "--cpu", action="store_true", help="use the CPU reference verifier"
    )
    p.add_argument("--batch-window", type=int, default=0)
    p.add_argument(
        "--ingest-shards",
        type=int,
        default=0,
        help="enable the pipelined wire-ingest path with this many "
        "decode shards (0 = per-message decode, the default)",
    )
    p.add_argument(
        "--profile-hz",
        type=float,
        default=0.0,
        help="continuous sampling-profiler rate over this worker's "
        "threads (utils/perf.py; 0 = off). Folded stacks are written "
        "to --profile-out on shutdown",
    )
    p.add_argument(
        "--profile-out",
        default="",
        help="where the folded collapsed stacks land on shutdown "
        "(flamegraph.pl format; default <db>.folded)",
    )
    p.add_argument(
        "--app",
        action="append",
        default=[],
        help="contract module(s) to import so their states/commands are "
        "codec-registered (the AttachmentsClassLoader analogue — the "
        "reference worker classloads contract code from attachments, "
        "AttachmentsClassLoader.kt:23)",
    )
    args = p.parse_args(argv)

    import importlib

    for mod in args.app or ["corda_tpu.finance"]:
        importlib.import_module(mod)

    keypair = schemes.generate_keypair(
        seed=args.seed if args.seed is not None else 1
    )
    db = NodeDatabase(args.db)
    node_addr = PeerAddress(args.node_host, args.node_port, None)
    ep = FabricEndpoint(
        args.name,
        keypair,
        db,
        resolve=lambda peer: node_addr if peer == args.node else None,
    )
    ep.start()
    verifier = CpuBatchVerifier() if args.cpu else TpuBatchVerifier()
    ingest = (
        request_ingest_pipeline(shards=args.ingest_shards)
        if args.ingest_shards
        else None
    )
    # the production worker watches itself: the drain heartbeat +
    # ring rule live on a real HealthMonitor ticked by the pump loop,
    # so a wedged drain is visible in-process (and on the worker's
    # registry as Health.* gauges), not only when node-side futures
    # start timing out
    from ..utils.health import HealthMonitor
    from ..utils.perf import PerfPlane, PerfPolicy

    health = HealthMonitor()
    # the production worker attributes itself too: kernel
    # compile-vs-execute accounting (the TPU verifier records into the
    # plane's process-default), drain-rate history, and — with
    # --profile-hz — continuous folded-stack profiling of the pump /
    # decode-pool threads
    perf = PerfPlane(policy=PerfPolicy(profile_hz=args.profile_hz or 19.0))
    health.watch_perf(perf)
    if args.profile_hz:
        perf.profiler.start()
    worker = VerifierWorker(
        ep,
        args.node,
        batch_verifier=verifier,
        batch_window=args.batch_window,
        advertised_address=("127.0.0.1", ep.listen_port),
        ingest=ingest,
        health=health,
        perf=perf,
    )
    try:
        while True:
            ep.pump(block=True, timeout=1.0)
            worker.drain()
            health.tick()
            perf.tick()
    except KeyboardInterrupt:
        pass
    finally:
        perf.profiler.stop()
        if args.profile_hz and perf.profiler.samples:
            # the capture must land somewhere retrievable — the worker
            # CLI has no web gateway to serve /profile from
            out_path = args.profile_out or (args.db + ".folded")
            try:
                with open(out_path, "w") as f:
                    f.write(perf.profiler.collapsed() + "\n")
                print(f"profile: folded stacks -> {out_path}",
                      file=sys.stderr)
            except OSError as e:
                print(f"profile: could not write {out_path}: {e}",
                      file=sys.stderr)
        ep.stop()
        db.close()


if __name__ == "__main__":
    main()
