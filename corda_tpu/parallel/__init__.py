"""Device-mesh parallelism helpers (ICI data-parallel batch sharding).

The reference scales verification with worker thread pools and
horizontally-scaled verifier processes (SURVEY.md §2.5); the TPU-native
equivalent shards signature batches across chips over ICI with
`jax.sharding` — embarrassingly data-parallel, no collectives in the
hot loop.
"""
