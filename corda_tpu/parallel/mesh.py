"""Mesh construction and batch sharding for the crypto kernels.

The kernels in crypto/ are pure elementwise-over-batch XLA programs, so
multi-chip scaling is a single NamedSharding over the trailing batch
axis: XLA partitions the whole verification program data-parallel
across the mesh with zero collectives (the analogue of the reference's
horizontally-scaled verifier worker pool,
node/.../transactions/OutOfProcessTransactionVerifierService.kt:19-73 —
but over ICI instead of a message broker).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"


def make_mesh(devices: Optional[list] = None) -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices, dtype=object).reshape(-1), (BATCH_AXIS,))


def shard_operand(mesh: Mesh, x, batch_axis: int = -1):
    """Place a host array on the mesh with its batch axis sharded
    (last dim for [limbs, B] operands; axis 0 for [B, bytes] packed
    records)."""
    axis = batch_axis % x.ndim
    spec = P(*[BATCH_AXIS if d == axis else None for d in range(x.ndim)])
    return jax.device_put(x, NamedSharding(mesh, spec))
