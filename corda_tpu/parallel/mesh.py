"""Mesh construction and batch sharding for the crypto kernels.

The kernels in crypto/ are pure elementwise-over-batch XLA programs, so
multi-chip scaling is a single NamedSharding over the trailing batch
axis: XLA partitions the whole verification program data-parallel
across the mesh with zero collectives (the analogue of the reference's
horizontally-scaled verifier worker pool,
node/.../transactions/OutOfProcessTransactionVerifierService.kt:19-73 —
but over ICI instead of a message broker).
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

BATCH_AXIS = "batch"
DCN_AXIS = "dcn"
ICI_AXIS = "ici"


def make_mesh(devices: Optional[list] = None) -> Mesh:
    """1-D data-parallel mesh over all (or the given) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.array(devices, dtype=object).reshape(-1), (BATCH_AXIS,))


def make_mesh_2d(
    dcn: int, ici: int, devices: Optional[list] = None
) -> Mesh:
    """2-D (dcn × ici) mesh for multi-host deployments: the leading
    axis spans host groups (DCN), the trailing axis each group's chips
    (ICI). The verify program still shards its batch over BOTH axes
    with zero collectives — the 2-D shape exists so the batch lays out
    host-contiguously: each host stages and feeds ITS shard locally
    (jax.make_array_from_process_local_data in a real multi-host run),
    and no verification byte ever crosses DCN. Device order follows
    jax.devices(), which sorts by (process_index, local id) — hence
    reshape(dcn, ici) groups each host's chips on one 'dcn' row."""
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) != dcn * ici:
        raise ValueError(
            f"mesh {dcn}x{ici} needs {dcn * ici} devices, "
            f"have {len(devices)}"
        )
    arr = np.array(devices, dtype=object).reshape(dcn, ici)
    return Mesh(arr, (DCN_AXIS, ICI_AXIS))


def batch_spec_axes(mesh: Mesh):
    """The PartitionSpec entry sharding a batch dimension over EVERY
    mesh axis — a bare axis name on the 1-D mesh, the axis tuple on
    multi-axis meshes."""
    names = tuple(mesh.axis_names)
    return names[0] if len(names) == 1 else names


def batch_sharding(mesh: Mesh, ndim: int, batch_axis: int = -1) -> NamedSharding:
    """The NamedSharding `shard_operand` places operands with — also
    usable standalone to ask "how would this split?" (shard_shape)
    without paying a device transfer."""
    axis = batch_axis % ndim
    b = batch_spec_axes(mesh)
    spec = P(*[b if d == axis else None for d in range(ndim)])
    return NamedSharding(mesh, spec)


def shard_operand(mesh: Mesh, x, batch_axis: int = -1):
    """Place a host array on the mesh with its batch axis sharded over
    every mesh axis (last dim for [limbs, B] operands; axis 0 for
    [B, bytes] packed records)."""
    return jax.device_put(x, batch_sharding(mesh, x.ndim, batch_axis))
