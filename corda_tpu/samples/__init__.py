"""Demo CorDapps (reference: samples/ — 7 demos, SURVEY §2.10).

Each demo module exposes `run(...)` executing its arc over a
MockNetwork (deterministic) and a `main()` running it over real node
processes via the Driver DSL where that adds value.
"""
