"""attachment-demo: a transaction referencing an attachment blob.

Reference: samples/attachment-demo/ — the sender uploads a jar to its
attachment store, builds a transaction referencing it by hash, and the
recipient (who has never seen the blob) fetches it during resolution
(FetchAttachmentsFlow) and checks the content hash.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core import serialization as ser
from ..core.contracts import register_contract
from ..core.identity import Party
from ..core.transactions import TransactionBuilder
from ..crypto.hashes import SecureHash
from ..flows.api import FlowLogic, initiating_flow
from ..flows.core_flows import FinalityFlow

ATTACHMENT_CONTRACT = "corda_tpu.samples.AttachmentDemo"


@ser.serializable
@dataclass(frozen=True)
class AttachmentDemoState:
    """Records that `att_id` was shared with the participants."""

    sender: Party
    recipient: Party
    att_id: SecureHash

    @property
    def participants(self):
        return (self.sender, self.recipient)


@ser.serializable
@dataclass(frozen=True)
class ShareAttachment:
    pass


class AttachmentDemoContract:
    def verify(self, ltx) -> None:
        from ..core.contracts import require_that

        outs = ltx.outputs_of_type(AttachmentDemoState)
        require_that("one demo state output", len(outs) == 1)
        require_that(
            "the referenced attachment rides the transaction",
            any(a.id == outs[0].att_id for a in ltx.attachments),
        )


register_contract(ATTACHMENT_CONTRACT, AttachmentDemoContract())


@initiating_flow
class ShareAttachmentFlow(FlowLogic):
    def __init__(self, recipient: Party, att_id: SecureHash, notary: Party):
        self.recipient = recipient
        self.att_id = att_id
        self.notary = notary

    def call(self):
        builder = TransactionBuilder(self.notary)
        builder.add_output_state(
            AttachmentDemoState(self.our_identity, self.recipient, self.att_id),
            ATTACHMENT_CONTRACT,
        )
        builder.add_command(ShareAttachment(), self.our_identity.owning_key)
        builder.add_attachment(self.att_id)
        stx = self.services.sign_initial_transaction(builder)
        result = yield from self.sub_flow(FinalityFlow(stx))
        return result


def run(seed: int = 42, payload: bytes = b"PK\x03\x04 demo jar bytes " * 100):
    """Sender uploads + shares; recipient ends up with the blob it
    never had. Returns (att_id, recipient_blob)."""
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary")
    sender = net.create_node("Sender")
    recipient = net.create_node("Recipient")

    att_id = sender.services.attachments.import_attachment(payload)
    assert att_id not in recipient.services.attachments

    fsm = sender.start_flow(
        ShareAttachmentFlow(recipient.party, att_id, notary.party)
    )
    net.run()
    fsm.result_or_throw()

    att = recipient.services.attachments.open_attachment(att_id)
    assert att is not None, "recipient did not fetch the attachment"
    assert SecureHash.sha256(att.data) == att_id
    return att_id, att.data


def main():
    att_id, data = run()
    print(f"attachment {att_id} delivered: {len(data)} bytes")


if __name__ == "__main__":
    main()
