"""bank-of-corda-demo: an issuer node serving issuance requests.

Reference: samples/bank-of-corda-demo/ — a bank node issues cash to
requesting parties on demand through `IssuerFlow`, with an issuance
policy; clients drive it via RPC.
"""

from __future__ import annotations

from ..finance.cash import CashState
from ..finance.trade_flows import IssuanceRequesterFlow


def run(seed: int = 42, requests=((7_000, "USD"), (3_000, "GBP"))):
    """Big Corporation asks the Bank of Corda for money; the bank's
    policy caps single issuances. Returns the requester's balances."""
    from ..flows.api import FlowException
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    net.create_notary("Notary")
    bank = net.create_node("BankOfCorda")
    big_corp = net.create_node("BigCorporation")

    def policy(req, requester):
        if req.quantity > 1_000_000:
            raise ValueError("single issuance cap is 1,000,000")

    bank.services.issuance_policy = policy

    for quantity, currency in requests:
        fsm = big_corp.start_flow(
            IssuanceRequesterFlow(bank.party, quantity, currency)
        )
        net.run()
        fsm.result_or_throw()

    # over-cap request refused
    fsm = big_corp.start_flow(
        IssuanceRequesterFlow(bank.party, 2_000_000, "USD")
    )
    net.run()
    refused = False
    try:
        fsm.result_or_throw()
    except FlowException:
        refused = True

    balances: dict[str, int] = {}
    for s in big_corp.vault.unconsumed_states(CashState):
        cur = s.state.data.amount.token.product
        balances[cur] = balances.get(cur, 0) + s.state.data.amount.quantity
    return balances, refused


def main():
    balances, refused = run()
    print(f"issued balances: {balances}; over-cap refused: {refused}")


if __name__ == "__main__":
    main()
