"""irs-demo: interest-rate swap with a rate-fixing oracle + scheduler.

Reference: samples/irs-demo/ — an IRS lifecycle where a rate oracle
(`NodeInterestRates` in api/NodeInterestRates.kt) serves interest-rate
queries and **signs Merkle tear-offs** of fixing transactions (it sees
only the Fix command, nothing else — the oracle privacy pattern,
`RatesFixFlow` in flows/RatesFixFlow.kt), and fixings are driven by the
scheduler: the swap state is a `SchedulableState` whose
nextScheduledActivity launches the next fixing flow at its fixing date.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..core import serialization as ser
from ..core.contracts import (
    ScheduledActivity,
    StateRef,
    register_contract,
    require_that,
)
from ..core.identity import Party
from ..core.transactions import (
    FilteredTransaction,
    G_COMMANDS,
    LedgerTransaction,
    TransactionBuilder,
    TransactionVerificationError,
)
from ..crypto.tx_signature import TransactionSignature
from ..flows.api import (
    FlowException,
    FlowLogic,
    initiated_by,
    initiating_flow,
)
from ..flows.core_flows import CollectSignaturesFlow, FinalityFlow
from ..node.cordapp import corda_service

IRS_CONTRACT = "corda_tpu.samples.InterestRateSwap"


# -- the rate model ----------------------------------------------------------


@ser.serializable
@dataclass(frozen=True)
class FixOf:
    """Which rate is being fixed: index name + fixing date (reference:
    core FixOf — name/forDay/ofTenor collapsed to name+date)."""

    name: str                       # e.g. "LIBOR-3M"
    date_micros: int


@ser.serializable
@dataclass(frozen=True)
class RateFix:
    """An observed fixing: the FixOf plus the rate in basis points
    (integer — no floats on the ledger)."""

    of: FixOf
    rate_bps: int


# -- the swap state ----------------------------------------------------------


@ser.serializable
@dataclass(frozen=True)
class InterestRateSwapState:
    """A stylised IRS: fixed leg vs floating leg fixed by the oracle on
    each fixing date. Fixings accumulate on the state; the state is
    SCHEDULABLE — it asks for a FixingFlow at its next unfixed date."""

    fixed_payer: Party
    floating_payer: Party
    oracle: Party
    notional: int
    fixed_rate_bps: int
    index_name: str
    fixing_dates: tuple[int, ...]          # micros, ascending
    fixings: tuple[RateFix, ...] = ()

    @property
    def participants(self):
        return (self.fixed_payer, self.floating_payer)

    def next_fixing_date(self) -> Optional[int]:
        fixed = {f.of.date_micros for f in self.fixings}
        for d in self.fixing_dates:
            if d not in fixed:
                return d
        return None

    def next_scheduled_activity(self, this_state_ref: StateRef):
        d = self.next_fixing_date()
        if d is None:
            return None
        return ScheduledActivity(
            flow_tag=f"{FixingFlow.__module__}.{FixingFlow.__qualname__}",
            flow_args=(this_state_ref,),
            scheduled_at=d,
        )

    def with_fixing(self, fix: RateFix) -> "InterestRateSwapState":
        return InterestRateSwapState(
            self.fixed_payer,
            self.floating_payer,
            self.oracle,
            self.notional,
            self.fixed_rate_bps,
            self.index_name,
            self.fixing_dates,
            self.fixings + (fix,),
        )


@ser.serializable
@dataclass(frozen=True)
class IRSAgree:
    pass


@ser.serializable
@dataclass(frozen=True)
class IRSFix:
    fix: RateFix


class InterestRateSwap:
    def verify(self, ltx: LedgerTransaction) -> None:
        agrees = ltx.commands_of_type(IRSAgree)
        fixes = ltx.commands_of_type(IRSFix)
        require_that(
            "exactly one IRS command", len(agrees) + len(fixes) == 1
        )
        ins = ltx.inputs_of_type(InterestRateSwapState)
        outs = ltx.outputs_of_type(InterestRateSwapState)
        if agrees:
            cmd = agrees[0]
            require_that("agreement creates one swap", not ins and len(outs) == 1)
            swap = outs[0]
            signers = set(cmd.signers)
            for p in swap.participants:
                require_that(
                    "agreement signed by both parties",
                    p.owning_key in signers,
                )
        else:
            cmd = fixes[0]
            require_that("fix consumes one swap", len(ins) == 1 and len(outs) == 1)
            before, after = ins[0], outs[0]
            fix = cmd.value.fix
            require_that(
                "fix is for the next unfixed date",
                before.next_fixing_date() == fix.of.date_micros,
            )
            require_that(
                "fix is for the swap's index",
                fix.of.name == before.index_name,
            )
            require_that(
                "output appends exactly this fixing",
                after == before.with_fixing(fix),
            )
            require_that(
                "fix is signed by the oracle",
                before.oracle.owning_key in set(cmd.signers),
            )


register_contract(IRS_CONTRACT, InterestRateSwap())


# -- the oracle (NodeInterestRates) ------------------------------------------


@corda_service
class RateOracleService:
    """A @corda_service (reference: `@CordaService class Oracle`,
    NodeInterestRates.kt + AbstractNode.kt:226-279): discovered from
    the cordapp module and constructed with the ServiceHub on every
    node that installs it; only nodes whose operator `configure()`s a
    rate table act as oracles. The sign check: EVERY revealed component
    must be an IRSFix command whose rate matches our table — the oracle
    never sees (and cannot be tricked into signing) anything else
    (NodeInterestRates.sign)."""

    def __init__(self, services):
        self.services = services
        self.rates: Optional[dict[tuple[str, int], int]] = None

    def configure(self, rates: dict[tuple[str, int], int]) -> None:
        self.rates = dict(rates)

    @property
    def configured(self) -> bool:
        return self.rates is not None

    def query(self, fix_of: FixOf) -> Optional[int]:
        if self.rates is None:
            return None
        return self.rates.get((fix_of.name, fix_of.date_micros))

    def sign(self, ftx: FilteredTransaction) -> TransactionSignature:
        if self.rates is None:
            raise ValueError("this node's oracle is not configured")
        ftx.verify()
        revealed = [
            (g, c) for g, _i, c in ftx.components if g != 6   # not meta
        ]
        if not revealed:
            raise ValueError("nothing revealed to sign over")
        for g, c in revealed:
            if g != G_COMMANDS:
                raise ValueError("oracle only signs command components")
            if not hasattr(c, "value") or not isinstance(c.value, IRSFix):
                raise ValueError("oracle only signs Fix commands")
            fix = c.value.fix
            expected = self.query(fix.of)
            if expected is None:
                raise ValueError(f"no rate known for {fix.of}")
            if fix.rate_bps != expected:
                raise ValueError(
                    f"rate {fix.rate_bps} != fixing {expected} for {fix.of}"
                )
        return self.services.key_management.sign(
            ftx.id, self.services.my_info.legal_identity.owning_key
        )


@ser.serializable
@dataclass(frozen=True)
class RateQuery:
    fix_of: FixOf


@ser.serializable
@dataclass(frozen=True)
class RateQueryResponse:
    rate_bps: Optional[int]


@initiating_flow
class OracleQueryFlow(FlowLogic):
    """Ask the oracle for a rate (RatesFixFlow.QueryRequest)."""

    def __init__(self, oracle: Party, fix_of: FixOf):
        self.oracle = oracle
        self.fix_of = fix_of

    def call(self):
        resp = yield from self.send_and_receive(
            self.oracle, RateQuery(self.fix_of), RateQueryResponse
        )
        if resp.rate_bps is None:
            raise FlowException(f"oracle knows no rate for {self.fix_of}")
        return resp.rate_bps


@initiated_by(OracleQueryFlow)
class OracleQueryHandler(FlowLogic):
    def __init__(self, other: Party):
        self.other = other

    def call(self):
        q = yield from self.receive(self.other, RateQuery)
        try:
            oracle = self.services.cordapp_service(RateOracleService)
        except KeyError:
            oracle = None
        if oracle is None or not oracle.configured:
            raise FlowException("this node is not a rate oracle")
        yield from self.send(
            self.other, RateQueryResponse(oracle.query(q.fix_of))
        )
        return None


@initiating_flow
class OracleSignFlow(FlowLogic):
    """Send the oracle a tear-off revealing only the Fix command; get
    its signature over the whole transaction id back
    (RatesFixFlow.SignRequest)."""

    def __init__(self, oracle: Party, ftx: FilteredTransaction):
        self.oracle = oracle
        self.ftx = ftx

    def call(self):
        sig = yield from self.send_and_receive(
            self.oracle, self.ftx, TransactionSignature
        )
        sig.verify(self.ftx.id)
        if sig.by != self.oracle.owning_key:
            raise FlowException("oracle signed with an unexpected key")
        return sig


@initiated_by(OracleSignFlow)
class OracleSignHandler(FlowLogic):
    def __init__(self, other: Party):
        self.other = other

    def call(self):
        ftx = yield from self.receive(self.other, FilteredTransaction)
        try:
            oracle = self.services.cordapp_service(RateOracleService)
        except KeyError:
            oracle = None
        if oracle is None or not oracle.configured:
            raise FlowException("this node is not a rate oracle")
        try:
            sig = oracle.sign(ftx)
        except (ValueError, TransactionVerificationError) as e:
            raise FlowException(f"oracle refused to sign: {e}")
        yield from self.send(self.other, sig)
        return None


# -- the fixing flow (scheduler-launched) ------------------------------------


@initiating_flow
class FixingFlow(FlowLogic):
    """Fix the swap's next date: query the oracle, build the fixing tx,
    have the oracle sign its tear-off, collect the counterparty's
    signature, finalise (RatesFixFlow + FixingFlow in the demo).

    Launched BY THE SCHEDULER on both participants at the fixing date —
    only the fixed payer proceeds (deterministic leader), the floating
    payer's instance no-ops (the reference demo picks sides the same
    way)."""

    def __init__(self, state_ref: StateRef):
        self.state_ref = state_ref

    def call(self):
        sar = self.services.vault.state_and_ref(self.state_ref)
        if sar is None:
            return None   # already fixed/consumed (at-least-once firing)
        swap: InterestRateSwapState = sar.state.data
        if self.our_identity != swap.fixed_payer:
            return None   # the floating payer's scheduler also fired
        fix_date = swap.next_fixing_date()
        if fix_date is None:
            return None
        fix_of = FixOf(swap.index_name, fix_date)
        rate = yield from self.sub_flow(
            OracleQueryFlow(swap.oracle, fix_of)
        )
        fix = RateFix(fix_of, rate)
        builder = TransactionBuilder()
        builder.add_input_state(sar)
        builder.add_output_state(swap.with_fixing(fix), IRS_CONTRACT)
        builder.add_command(
            IRSFix(fix),
            swap.oracle.owning_key,
            swap.fixed_payer.owning_key,
            swap.floating_payer.owning_key,
        )
        stx = self.services.sign_initial_transaction(builder)
        # the oracle sees ONLY its Fix command
        ftx = stx.wtx.build_filtered_transaction(
            lambda c: hasattr(c, "value") and isinstance(c.value, IRSFix)
        )
        oracle_sig = yield from self.sub_flow(
            OracleSignFlow(swap.oracle, ftx)
        )
        stx = stx.with_additional_signature(oracle_sig)
        stx = yield from self.sub_flow(CollectSignaturesFlow(stx))
        result = yield from self.sub_flow(FinalityFlow(stx))
        return result


@initiating_flow
class StartSwapFlow(FlowLogic):
    """Agree the swap between the two parties (demo setup)."""

    def __init__(self, swap: InterestRateSwapState, notary: Party):
        self.swap = swap
        self.notary = notary

    def call(self):
        builder = TransactionBuilder(self.notary)
        builder.add_output_state(self.swap, IRS_CONTRACT)
        builder.add_command(
            IRSAgree(),
            self.swap.fixed_payer.owning_key,
            self.swap.floating_payer.owning_key,
        )
        stx = self.services.sign_initial_transaction(builder)
        stx = yield from self.sub_flow(CollectSignaturesFlow(stx))
        result = yield from self.sub_flow(FinalityFlow(stx))
        return result


# -- the demo arc ------------------------------------------------------------


def run(seed: int = 42, n_fixings: int = 3):
    """The full demo on a MockNetwork: agree a swap, let the SCHEDULER
    fire each fixing as its date arrives, oracle-sign each one. Returns
    the final swap state."""
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary", validating=True)
    bank_a = net.create_node("BankA")
    bank_b = net.create_node("BankB")
    oracle_node = net.create_node("RateOracle")

    now = net.clock.now_micros()
    dates = tuple(now + (i + 1) * 1_000_000 for i in range(n_fixings))
    rates = {("LIBOR-3M", d): 500 + 7 * i for i, d in enumerate(dates)}
    oracle_node.services.cordapp_service(RateOracleService).configure(rates)

    swap = InterestRateSwapState(
        fixed_payer=bank_a.party,
        floating_payer=bank_b.party,
        oracle=oracle_node.party,
        notional=10_000_000,
        fixed_rate_bps=450,
        index_name="LIBOR-3M",
        fixing_dates=dates,
    )
    fsm = bank_a.start_flow(StartSwapFlow(swap, notary.party))
    net.run()
    fsm.result_or_throw()

    # let time pass; the scheduler fires each fixing
    for _ in range(n_fixings):
        net.clock.advance(1_000_000)
        net.run()

    final = bank_b.vault.unconsumed_states(InterestRateSwapState)
    assert len(final) == 1
    return final[0].state.data
