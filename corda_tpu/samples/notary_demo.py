"""notary-demo: fire N transactions through a chosen notary flavour.

Reference: samples/notary-demo/ — `Notarise.kt` drives N transactions
through `DummyIssueAndMove` against a Single, Raft, or BFT notary
cluster and prints which member(s) signed each one.
"""

from __future__ import annotations

import time

from ..core.contracts import Amount, Issued
from ..core.identity import PartyAndReference
from ..crypto.composite import leaves_of
from ..finance.cash import CashIssueFlow, CashPaymentFlow


def run(flavour: str = "single", n_txs: int = 10, seed: int = 42):
    """Issue-and-move n_txs through the selected notary flavour on a
    MockNetwork. Returns (signer names per tx, elapsed seconds)."""
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    if flavour == "single":
        notary_party = net.create_notary("Notary").party
        members = []
    elif flavour == "batching":
        notary_party = net.create_notary("Notary", batching=True).party
        members = []
    elif flavour == "raft":
        notary_party, members = net.create_raft_notary_cluster(3)
        net.elect(members)
    elif flavour == "bft":
        notary_party, members = net.create_bft_notary_cluster(4)
    else:
        raise ValueError(f"unknown notary flavour {flavour!r}")

    alice = net.create_node("Counterparty")
    bob = net.create_node("Requestor")

    def settle(fsm, rounds=600):
        for _ in range(rounds):
            net.run()
            if fsm.done:
                return
            net.clock.advance(100_000)
        raise AssertionError("notarisation did not settle")

    # one vault state per planned payment so concurrent flows can each
    # soft-lock a distinct coin (distinct nonces: identical issuances
    # would collapse into one deterministic tx id)
    for i in range(n_txs):
        fsm = bob.start_flow(
            CashIssueFlow(100, "USD", bob.party, notary_party, nonce=i)
        )
        settle(fsm)
        fsm.result_or_throw()

    notary_leaves = set(leaves_of(notary_party.owning_key))
    signers_per_tx = []
    t0 = time.perf_counter()
    if flavour == "batching":
        # the point of the batching notary: N requests in flight at
        # once share SPI dispatches (one per quiescent pump round)
        fsms = [
            bob.start_flow(CashPaymentFlow(100, "USD", alice.party))
            for _ in range(n_txs)
        ]
        for fsm in fsms:
            settle(fsm)
        stxs = [fsm.result_or_throw() for fsm in fsms]
    else:
        stxs = []
        for i in range(n_txs):
            fsm = bob.start_flow(CashPaymentFlow(100, "USD", alice.party))
            settle(fsm)
            stxs.append(fsm.result_or_throw())
    elapsed = time.perf_counter() - t0
    for stx in stxs:
        signers_per_tx.append(
            [s.by for s in stx.sigs if s.by in notary_leaves]
        )
    assert all(signers_per_tx), "every tx must carry notary signature(s)"
    return signers_per_tx, elapsed


def main():
    for flavour in ("single", "batching", "raft", "bft"):
        signers, elapsed = run(flavour, n_txs=5)
        per_tx = [len(s) for s in signers]
        print(
            f"{flavour:>8}: 5 txs notarised in {elapsed:.2f}s "
            f"({5 / elapsed:.1f} tx/s), signatures per tx: {per_tx}"
        )


if __name__ == "__main__":
    main()
