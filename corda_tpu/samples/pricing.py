"""Deterministic curve pricing for the SIMM demo portfolio.

Reference: samples/simm-valuation-demo delegates pricing to OpenGamma
analytics (samples/simm-valuation-demo/src/main/kotlin/net/corda/vega/
analytics/ — curve calibration, swap PV, bucketed PV01 + vega via
algorithmic differentiation). Here the same role is played by a small
fixed-order float64 pricer: a zero curve with linear zero-rate
interpolation, par-annuity swap PV, Black-76 European swaptions, and
bump-and-revalue sensitivity ladders on the SIMM tenor vertices.

CONSENSUS-CRITICAL: both parties reprice the shared portfolio
independently and must agree the margin bit-for-bit, so every loop
below runs in a fixed order over the same pillar grid and stays in
IEEE-754 doubles (plain `math`/numpy float64 — never the accelerator,
whose native precision is float32).
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import numpy as np

from .simm import (
    CREDIT_TENORS_Y,
    N_CREDIT_TENORS,
    N_TENORS,
    TENORS_Y,
)

BUMP = 1e-4          # 1bp zero-rate bump for delta ladders
VOL_BUMP = 1e-2      # 1 vol-point bump for vega ladders


def _interp_pillars(
    values: tuple[float, ...], t: float, ts: tuple[float, ...] = TENORS_Y
) -> float:
    """Linear interpolation over a pillar grid (SIMM tenor vertices by
    default, credit vertices for `CreditCurve`), flat beyond the ends.
    ONE implementation for every pillar curve: this loop is
    consensus-critical, and two copies that drift apart would silently
    break cross-party agreement between delta and vega repricing."""
    if t <= ts[0]:
        return values[0]
    if t >= ts[-1]:
        return values[-1]
    hi = next(i for i, v in enumerate(ts) if v >= t)
    lo = hi - 1
    frac = (t - ts[lo]) / (ts[hi] - ts[lo])
    return values[lo] * (1.0 - frac) + values[hi] * frac


@dataclass(frozen=True)
class _PillarCurve:
    """Values on the SIMM tenor pillars with shared interpolation."""

    values: tuple[float, ...]

    def __post_init__(self):
        if len(self.values) != N_TENORS:
            raise ValueError(
                f"need {N_TENORS} pillar values, got {len(self.values)}"
            )

    def at(self, t: float) -> float:
        return _interp_pillars(self.values, t)

    def bumped(self, pillar: int, size: float):
        values = list(self.values)
        values[pillar] += size
        return type(self)(tuple(values))


class ZeroCurve(_PillarCurve):
    """Continuously-compounded zero rates on the SIMM tenor pillars
    (the standard bootstrap presentation; OpenGamma's calibrated nodal
    curves play this role in the reference demo)."""

    @property
    def rates(self) -> tuple[float, ...]:
        return self.values

    def zero(self, t: float) -> float:
        return self.at(t)

    def df(self, t: float) -> float:
        return math.exp(-self.zero(t) * t)

    def bumped(self, pillar: int, size: float = BUMP) -> "ZeroCurve":
        return super().bumped(pillar, size)


class VolCurve(_PillarCurve):
    """Flat-in-strike Black vols on the SIMM expiry pillars."""

    @property
    def vols(self) -> tuple[float, ...]:
        return self.values

    def vol(self, expiry: float) -> float:
        return self.at(expiry)

    def bumped(self, pillar: int, size: float = VOL_BUMP) -> "VolCurve":
        return super().bumped(pillar, size)


def demo_market() -> tuple[ZeroCurve, VolCurve]:
    """The fixture market both demo parties price against (the
    reference ships static market-data resources the same way:
    simm-valuation-demo/src/main/resources)."""
    # gently upward-sloping zero curve, 1.5% -> 3.1%
    zeros = tuple(
        0.015 + 0.016 * math.log1p(t) / math.log1p(TENORS_Y[-1])
        for t in TENORS_Y
    )
    # downward-sloping Black vol, 45% short end -> 18% long end
    vols = tuple(
        0.45 - 0.27 * math.log1p(t) / math.log1p(TENORS_Y[-1])
        for t in TENORS_Y
    )
    return ZeroCurve(zeros), VolCurve(vols)


# fixture FX market: foreign discount curves + spot rates into the
# demo's valuation currency, keyed by foreign currency code
DEMO_FX_SPOTS = {"EUR": 1.09, "GBP": 1.27}


def demo_foreign_curve(
    ccy: str, domestic: ZeroCurve | None = None
) -> ZeroCurve:
    """Foreign zero curve for a demo currency: the DOMESTIC curve with
    a fixed per-currency basis so forwards carry real rate differential
    risk on both curves. Pass the domestic curve actually being priced
    against (e.g. a scenario-bumped one) so both legs of the
    covered-interest-parity formula move together; default is the
    fixture market."""
    basis = {"EUR": -0.007, "GBP": 0.004}.get(ccy, 0.0)
    if domestic is None:
        domestic, _ = demo_market()
    return ZeroCurve(
        tuple(max(z + basis, 1e-4) for z in domestic.rates)
    )


# -- instruments -------------------------------------------------------------


def annuity(curve: ZeroCurve, start: float, end: float) -> float:
    """Annual fixed-leg annuity sum_i df(t_i), t_i = start+1 .. end."""
    a = 0.0
    n = max(int(round(end - start)), 1)
    for i in range(1, n + 1):
        a += curve.df(start + i)
    return a


def par_rate(curve: ZeroCurve, start: float, end: float) -> float:
    """Forward par swap rate: (df(start) - df(end)) / annuity."""
    a = annuity(curve, start, end)
    return (curve.df(start) - curve.df(end)) / a


def swap_pv(
    notional: float, fixed_rate_bps: float, maturity_y: float, curve: ZeroCurve
) -> float:
    """PV to the FIXED PAYER of a spot-starting annual IRS: receive
    float (1 - df(T)), pay fixed (c * annuity)."""
    t = max(maturity_y, TENORS_Y[0])
    c = fixed_rate_bps / 10_000.0
    return notional * ((1.0 - curve.df(t)) - c * annuity(curve, 0.0, t))


def _norm_cdf(x: float) -> float:
    return 0.5 * (1.0 + math.erf(x / math.sqrt(2.0)))


def black_price(
    forward: float, strike: float, expiry: float, vol: float, is_call: bool
) -> float:
    """Undiscounted Black-76 option on a rate (payer swaption = call on
    the forward par rate)."""
    if expiry <= 0.0 or vol <= 0.0:
        intrinsic = forward - strike if is_call else strike - forward
        return max(intrinsic, 0.0)
    sd = vol * math.sqrt(expiry)
    d1 = (math.log(forward / strike) + 0.5 * sd * sd) / sd
    d2 = d1 - sd
    if is_call:
        return forward * _norm_cdf(d1) - strike * _norm_cdf(d2)
    return strike * _norm_cdf(-d2) - forward * _norm_cdf(-d1)


def swaption_pv(
    notional: float,
    strike_bps: float,
    expiry_y: float,
    tenor_y: float,
    curve: ZeroCurve,
    vols: VolCurve,
    is_payer: bool = True,
) -> float:
    """European swaption under Black-76 on the forward par rate, cash
    value = notional * annuity * Black(F, K, sigma, Te)."""
    start = max(expiry_y, TENORS_Y[0])
    end = start + max(tenor_y, 1.0)
    f = par_rate(curve, start, end)
    k = strike_bps / 10_000.0
    a = annuity(curve, start, end)
    return notional * a * black_price(
        f, k, start, vols.vol(start), is_payer
    )


def fx_forward_pv(
    notional_fgn: float,
    strike: float,
    maturity_y: float,
    dom_curve: ZeroCurve,
    fgn_curve: ZeroCurve,
    spot: float,
) -> float:
    """PV in domestic currency to the BUYER of `notional_fgn` units of
    foreign currency at rate `strike` (domestic per foreign) in
    `maturity_y` years:  PV = N * (spot * df_f(T) - strike * df_d(T)).
    The covered-interest-parity form OpenGamma's FX analytics reduce to
    for a deliverable forward."""
    t = max(maturity_y, TENORS_Y[0])
    return notional_fgn * (
        spot * fgn_curve.df(t) - strike * dom_curve.df(t)
    )


def equity_option_pv(
    n_shares: float,
    strike: float,
    expiry_y: float,
    curve: ZeroCurve,
    spot: float,
    vol: float,
    is_call: bool = True,
) -> float:
    """European equity option: Black on the dividend-free forward
    F = spot / df(T), discounted — PV = n * df(T) * Black(F, K, v, T)."""
    t = max(expiry_y, TENORS_Y[0])
    f = spot / curve.df(t)
    return n_shares * curve.df(t) * black_price(f, strike, t, vol, is_call)


def commodity_forward_pv(
    units: float,
    strike: float,
    maturity_y: float,
    curve: ZeroCurve,
    spot: float,
    carry: float = 0.0,
) -> float:
    """PV to the BUYER of `units` of a commodity at `strike` in
    `maturity_y` years: F = spot * exp(carry * T) (cost-of-carry
    forward), PV = units * df(T) * (F - strike)."""
    t = max(maturity_y, TENORS_Y[0])
    f = spot * math.exp(carry * t)
    return units * curve.df(t) * (f - strike)


@dataclass(frozen=True)
class CreditCurve:
    """Flat-forward par CDS spreads (decimal) on the five SIMM credit
    vertices, linearly interpolated; `recovery` feeds the standard
    spread/(1-R) flat-hazard reduction."""

    spreads: tuple[float, ...]
    recovery: float = 0.4

    def __post_init__(self):
        if len(self.spreads) != N_CREDIT_TENORS:
            raise ValueError(
                f"need {N_CREDIT_TENORS} credit pillar spreads, "
                f"got {len(self.spreads)}"
            )

    def spread(self, t: float) -> float:
        return _interp_pillars(self.spreads, t, CREDIT_TENORS_Y)

    def survival(self, t: float) -> float:
        lam = self.spread(t) / max(1.0 - self.recovery, 1e-9)
        return math.exp(-lam * t)

    def bumped(self, pillar: int, size: float = BUMP) -> "CreditCurve":
        spreads = list(self.spreads)
        spreads[pillar] += size
        return CreditCurve(tuple(spreads), self.recovery)


def cds_pv(
    notional: float,
    contract_spread_bps: float,
    maturity_y: float,
    curve: ZeroCurve,
    credit: CreditCurve,
) -> float:
    """PV to the PROTECTION BUYER of a single-name CDS paying
    `contract_spread_bps` annually: (s_market(T) - s_contract) * risky
    annuity, risky annuity = sum_i df(t_i) * surv(t_i) on the annual
    grid — the standard flat-hazard credit-triangle reduction the
    reference's OpenGamma ISDA-model pricer collapses to for a flat
    quote."""
    t = max(maturity_y, CREDIT_TENORS_Y[0])
    n = max(int(round(t)), 1)
    risky_annuity = 0.0
    for i in range(1, n + 1):
        risky_annuity += curve.df(float(i)) * credit.survival(float(i))
    s_mkt = credit.spread(t)
    s_con = contract_spread_bps / 10_000.0
    return notional * (s_mkt - s_con) * risky_annuity


# fixture single-name credit market: issuer -> (bucket, CreditCurve).
# CreditQ buckets are quality x region in the published model; the two
# demo issuers land in representative investment-grade buckets.
DEMO_CREDIT_CURVES = {
    "ACME-INDUSTRIAL": (
        2, CreditCurve((0.006, 0.0065, 0.007, 0.008, 0.0095)),
    ),
    "GLOBEX-FINANCIAL": (
        1, CreditCurve((0.009, 0.0097, 0.0105, 0.012, 0.014)),
    ),
}

# fixture equity market: name -> (SIMM equity bucket, spot, flat vol)
DEMO_EQUITY_MARKET = {
    "ACME-INDUSTRIAL": (5, 120.0, 0.28),
    "GLOBEX-FINANCIAL": (7, 45.0, 0.35),
    "DEMO-INDEX": (11, 4_800.0, 0.18),
}

# fixture commodity market: name -> (SIMM commodity bucket, spot,
# cost-of-carry). Bucket 2 = crude, 11 = base metals, 12 = precious.
DEMO_COMMODITY_MARKET = {
    "CRUDE": (2, 82.0, 0.01),
    "COPPER": (11, 9_400.0, 0.005),
    "GOLD": (12, 1_950.0, -0.002),
}


# -- sensitivity ladders (bump and revalue) ----------------------------------


def bump_ladder(n_pillars: int, pv_at) -> np.ndarray:
    """[n_pillars] bump-and-revalue ladder: `pv_at(None)` prices the
    base scenario, `pv_at(k)` with pillar k bumped; entries are
    bumped - base in fixed pillar order. THE one bump loop every
    sensitivity ladder shares — like `_interp_pillars`, this is
    consensus-critical: copies that drift apart (bump size, loop
    order, dtype) would silently break cross-party bit-for-bit
    agreement."""
    base = pv_at(None)
    s = np.zeros(n_pillars, dtype=np.float64)
    for k in range(n_pillars):
        s[k] = pv_at(k) - base
    return s



def swap_delta_ladder(
    notional: float, fixed_rate_bps: float, maturity_y: float, curve: ZeroCurve
) -> np.ndarray:
    """[K] curve-priced PV01 ladder: PV under a +1bp bump of each zero
    pillar minus base PV, in fixed pillar order. This replaces the
    hard-coded `notional * years / 1e4` vertex split the round-2 demo
    used (VERDICT round 2, SIMM breadth)."""
    return bump_ladder(
        N_TENORS,
        lambda k: swap_pv(
            notional, fixed_rate_bps, maturity_y,
            curve if k is None else curve.bumped(k),
        ),
    )


def swaption_delta_ladder(
    notional: float,
    strike_bps: float,
    expiry_y: float,
    tenor_y: float,
    curve: ZeroCurve,
    vols: VolCurve,
    is_payer: bool = True,
) -> np.ndarray:
    """[K] rate-delta ladder: a payer swaption gains as rates rise
    (positive ladder), a receiver loses (negative) — the sign must
    reach the margin so receivers net against payer swaps."""
    return bump_ladder(
        N_TENORS,
        lambda k: swaption_pv(
            notional, strike_bps, expiry_y, tenor_y,
            curve if k is None else curve.bumped(k), vols, is_payer,
        ),
    )


def fx_forward_spot_delta(
    notional_fgn: float,
    strike: float,
    maturity_y: float,
    dom_curve: ZeroCurve,
    fgn_curve: ZeroCurve,
    spot: float,
) -> float:
    """SIMM FX sensitivity: PV change for a +1% RELATIVE spot move
    (the published FX delta definition), by bump-and-revalue so the
    number stays consistent with the PV function above."""
    base = fx_forward_pv(
        notional_fgn, strike, maturity_y, dom_curve, fgn_curve, spot
    )
    return (
        fx_forward_pv(
            notional_fgn, strike, maturity_y, dom_curve, fgn_curve,
            spot * 1.01,
        )
        - base
    )


def fx_forward_rate_ladders(
    notional_fgn: float,
    strike: float,
    maturity_y: float,
    dom_curve: ZeroCurve,
    fgn_curve: ZeroCurve,
    spot: float,
) -> tuple[np.ndarray, np.ndarray]:
    """([K] domestic, [K] foreign) IR delta ladders of the forward: +1bp
    bump of each zero pillar on each curve, fixed pillar order."""
    dom = bump_ladder(
        N_TENORS,
        lambda k: fx_forward_pv(
            notional_fgn, strike, maturity_y,
            dom_curve if k is None else dom_curve.bumped(k),
            fgn_curve, spot,
        ),
    )
    fgn = bump_ladder(
        N_TENORS,
        lambda k: fx_forward_pv(
            notional_fgn, strike, maturity_y, dom_curve,
            fgn_curve if k is None else fgn_curve.bumped(k), spot,
        ),
    )
    return dom, fgn


def equity_spot_delta(
    n_shares: float,
    strike: float,
    expiry_y: float,
    curve: ZeroCurve,
    spot: float,
    vol: float,
    is_call: bool = True,
) -> float:
    """SIMM equity sensitivity: PV change for a +1% RELATIVE spot move
    (the published equity delta definition), bump-and-revalue."""
    base = equity_option_pv(
        n_shares, strike, expiry_y, curve, spot, vol, is_call
    )
    return (
        equity_option_pv(
            n_shares, strike, expiry_y, curve, spot * 1.01, vol, is_call
        )
        - base
    )


def equity_option_rate_ladder(
    n_shares: float,
    strike: float,
    expiry_y: float,
    curve: ZeroCurve,
    spot: float,
    vol: float,
    is_call: bool = True,
) -> np.ndarray:
    """[K] IR delta ladder of the equity option (discounting + forward
    both move with the zero curve), +1bp pillar bumps in fixed order."""
    return bump_ladder(
        N_TENORS,
        lambda k: equity_option_pv(
            n_shares, strike, expiry_y,
            curve if k is None else curve.bumped(k), spot, vol, is_call,
        ),
    )


def equity_vega(
    n_shares: float,
    strike: float,
    expiry_y: float,
    curve: ZeroCurve,
    spot: float,
    vol: float,
    is_call: bool = True,
) -> float:
    """SIMM equity vega sensitivity: PV change per +1 vol-point bump,
    bump-and-revalue (feeds the equity vega layer and, scaled by
    `simm.scaling_function(expiry)`, the equity curvature layer)."""
    base = equity_option_pv(
        n_shares, strike, expiry_y, curve, spot, vol, is_call
    )
    return (
        equity_option_pv(
            n_shares, strike, expiry_y, curve, spot, vol + VOL_BUMP,
            is_call,
        )
        - base
    )


def commodity_spot_delta(
    units: float,
    strike: float,
    maturity_y: float,
    curve: ZeroCurve,
    spot: float,
    carry: float = 0.0,
) -> float:
    """SIMM commodity sensitivity: PV change for a +1% relative spot
    move, bump-and-revalue."""
    base = commodity_forward_pv(units, strike, maturity_y, curve, spot, carry)
    return (
        commodity_forward_pv(
            units, strike, maturity_y, curve, spot * 1.01, carry
        )
        - base
    )


def commodity_forward_rate_ladder(
    units: float,
    strike: float,
    maturity_y: float,
    curve: ZeroCurve,
    spot: float,
    carry: float = 0.0,
) -> np.ndarray:
    """[K] IR delta ladder of the commodity forward (discounting
    risk), +1bp pillar bumps in fixed order."""
    return bump_ladder(
        N_TENORS,
        lambda k: commodity_forward_pv(
            units, strike, maturity_y,
            curve if k is None else curve.bumped(k), spot, carry,
        ),
    )


def cds_cs01_ladder(
    notional: float,
    contract_spread_bps: float,
    maturity_y: float,
    curve: ZeroCurve,
    credit: CreditCurve,
) -> np.ndarray:
    """[5] CS01 ladder on the SIMM credit vertices: CDS PV under a
    +1bp bump of each credit pillar minus base PV, fixed pillar order —
    the curve-priced replacement for `simm.credit_cs01_ladder`'s vertex
    split when a real credit curve is in play."""
    return bump_ladder(
        N_CREDIT_TENORS,
        lambda k: cds_pv(
            notional, contract_spread_bps, maturity_y, curve,
            credit if k is None else credit.bumped(k),
        ),
    )


def cds_rate_ladder(
    notional: float,
    contract_spread_bps: float,
    maturity_y: float,
    curve: ZeroCurve,
    credit: CreditCurve,
) -> np.ndarray:
    """[K] IR delta ladder of the CDS (the risky annuity discounts on
    the zero curve), +1bp pillar bumps in fixed order."""
    return bump_ladder(
        N_TENORS,
        lambda k: cds_pv(
            notional, contract_spread_bps, maturity_y,
            curve if k is None else curve.bumped(k), credit,
        ),
    )


def swaption_vega_ladder(
    notional: float,
    strike_bps: float,
    expiry_y: float,
    tenor_y: float,
    curve: ZeroCurve,
    vols: VolCurve,
    is_payer: bool = True,
) -> np.ndarray:
    """[K] vega ladder: PV change per +1 vol-point bump of each expiry
    pillar (only pillars the expiry interpolates against are hit)."""
    return bump_ladder(
        N_TENORS,
        lambda k: swaption_pv(
            notional, strike_bps, expiry_y, tenor_y, curve,
            vols if k is None else vols.bumped(k), is_payer,
        ),
    )
