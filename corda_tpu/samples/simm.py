"""ISDA-SIMM-style initial margin for IR + FX portfolios.

Reference: samples/simm-valuation-demo/ delegates the maths to
OpenGamma's implementation of the ISDA Standard Initial Margin Model.
This module implements the published SIMM *structure* — the interest
-rate risk class with delta, vega AND curvature layers, the FX delta
risk class, and the cross-risk-class psi aggregation — instead of a
toy heuristic:

  1. per-trade sensitivities bucketed onto the SIMM tenor vertices
     (curve-priced ladders come from samples/pricing.py);
  2. weighted sensitivities WS_k = RW_k * s_k (risk weight per tenor;
     vega uses the scalar IR VRW);
  3. intra-bucket (per-currency) aggregation
     K_b = sqrt( WS^T . rho . WS ) with a tenor-tenor correlation
     matrix;
  4. cross-bucket aggregation
     M = sqrt( sum_b K_b^2 + sum_{b!=c} gamma * S_b * S_c ),
     S_b = clamp(sum_k WS_bk, -K_b, K_b);
  5. curvature from scaled vega (CVR = SF(t) * vega) through the
     squared-correlation aggregation with the lambda/theta tail factor
     (`curvature_margin`); risk-class IM = delta + vega + curvature;
  6. FX delta: one bucket, per-currency sensitivities to a 1% spot
     move, scalar risk weight, uniform 0.5 FX-FX correlation
     (`fx_margin`);
  7. cross-risk-class aggregation over the six published risk classes
     SIMM = sqrt( sum_r IM_r^2 + sum_{r!=s} psi_rs IM_r IM_s )
     (`product_margin` with the representative `RISK_CLASS_PSI`).

Weights/correlations are representative of SIMM calibrations
(risk weights in bp, correlation decaying with tenor distance with the
published long-range floor); exact ISDA parameter tables are
versioned + licensed, so this stays a faithfully-shaped, openly
parameterised calculator — the ledger only needs both parties to run
the SAME deterministic function (float64 op order fixed below).

The CONSENSUS margin runs in fixed-order float64 numpy (bit-for-bit
reproducible across parties); `estimate_margins_batch` offers the same
quadratic form as one batched device matmul for analytics-scale
valuation sweeps — the TPU-shaped core of why the reference demo
exists (heavy-compute CorDapp), but never the recorded number.
"""

from __future__ import annotations

import math

import numpy as np

# SIMM tenor vertices, in years (the 12 IR delta vertices)
TENORS_Y = (
    2 / 52, 1 / 12, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 15.0, 20.0, 30.0
)
N_TENORS = len(TENORS_Y)

# representative per-tenor risk weights, basis points of sensitivity
RISK_WEIGHTS_BP = (
    114.0, 115.0, 102.0, 71.0, 61.0, 52.0, 50.0, 51.0, 51.0, 50.0, 54.0, 63.0
)

CROSS_CCY_GAMMA = 0.32      # cross-bucket (currency) correlation

# representative IR vega risk weight (SIMM publishes one scalar VRW
# for the whole IR vega risk class)
VEGA_RISK_WEIGHT = 0.21

# Phi^-1(0.995) — the 99.5% normal quantile in the SIMM curvature
# lambda; a fixed constant so both parties share one literal rather
# than each inverting the normal CDF
PHI_INV_995 = 2.5758293035489004

# FX delta risk class: ONE bucket, a scalar risk weight applied to the
# per-currency sensitivity to a 1% relative spot move, and the
# published uniform 0.5 correlation between currency pairs
FX_RISK_WEIGHT = 8.1
FX_CORR = 0.5

# the six published SIMM risk classes, in the fixed aggregation order
RISK_CLASSES = ("IR", "CreditQ", "CreditNonQ", "Equity", "Commodity", "FX")

# representative cross-risk-class correlations psi_rs (the published
# SIMM tables carry exact, versioned values; the structure — a fixed
# symmetric PSD matrix over the six classes — is what consensus needs)
RISK_CLASS_PSI = np.array(
    [
        # IR    CrQ   CrNQ  Eq    Comm  FX
        [1.00, 0.29, 0.13, 0.28, 0.46, 0.32],   # IR
        [0.29, 1.00, 0.54, 0.71, 0.52, 0.38],   # CreditQ
        [0.13, 0.54, 1.00, 0.46, 0.41, 0.12],   # CreditNonQ
        [0.28, 0.71, 0.46, 1.00, 0.49, 0.35],   # Equity
        [0.46, 0.52, 0.41, 0.49, 1.00, 0.41],   # Commodity
        [0.32, 0.38, 0.12, 0.35, 0.41, 1.00],   # FX
    ],
    dtype=np.float64,
)


def tenor_correlation() -> np.ndarray:
    """[K, K] tenor-tenor correlation: exp decay in log-tenor distance
    with the SIMM-style long-range floor."""
    t = np.asarray(TENORS_Y, dtype=np.float64)
    lt = np.log(t)
    d = np.abs(lt[:, None] - lt[None, :])
    rho = np.maximum(np.exp(-0.35 * d), 0.27)
    np.fill_diagonal(rho, 1.0)
    return rho


_RHO = tenor_correlation()
_RW = np.asarray(RISK_WEIGHTS_BP, dtype=np.float64)


def bucket_pv01(
    notional: int, years_to_maturity: float
) -> np.ndarray:
    """[K] PV01-style delta ladder for a vanilla swap: DV01 of the
    fixed leg, split linearly between the two tenor vertices framing
    maturity (standard vertex interpolation)."""
    dv01 = notional * years_to_maturity / 10_000.0
    s = np.zeros(N_TENORS, dtype=np.float64)
    t = max(min(years_to_maturity, TENORS_Y[-1]), TENORS_Y[0])
    hi = next(i for i, v in enumerate(TENORS_Y) if v >= t)
    if TENORS_Y[hi] == t or hi == 0:
        s[hi] = dv01
        return s
    lo = hi - 1
    frac = (t - TENORS_Y[lo]) / (TENORS_Y[hi] - TENORS_Y[lo])
    s[lo] = dv01 * (1.0 - frac)
    s[hi] = dv01 * frac
    return s


def _ks(ws: np.ndarray, rho: np.ndarray):
    """Weighted sensitivities [P, K] -> ([P] K_b, [P] S_b) under the
    given tenor correlation: K_b = sqrt(WS^T rho WS),
    S_b = clamp(sum WS, -K_b, K_b). Shared quadratic core of the
    delta, vega and curvature layers."""
    q = np.einsum("pk,kl,pl->p", ws, rho, ws)
    k = np.sqrt(np.maximum(q, 0.0))
    s = np.clip(ws.sum(axis=1), -k, k)
    return k, s


def bucket_margins(sensitivities: np.ndarray):
    """[P, K] per-bucket DELTA sensitivity ladders -> (K_b, S_b).

    CONSENSUS PATH: float64 numpy with a fixed op order — both parties
    must reproduce the margin bit-for-bit, and jax without x64 would
    silently compute in float32. The TPU belongs to analytics-scale
    estimation (estimate_margins_batch), never to the agreed number."""
    return _ks(sensitivities * _RW[None, :], _RHO)


def vega_bucket_margins(vegas: np.ndarray):
    """[P, K] per-bucket VEGA ladders -> (K_b, S_b): same correlation
    structure as delta with the scalar IR vega risk weight."""
    return _ks(vegas * VEGA_RISK_WEIGHT, _RHO)


def scaling_function(t_years: float) -> float:
    """SIMM curvature scaling SF(t) = 0.5 * min(1, 14 days / t)."""
    return 0.5 * min(1.0, 14.0 / (365.0 * max(t_years, 1e-12)))


_SF = np.asarray([scaling_function(t) for t in TENORS_Y], dtype=np.float64)


def curvature_ladders(vegas: np.ndarray) -> np.ndarray:
    """[P, K] vega ladders -> [P, K] curvature exposures
    CVR_k = SF(t_k) * vega_k (the SIMM vega-derived gamma proxy)."""
    return vegas * _SF[None, :]


def curvature_margin(cvr: np.ndarray) -> float:
    """Published SIMM curvature aggregation over [P, K] CVR ladders:

      K_b   = sqrt( CVR^T rho^2 CVR )          (correlations squared)
      S_b   = clamp(sum CVR, -K_b, K_b)
      theta = min( sum CVR / sum |CVR|, 0 )
      lam   = (Phi^-1(0.995)^2 - 1) * (1 + theta) - theta
      CM    = max( sum CVR + lam * sqrt( sum K_b^2
                   + sum_{b!=c} gamma^2 S_b S_c ), 0 )
    """
    abs_total = float(np.abs(cvr).sum())
    if abs_total == 0.0:
        return 0.0
    total = float(cvr.sum())
    k, s = _ks(cvr, _RHO * _RHO)
    theta = min(total / abs_total, 0.0)
    lam = (PHI_INV_995 * PHI_INV_995 - 1.0) * (1.0 + theta) - theta
    inner = float(np.dot(k, k))
    cross = float(s.sum() ** 2 - np.dot(s, s))
    agg = math.sqrt(
        max(inner + (CROSS_CCY_GAMMA * CROSS_CCY_GAMMA) * cross, 0.0)
    )
    return max(total + lam * agg, 0.0)


def estimate_margins_batch(sensitivities: np.ndarray) -> np.ndarray:
    """[P, K] -> [P] per-bucket K estimates as ONE device matmul — the
    demo's heavy-compute shape (value thousands of portfolios per
    dispatch). ANALYTICS ONLY: runs in the accelerator's native
    precision (float32 without x64), so it may differ from the
    consensus float64 path in the last digits; anything recorded on
    ledger must come from bucket_margins/simm_im."""
    import jax.numpy as jnp

    ws = jnp.asarray(sensitivities * _RW[None, :])
    q = jnp.einsum(
        "pk,kl,pl->p", ws, jnp.asarray(_RHO), ws, precision="highest"
    )
    return np.sqrt(np.maximum(np.asarray(q), 0.0))


def aggregate_margin(k: np.ndarray, s: np.ndarray) -> float:
    """Cross-bucket SIMM aggregation over per-bucket (K_b, S_b)."""
    total = float(np.dot(k, k))
    cross = float(s.sum() ** 2 - np.dot(s, s))
    return math.sqrt(max(total + CROSS_CCY_GAMMA * cross, 0.0))


def fx_margin(fx_deltas: dict[str, float]) -> float:
    """FX delta margin over {currency: PV change per +1% spot move}
    sensitivities: single bucket, WS_i = FX_RISK_WEIGHT * s_i,
    K = sqrt( sum_i WS_i^2 + FX_CORR * sum_{i!=j} WS_i WS_j ).
    Fixed currency order (sorted) keeps the float64 op order shared."""
    if not fx_deltas:
        return 0.0
    ws = (
        np.asarray(
            [fx_deltas[c] for c in sorted(fx_deltas)], dtype=np.float64
        )
        * FX_RISK_WEIGHT
    )
    own = float(np.dot(ws, ws))
    cross = float(ws.sum() ** 2 - own)
    return math.sqrt(max(own + FX_CORR * cross, 0.0))


def product_margin(class_margins: dict[str, float]) -> float:
    """Cross-risk-class SIMM aggregation:
    SIMM = sqrt( sum_r IM_r^2 + sum_{r!=s} psi_rs IM_r IM_s ) over the
    six published risk classes (unknown class names raise — a typo must
    not silently drop a margin contribution)."""
    unknown = set(class_margins) - set(RISK_CLASSES)
    if unknown:
        raise ValueError(f"unknown SIMM risk class(es): {sorted(unknown)}")
    im = np.asarray(
        [float(class_margins.get(c, 0.0)) for c in RISK_CLASSES],
        dtype=np.float64,
    )
    q = float(im @ RISK_CLASS_PSI @ im)
    return math.sqrt(max(q, 0.0))


def simm_breakdown(
    delta_buckets: dict[str, np.ndarray],
    vega_buckets: dict[str, np.ndarray] | None = None,
    fx_deltas: dict[str, float] | None = None,
) -> dict[str, float]:
    """Per-layer margins for {currency: [K] ladder} inputs plus the
    optional FX class. The IR risk-class margin is DeltaMargin +
    VegaMargin + CurvatureMargin (the published SIMM sums the three
    within a risk class); `total` is the cross-risk-class psi
    aggregation of the IR and FX class margins — with no FX exposure it
    equals the IR margin, so IR-only callers see the same number as
    before the FX class landed."""
    out = {"delta": 0.0, "vega": 0.0, "curvature": 0.0, "fx": 0.0}
    if delta_buckets:
        mat = np.stack([delta_buckets[c] for c in sorted(delta_buckets)])
        out["delta"] = aggregate_margin(*bucket_margins(mat))
    if vega_buckets:
        mat = np.stack([vega_buckets[c] for c in sorted(vega_buckets)])
        out["vega"] = aggregate_margin(*vega_bucket_margins(mat))
        out["curvature"] = curvature_margin(curvature_ladders(mat))
    if fx_deltas:
        out["fx"] = fx_margin(fx_deltas)
    ir = out["delta"] + out["vega"] + out["curvature"]
    out["total"] = product_margin({"IR": ir, "FX": out["fx"]})
    return out


def simm_im(
    delta_buckets: dict[str, np.ndarray],
    vega_buckets: dict[str, np.ndarray] | None = None,
    fx_deltas: dict[str, float] | None = None,
) -> int:
    """Initial margin for {currency: [K] sensitivity ladder} inputs
    (delta, optionally vega — curvature follows from vega — and
    optionally per-currency FX spot sensitivities), rounded to an
    integer ledger amount (both parties must agree bit-for-bit; every
    float op above has a fixed order, so IEEE-754 doubles give one
    answer on any host)."""
    return int(round(simm_breakdown(delta_buckets, vega_buckets,
                                    fx_deltas)["total"]))
