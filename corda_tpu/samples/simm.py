"""ISDA-SIMM-style initial margin for IR portfolios.

Reference: samples/simm-valuation-demo/ delegates the maths to
OpenGamma's implementation of the ISDA Standard Initial Margin Model.
This module implements the published SIMM *structure* for the interest
-rate delta risk class (the demo portfolio's only exposure) instead of
a toy heuristic:

  1. per-trade PV01 sensitivities bucketed onto the SIMM tenor
     vertices;
  2. weighted sensitivities WS_k = RW_k * s_k (risk weight per tenor);
  3. intra-bucket (per-currency) aggregation
     K_b = sqrt( WS^T . rho . WS ) with a tenor-tenor correlation
     matrix;
  4. cross-bucket aggregation
     IM = sqrt( sum_b K_b^2 + sum_{b!=c} gamma * S_b * S_c ),
     S_b = clamp(sum_k WS_bk, -K_b, K_b).

Weights/correlations are representative of SIMM calibrations
(risk weights in bp, correlation decaying with tenor distance with the
published long-range floor); exact ISDA parameter tables are
versioned + licensed, so this stays a faithfully-shaped, openly
parameterised calculator — the ledger only needs both parties to run
the SAME deterministic function (float64 op order fixed below).

The CONSENSUS margin runs in fixed-order float64 numpy (bit-for-bit
reproducible across parties); `estimate_margins_batch` offers the same
quadratic form as one batched device matmul for analytics-scale
valuation sweeps — the TPU-shaped core of why the reference demo
exists (heavy-compute CorDapp), but never the recorded number.
"""

from __future__ import annotations

import math

import numpy as np

# SIMM tenor vertices, in years (the 12 IR delta vertices)
TENORS_Y = (
    2 / 52, 1 / 12, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 15.0, 20.0, 30.0
)
N_TENORS = len(TENORS_Y)

# representative per-tenor risk weights, basis points of sensitivity
RISK_WEIGHTS_BP = (
    114.0, 115.0, 102.0, 71.0, 61.0, 52.0, 50.0, 51.0, 51.0, 50.0, 54.0, 63.0
)

CROSS_CCY_GAMMA = 0.32      # cross-bucket (currency) correlation


def tenor_correlation() -> np.ndarray:
    """[K, K] tenor-tenor correlation: exp decay in log-tenor distance
    with the SIMM-style long-range floor."""
    t = np.asarray(TENORS_Y, dtype=np.float64)
    lt = np.log(t)
    d = np.abs(lt[:, None] - lt[None, :])
    rho = np.maximum(np.exp(-0.35 * d), 0.27)
    np.fill_diagonal(rho, 1.0)
    return rho


_RHO = tenor_correlation()
_RW = np.asarray(RISK_WEIGHTS_BP, dtype=np.float64)


def bucket_pv01(
    notional: int, years_to_maturity: float
) -> np.ndarray:
    """[K] PV01-style delta ladder for a vanilla swap: DV01 of the
    fixed leg, split linearly between the two tenor vertices framing
    maturity (standard vertex interpolation)."""
    dv01 = notional * years_to_maturity / 10_000.0
    s = np.zeros(N_TENORS, dtype=np.float64)
    t = max(min(years_to_maturity, TENORS_Y[-1]), TENORS_Y[0])
    hi = next(i for i, v in enumerate(TENORS_Y) if v >= t)
    if TENORS_Y[hi] == t or hi == 0:
        s[hi] = dv01
        return s
    lo = hi - 1
    frac = (t - TENORS_Y[lo]) / (TENORS_Y[hi] - TENORS_Y[lo])
    s[lo] = dv01 * (1.0 - frac)
    s[hi] = dv01 * frac
    return s


def bucket_margins(sensitivities: np.ndarray):
    """[P, K] per-bucket sensitivity ladders -> ([P] K_b, [P] S_b).

    CONSENSUS PATH: float64 numpy with a fixed op order — both parties
    must reproduce the margin bit-for-bit, and jax without x64 would
    silently compute in float32. The TPU belongs to analytics-scale
    estimation (estimate_margins_batch), never to the agreed number."""
    ws = sensitivities * _RW[None, :]
    q = np.einsum("pk,kl,pl->p", ws, _RHO, ws)
    k = np.sqrt(np.maximum(q, 0.0))
    s = np.clip(ws.sum(axis=1), -k, k)
    return k, s


def estimate_margins_batch(sensitivities: np.ndarray) -> np.ndarray:
    """[P, K] -> [P] per-bucket K estimates as ONE device matmul — the
    demo's heavy-compute shape (value thousands of portfolios per
    dispatch). ANALYTICS ONLY: runs in the accelerator's native
    precision (float32 without x64), so it may differ from the
    consensus float64 path in the last digits; anything recorded on
    ledger must come from bucket_margins/simm_im."""
    import jax.numpy as jnp

    ws = jnp.asarray(sensitivities * _RW[None, :])
    q = jnp.einsum(
        "pk,kl,pl->p", ws, jnp.asarray(_RHO), ws, precision="highest"
    )
    return np.sqrt(np.maximum(np.asarray(q), 0.0))


def aggregate_margin(k: np.ndarray, s: np.ndarray) -> float:
    """Cross-bucket SIMM aggregation over per-bucket (K_b, S_b)."""
    total = float(np.dot(k, k))
    cross = float(s.sum() ** 2 - np.dot(s, s))
    return math.sqrt(max(total + CROSS_CCY_GAMMA * cross, 0.0))


def simm_im(buckets: dict[str, np.ndarray]) -> int:
    """Initial margin for {currency: [K] sensitivity ladder}, rounded
    to an integer ledger amount (both parties must agree bit-for-bit;
    every float op above has a fixed order, so IEEE-754 doubles give
    one answer on any host)."""
    if not buckets:
        return 0
    mat = np.stack([buckets[c] for c in sorted(buckets)])
    k, s = bucket_margins(mat)
    return int(round(aggregate_margin(k, s)))
