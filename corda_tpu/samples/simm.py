"""ISDA-SIMM-style initial margin across all six published risk classes.

Reference: samples/simm-valuation-demo/ delegates the maths to
OpenGamma's implementation of the ISDA Standard Initial Margin Model.
This module implements the published SIMM *structure* — the interest
-rate risk class with delta, vega AND curvature layers, the FX delta
risk class, Equity/Commodity bucketed delta classes, the two Credit
(qualifying / non-qualifying) CS01 classes with same-vs-different
issuer correlation and residual buckets, and the cross-risk-class psi
aggregation — instead of a toy heuristic:

  1. per-trade sensitivities bucketed onto the SIMM tenor vertices
     (curve-priced ladders come from samples/pricing.py);
  2. weighted sensitivities WS_k = RW_k * s_k (risk weight per tenor;
     vega uses the scalar IR VRW);
  3. intra-bucket (per-currency) aggregation
     K_b = sqrt( WS^T . rho . WS ) with a tenor-tenor correlation
     matrix;
  4. cross-bucket aggregation
     M = sqrt( sum_b K_b^2 + sum_{b!=c} gamma * S_b * S_c ),
     S_b = clamp(sum_k WS_bk, -K_b, K_b);
  5. curvature from scaled vega (CVR = SF(t) * vega) through the
     squared-correlation aggregation with the lambda/theta tail factor
     (`curvature_margin`); risk-class IM = delta + vega + curvature;
  6. FX delta: one bucket, per-currency sensitivities to a 1% spot
     move, scalar risk weight, uniform 0.5 FX-FX correlation
     (`fx_margin`);
  7. Equity and Commodity delta: per-bucket scalar risk weights and
     intra-bucket correlations over per-name sensitivities to a 1%
     relative move, a flat cross-bucket gamma (representative of the
     published per-pair tables), and — for equity — a RESIDUAL bucket
     whose K adds OUTSIDE the cross-bucket square root
     (`equity_margin`, `commodity_margin`);
  8. CreditQ / CreditNonQ delta: per-(issuer, tenor) CS01 ladders on
     the five published credit vertices, intra-bucket correlation
     split into same-issuer rho and different-issuer rho, bucketed
     risk weights + gamma, residual bucket (`credit_q_margin`,
     `credit_nonq_margin`);
  9. cross-risk-class aggregation over the six published risk classes
     SIMM = sqrt( sum_r IM_r^2 + sum_{r!=s} psi_rs IM_r IM_s )
     (`product_margin` with the representative `RISK_CLASS_PSI`).

Weights/correlations are representative of SIMM calibrations
(risk weights in bp, correlation decaying with tenor distance with the
published long-range floor); exact ISDA parameter tables are
versioned + licensed, so this stays a faithfully-shaped, openly
parameterised calculator — the ledger only needs both parties to run
the SAME deterministic function (float64 op order fixed below).

The CONSENSUS margin runs in fixed-order float64 numpy (bit-for-bit
reproducible across parties); `estimate_margins_batch` offers the same
quadratic form as one batched device matmul for analytics-scale
valuation sweeps — the TPU-shaped core of why the reference demo
exists (heavy-compute CorDapp), but never the recorded number.
"""

from __future__ import annotations

import math

import numpy as np

# SIMM tenor vertices, in years (the 12 IR delta vertices)
TENORS_Y = (
    2 / 52, 1 / 12, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 10.0, 15.0, 20.0, 30.0
)
N_TENORS = len(TENORS_Y)

# representative per-tenor risk weights, basis points of sensitivity
RISK_WEIGHTS_BP = (
    114.0, 115.0, 102.0, 71.0, 61.0, 52.0, 50.0, 51.0, 51.0, 50.0, 54.0, 63.0
)

CROSS_CCY_GAMMA = 0.32      # cross-bucket (currency) correlation

# representative IR vega risk weight (SIMM publishes one scalar VRW
# for the whole IR vega risk class)
VEGA_RISK_WEIGHT = 0.21

# Phi^-1(0.995) — the 99.5% normal quantile in the SIMM curvature
# lambda; a fixed constant so both parties share one literal rather
# than each inverting the normal CDF
PHI_INV_995 = 2.5758293035489004

# FX delta risk class: ONE bucket, a scalar risk weight applied to the
# per-currency sensitivity to a 1% relative spot move, and the
# published uniform 0.5 correlation between currency pairs
FX_RISK_WEIGHT = 8.1
FX_CORR = 0.5

# the six published SIMM risk classes, in the fixed aggregation order
RISK_CLASSES = ("IR", "CreditQ", "CreditNonQ", "Equity", "Commodity", "FX")

# representative cross-risk-class correlations psi_rs (the published
# SIMM tables carry exact, versioned values; the structure — a fixed
# symmetric PSD matrix over the six classes — is what consensus needs)
RISK_CLASS_PSI = np.array(
    [
        # IR    CrQ   CrNQ  Eq    Comm  FX
        [1.00, 0.29, 0.13, 0.28, 0.46, 0.32],   # IR
        [0.29, 1.00, 0.54, 0.71, 0.52, 0.38],   # CreditQ
        [0.13, 0.54, 1.00, 0.46, 0.41, 0.12],   # CreditNonQ
        [0.28, 0.71, 0.46, 1.00, 0.49, 0.35],   # Equity
        [0.46, 0.52, 0.41, 0.49, 1.00, 0.41],   # Commodity
        [0.32, 0.38, 0.12, 0.35, 0.41, 1.00],   # FX
    ],
    dtype=np.float64,
)


def tenor_correlation() -> np.ndarray:
    """[K, K] tenor-tenor correlation: exp decay in log-tenor distance
    with the SIMM-style long-range floor."""
    t = np.asarray(TENORS_Y, dtype=np.float64)
    lt = np.log(t)
    d = np.abs(lt[:, None] - lt[None, :])
    rho = np.maximum(np.exp(-0.35 * d), 0.27)
    np.fill_diagonal(rho, 1.0)
    return rho


_RHO = tenor_correlation()
_RW = np.asarray(RISK_WEIGHTS_BP, dtype=np.float64)


def vertex_split(
    vertices: tuple, t: float, value: float
) -> np.ndarray:
    """[len(vertices)] ladder placing `value` linearly between the two
    vertices framing `t` (standard vertex interpolation, clamped to
    the vertex range) — shared by the IR tenor and credit vertex
    grids."""
    s = np.zeros(len(vertices), dtype=np.float64)
    t = max(min(t, vertices[-1]), vertices[0])
    hi = next(i for i, v in enumerate(vertices) if v >= t)
    if vertices[hi] == t:
        s[hi] = value
        return s
    lo = hi - 1
    frac = (t - vertices[lo]) / (vertices[hi] - vertices[lo])
    s[lo] = value * (1.0 - frac)
    s[hi] = value * frac
    return s


def bucket_pv01(
    notional: int, years_to_maturity: float
) -> np.ndarray:
    """[K] PV01-style delta ladder for a vanilla swap: DV01 of the
    fixed leg, split linearly between the two tenor vertices framing
    maturity (standard vertex interpolation)."""
    return vertex_split(
        TENORS_Y, years_to_maturity,
        notional * years_to_maturity / 10_000.0,
    )


def _ks(ws: np.ndarray, rho: np.ndarray):
    """Weighted sensitivities [P, K] -> ([P] K_b, [P] S_b) under the
    given tenor correlation: K_b = sqrt(WS^T rho WS),
    S_b = clamp(sum WS, -K_b, K_b). Shared quadratic core of the
    delta, vega and curvature layers."""
    q = np.einsum("pk,kl,pl->p", ws, rho, ws)
    k = np.sqrt(np.maximum(q, 0.0))
    s = np.clip(ws.sum(axis=1), -k, k)
    return k, s


def bucket_margins(sensitivities: np.ndarray):
    """[P, K] per-bucket DELTA sensitivity ladders -> (K_b, S_b).

    CONSENSUS PATH: float64 numpy with a fixed op order — both parties
    must reproduce the margin bit-for-bit, and jax without x64 would
    silently compute in float32. The TPU belongs to analytics-scale
    estimation (estimate_margins_batch), never to the agreed number."""
    return _ks(sensitivities * _RW[None, :], _RHO)


def vega_bucket_margins(vegas: np.ndarray):
    """[P, K] per-bucket VEGA ladders -> (K_b, S_b): same correlation
    structure as delta with the scalar IR vega risk weight."""
    return _ks(vegas * VEGA_RISK_WEIGHT, _RHO)


def scaling_function(t_years: float) -> float:
    """SIMM curvature scaling SF(t) = 0.5 * min(1, 14 days / t)."""
    return 0.5 * min(1.0, 14.0 / (365.0 * max(t_years, 1e-12)))


_SF = np.asarray([scaling_function(t) for t in TENORS_Y], dtype=np.float64)


def curvature_ladders(vegas: np.ndarray) -> np.ndarray:
    """[P, K] vega ladders -> [P, K] curvature exposures
    CVR_k = SF(t_k) * vega_k (the SIMM vega-derived gamma proxy)."""
    return vegas * _SF[None, :]


def curvature_margin(cvr: np.ndarray) -> float:
    """Published SIMM curvature aggregation over [P, K] CVR ladders:

      K_b   = sqrt( CVR^T rho^2 CVR )          (correlations squared)
      S_b   = clamp(sum CVR, -K_b, K_b)
      theta = min( sum CVR / sum |CVR|, 0 )
      lam   = (Phi^-1(0.995)^2 - 1) * (1 + theta) - theta
      CM    = max( sum CVR + lam * sqrt( sum K_b^2
                   + sum_{b!=c} gamma^2 S_b S_c ), 0 )
    """
    abs_total = float(np.abs(cvr).sum())
    if abs_total == 0.0:
        return 0.0
    total = float(cvr.sum())
    k, s = _ks(cvr, _RHO * _RHO)
    theta = min(total / abs_total, 0.0)
    lam = (PHI_INV_995 * PHI_INV_995 - 1.0) * (1.0 + theta) - theta
    inner = float(np.dot(k, k))
    cross = float(s.sum() ** 2 - np.dot(s, s))
    agg = math.sqrt(
        max(inner + (CROSS_CCY_GAMMA * CROSS_CCY_GAMMA) * cross, 0.0)
    )
    return max(total + lam * agg, 0.0)


def estimate_margins_batch(sensitivities: np.ndarray) -> np.ndarray:
    """[P, K] -> [P] per-bucket K estimates as ONE device matmul — the
    demo's heavy-compute shape (value thousands of portfolios per
    dispatch). ANALYTICS ONLY: runs in the accelerator's native
    precision (float32 without x64), so it may differ from the
    consensus float64 path in the last digits; anything recorded on
    ledger must come from bucket_margins/simm_im."""
    import jax.numpy as jnp

    ws = jnp.asarray(sensitivities * _RW[None, :])
    q = jnp.einsum(
        "pk,kl,pl->p", ws, jnp.asarray(_RHO), ws, precision="highest"
    )
    return np.sqrt(np.maximum(np.asarray(q), 0.0))


def aggregate_margin(k: np.ndarray, s: np.ndarray) -> float:
    """Cross-bucket SIMM aggregation over per-bucket (K_b, S_b)."""
    total = float(np.dot(k, k))
    cross = float(s.sum() ** 2 - np.dot(s, s))
    return math.sqrt(max(total + CROSS_CCY_GAMMA * cross, 0.0))


def fx_margin(fx_deltas: dict[str, float]) -> float:
    """FX delta margin over {currency: PV change per +1% spot move}
    sensitivities: single bucket, WS_i = FX_RISK_WEIGHT * s_i,
    K = sqrt( sum_i WS_i^2 + FX_CORR * sum_{i!=j} WS_i WS_j ).
    Fixed currency order (sorted) keeps the float64 op order shared."""
    if not fx_deltas:
        return 0.0
    ws = (
        np.asarray(
            [fx_deltas[c] for c in sorted(fx_deltas)], dtype=np.float64
        )
        * FX_RISK_WEIGHT
    )
    return _scalar_bucket_k(ws, FX_CORR)


# ---------------------------------------------------------------------------
# Equity / Commodity: bucketed delta classes over per-name sensitivities
#
# Published structure: sensitivities are per-name PV changes for a 1%
# relative move, assigned to numbered buckets (equity: market-cap x
# region x sector, 12 buckets; commodity: 17 product buckets). Within a
# bucket every distinct name correlates at one scalar rho_b; across
# buckets the S_b totals correlate through a gamma matrix; names that
# fit no bucket go to the RESIDUAL bucket, whose K adds OUTSIDE the
# cross-bucket square root (no diversification against classified
# risk). Weights/correlations below are representative of the
# published calibrations (exact tables are versioned + licensed).

RESIDUAL = "Residual"

EQUITY_RISK_WEIGHTS = (
    25.0, 32.0, 29.0, 27.0, 18.0, 21.0, 24.0, 21.0, 33.0, 34.0, 17.0, 17.0
)
EQUITY_INTRA_RHO = (
    0.14, 0.20, 0.19, 0.21, 0.24, 0.35, 0.34, 0.34, 0.20, 0.24, 0.62, 0.62
)
EQUITY_CROSS_GAMMA = 0.15
EQUITY_RESIDUAL_RW = max(EQUITY_RISK_WEIGHTS)

COMMODITY_RISK_WEIGHTS = (
    19.0, 20.0, 17.0, 18.0, 24.0, 20.0, 24.0, 41.0, 25.0, 91.0,
    20.0, 19.0, 16.0, 15.0, 10.0, 74.0, 16.0
)
COMMODITY_INTRA_RHO = (
    0.30, 0.97, 0.93, 0.97, 0.98, 0.90, 0.98, 0.60, 0.65, 0.55,
    0.93, 0.91, 0.89, 0.97, 0.21, 0.19, 0.99
)
COMMODITY_CROSS_GAMMA = 0.20


def _scalar_bucket_k(ws: np.ndarray, rho: float) -> float:
    """K_b for one bucket of weighted per-name sensitivities under a
    single intra-bucket correlation:
    K^2 = sum WS_i^2 + rho * sum_{i!=j} WS_i WS_j."""
    own = float(np.dot(ws, ws))
    cross = float(ws.sum() ** 2 - own)
    return math.sqrt(max(own + rho * cross, 0.0))


def _classed_margin(
    sensitivities: dict,
    n_buckets: int,
    bucket_ks,
    cross_gamma: float,
    residual_ks,
) -> float:
    """Shared bucket-walk + tail aggregation for every classed risk
    family (Equity/Commodity scalar buckets AND the credit CS01
    classes): per bucket `bucket_ks(bucket, entries) -> (K_b, S_b)`,
    then M = sqrt( sum_b K_b^2 + gamma * sum_{b!=c} S_b S_c )
    + K_residual. Fixed iteration order (sorted buckets; callees sort
    names) keeps the float64 op order shared between the agreeing
    parties. Unknown bucket numbers raise — a misfiled name must not
    silently drop; classes without a residual bucket pass
    residual_ks=None and RESIDUAL raises too."""
    ks: list[float] = []
    ss: list[float] = []
    k_residual = 0.0
    for bucket in sorted(
        sensitivities, key=lambda b: (isinstance(b, str), b)
    ):
        entries = sensitivities[bucket]
        if not entries:
            continue
        if bucket == RESIDUAL and residual_ks is not None:
            k_residual, _ = residual_ks(entries)
            continue
        if not isinstance(bucket, int) or not (1 <= bucket <= n_buckets):
            raise ValueError(f"unknown bucket {bucket!r}")
        k, s = bucket_ks(bucket, entries)
        ks.append(k)
        ss.append(s)
    if not ks and k_residual == 0.0:
        return 0.0
    kv = np.asarray(ks, dtype=np.float64)
    sv = np.asarray(ss, dtype=np.float64)
    inner = float(np.dot(kv, kv))
    cross = float(sv.sum() ** 2 - np.dot(sv, sv))
    return math.sqrt(max(inner + cross_gamma * cross, 0.0)) + k_residual


def _scalar_bucket_ks(names: dict, rw: float, rho: float):
    """(K_b, S_b) for one Equity/Commodity bucket of {name: s}."""
    s = np.asarray(
        [float(names[n]) for n in sorted(names)], dtype=np.float64
    )
    ws = s * rw
    k = _scalar_bucket_k(ws, rho)
    return k, max(min(float(ws.sum()), k), -k)


def equity_margin(sensitivities: dict) -> float:
    """Equity delta margin over {bucket: {issuer: PV change per +1%
    relative equity move}}; buckets 1-12 (market cap x region x
    sector; 11 = indexes/funds, 12 = volatility indexes) plus
    RESIDUAL."""
    return _classed_margin(
        sensitivities,
        len(EQUITY_RISK_WEIGHTS),
        lambda b, names: _scalar_bucket_ks(
            names, EQUITY_RISK_WEIGHTS[b - 1], EQUITY_INTRA_RHO[b - 1]
        ),
        EQUITY_CROSS_GAMMA,
        lambda names: _scalar_bucket_ks(names, EQUITY_RESIDUAL_RW, 0.0),
    )


# equity vega: the published SIMM gives every risk class a vega layer
# with one scalar class VRW over the same bucket structure/correlations
# as delta, and a curvature layer fed by SF(expiry)-scaled vega
EQUITY_VEGA_RISK_WEIGHT = 0.28


def equity_vega_margin(vega_sensitivities: dict) -> float:
    """Equity vega margin over {bucket: {issuer: PV change per +1
    vol-point move}}: delta's bucket structure with the scalar equity
    VRW (mirrors the IR class, where vega shares the delta
    correlations under `VEGA_RISK_WEIGHT`)."""
    return _classed_margin(
        vega_sensitivities,
        len(EQUITY_RISK_WEIGHTS),
        lambda b, names: _scalar_bucket_ks(
            names, EQUITY_VEGA_RISK_WEIGHT, EQUITY_INTRA_RHO[b - 1]
        ),
        EQUITY_CROSS_GAMMA,
        lambda names: _scalar_bucket_ks(names, EQUITY_VEGA_RISK_WEIGHT, 0.0),
    )


def equity_curvature_margin(cvr_sensitivities: dict) -> float:
    """Equity curvature over {bucket: {issuer: CVR}} where
    CVR = SF(expiry) * vega (`scaling_function`): the published
    curvature aggregation — squared correlations, lambda/theta tail
    factor, zero floor — applied to the equity bucket structure.
    Mirrors `curvature_margin` (IR), which runs the same formula over
    the tenor grid."""
    total = 0.0
    abs_total = 0.0
    for names in cvr_sensitivities.values():
        for v in names.values():
            total += float(v)
            abs_total += abs(float(v))
    # aggregate FIRST: the bucket walk validates bucket numbers, and a
    # misfiled name must raise even while its CVR happens to be zero
    agg = _classed_margin(
        cvr_sensitivities,
        len(EQUITY_RISK_WEIGHTS),
        lambda b, names: _scalar_bucket_ks(
            names, 1.0, EQUITY_INTRA_RHO[b - 1] ** 2
        ),
        EQUITY_CROSS_GAMMA * EQUITY_CROSS_GAMMA,
        lambda names: _scalar_bucket_ks(names, 1.0, 0.0),
    )
    if abs_total == 0.0:
        return 0.0
    theta = min(total / abs_total, 0.0)
    lam = (PHI_INV_995 * PHI_INV_995 - 1.0) * (1.0 + theta) - theta
    return max(total + lam * agg, 0.0)


def commodity_margin(sensitivities: dict) -> float:
    """Commodity delta margin over {bucket: {commodity: PV change per
    +1% relative price move}}; 17 published product buckets (16 =
    "other" — the published model has NO commodity residual bucket, so
    RESIDUAL raises here like any other unknown bucket)."""
    return _classed_margin(
        sensitivities,
        len(COMMODITY_RISK_WEIGHTS),
        lambda b, names: _scalar_bucket_ks(
            names, COMMODITY_RISK_WEIGHTS[b - 1], COMMODITY_INTRA_RHO[b - 1]
        ),
        COMMODITY_CROSS_GAMMA,
        None,
    )


# ---------------------------------------------------------------------------
# CreditQ / CreditNonQ: per-(issuer, tenor) CS01 classes
#
# Sensitivities are CS01 ladders on the five published credit vertices
# per issuer, bucketed by quality x region (CreditQ, 12 buckets) or
# rating band (CreditNonQ, 2 buckets). Correlation between entries of
# one bucket: 1 for the same (issuer, tenor), rho_same for the same
# issuer at different tenors, rho_diff across issuers; cross-bucket
# gamma is flat; residual bucket adds outside the square root.

CREDIT_TENORS_Y = (1.0, 2.0, 3.0, 5.0, 10.0)
N_CREDIT_TENORS = len(CREDIT_TENORS_Y)

CREDITQ_RISK_WEIGHTS_BP = (
    97.0, 110.0, 73.0, 65.0, 52.0, 39.0, 198.0, 187.0, 110.0, 66.0,
    67.0, 74.0
)
CREDITQ_RHO_SAME = 0.93
CREDITQ_RHO_DIFF = 0.42
CREDITQ_CROSS_GAMMA = 0.42
CREDITQ_RESIDUAL_RW = max(CREDITQ_RISK_WEIGHTS_BP)

CREDITNONQ_RISK_WEIGHTS_BP = (169.0, 646.0)
CREDITNONQ_RHO_SAME = 0.60
CREDITNONQ_RHO_DIFF = 0.21
CREDITNONQ_CROSS_GAMMA = 0.05
CREDITNONQ_RESIDUAL_RW = max(CREDITNONQ_RISK_WEIGHTS_BP)


def credit_cs01_ladder(notional: int, years_to_maturity: float) -> np.ndarray:
    """[5] CS01-style ladder for a single-name CDS: spread DV01 split
    between the two credit vertices framing maturity (the credit
    analogue of `bucket_pv01`)."""
    return vertex_split(
        CREDIT_TENORS_Y, years_to_maturity,
        notional * years_to_maturity / 10_000.0,
    )


def _credit_bucket_k(
    ladders: dict, rw: float, rho_same: float, rho_diff: float
) -> tuple[float, float]:
    """(K_b, S_b) for one credit bucket of {issuer: [5] CS01 ladder}:
    K^2 = sum_i ( sum_t WS_it^2 + rho_same (S_i^2 - sum_t WS_it^2) )
          + rho_diff * sum_{i!=j} S_i S_j."""
    k2 = 0.0
    issuer_sums: list[float] = []
    for issuer in sorted(ladders):
        ws = np.asarray(ladders[issuer], dtype=np.float64) * rw
        if ws.shape != (N_CREDIT_TENORS,):
            raise ValueError(
                f"credit ladder for {issuer!r} must have "
                f"{N_CREDIT_TENORS} vertices, got {ws.shape}"
            )
        own = float(np.dot(ws, ws))
        si = float(ws.sum())
        k2 += own + rho_same * (si * si - own)
        issuer_sums.append(si)
    sv = np.asarray(issuer_sums, dtype=np.float64)
    k2 += rho_diff * float(sv.sum() ** 2 - np.dot(sv, sv))
    k = math.sqrt(max(k2, 0.0))
    s = max(min(float(sv.sum()), k), -k)
    return k, s


def _credit_margin(
    sensitivities: dict,
    risk_weights: tuple,
    rho_same: float,
    rho_diff: float,
    cross_gamma: float,
    residual_rw: float,
) -> float:
    """Shared CreditQ/CreditNonQ aggregation over
    {bucket_number_or_RESIDUAL: {issuer: [5] CS01 ladder}}."""
    return _classed_margin(
        sensitivities,
        len(risk_weights),
        lambda b, ladders: _credit_bucket_k(
            ladders, risk_weights[b - 1], rho_same, rho_diff
        ),
        cross_gamma,
        lambda ladders: _credit_bucket_k(
            ladders, residual_rw, rho_same, rho_diff
        ),
    )


def credit_q_margin(sensitivities: dict) -> float:
    """Qualifying-credit delta margin over
    {bucket: {issuer: [5] CS01 ladder}} (12 quality x region buckets
    plus RESIDUAL)."""
    return _credit_margin(
        sensitivities, CREDITQ_RISK_WEIGHTS_BP, CREDITQ_RHO_SAME,
        CREDITQ_RHO_DIFF, CREDITQ_CROSS_GAMMA, CREDITQ_RESIDUAL_RW,
    )


def credit_nonq_margin(sensitivities: dict) -> float:
    """Non-qualifying-credit delta margin (2 rating-band buckets plus
    RESIDUAL)."""
    return _credit_margin(
        sensitivities, CREDITNONQ_RISK_WEIGHTS_BP, CREDITNONQ_RHO_SAME,
        CREDITNONQ_RHO_DIFF, CREDITNONQ_CROSS_GAMMA,
        CREDITNONQ_RESIDUAL_RW,
    )


def product_margin(class_margins: dict[str, float]) -> float:
    """Cross-risk-class SIMM aggregation:
    SIMM = sqrt( sum_r IM_r^2 + sum_{r!=s} psi_rs IM_r IM_s ) over the
    six published risk classes (unknown class names raise — a typo must
    not silently drop a margin contribution)."""
    unknown = set(class_margins) - set(RISK_CLASSES)
    if unknown:
        raise ValueError(f"unknown SIMM risk class(es): {sorted(unknown)}")
    im = np.asarray(
        [float(class_margins.get(c, 0.0)) for c in RISK_CLASSES],
        dtype=np.float64,
    )
    q = float(im @ RISK_CLASS_PSI @ im)
    return math.sqrt(max(q, 0.0))


def simm_breakdown(
    delta_buckets: dict[str, np.ndarray],
    vega_buckets: dict[str, np.ndarray] | None = None,
    fx_deltas: dict[str, float] | None = None,
    equity: dict | None = None,
    commodity: dict | None = None,
    credit_q: dict | None = None,
    credit_nonq: dict | None = None,
    equity_vega: dict | None = None,
    equity_cvr: dict | None = None,
) -> dict[str, float]:
    """Per-layer margins for {currency: [K] ladder} IR inputs plus the
    optional FX / Equity / Commodity / CreditQ / CreditNonQ classes.
    The IR risk-class margin is DeltaMargin + VegaMargin +
    CurvatureMargin (the published SIMM sums the three within a risk
    class); `total` is the cross-risk-class psi aggregation over every
    class with exposure — with IR-only input it equals the IR margin,
    so IR-only callers see the same number as before the other classes
    landed."""
    out = {
        "delta": 0.0, "vega": 0.0, "curvature": 0.0, "fx": 0.0,
        "equity": 0.0, "commodity": 0.0, "credit_q": 0.0,
        "credit_nonq": 0.0, "equity_vega": 0.0, "equity_curvature": 0.0,
    }
    if delta_buckets:
        mat = np.stack([delta_buckets[c] for c in sorted(delta_buckets)])
        out["delta"] = aggregate_margin(*bucket_margins(mat))
    if vega_buckets:
        mat = np.stack([vega_buckets[c] for c in sorted(vega_buckets)])
        out["vega"] = aggregate_margin(*vega_bucket_margins(mat))
        out["curvature"] = curvature_margin(curvature_ladders(mat))
    if fx_deltas:
        out["fx"] = fx_margin(fx_deltas)
    if equity:
        out["equity"] = equity_margin(equity)
    if equity_vega:
        out["equity_vega"] = equity_vega_margin(equity_vega)
    if equity_cvr:
        out["equity_curvature"] = equity_curvature_margin(equity_cvr)
    if commodity:
        out["commodity"] = commodity_margin(commodity)
    if credit_q:
        out["credit_q"] = credit_q_margin(credit_q)
    if credit_nonq:
        out["credit_nonq"] = credit_nonq_margin(credit_nonq)
    ir = out["delta"] + out["vega"] + out["curvature"]
    # a risk class's IM is the sum of its delta/vega/curvature layers
    eq = out["equity"] + out["equity_vega"] + out["equity_curvature"]
    out["total"] = product_margin({
        "IR": ir,
        "FX": out["fx"],
        "Equity": eq,
        "Commodity": out["commodity"],
        "CreditQ": out["credit_q"],
        "CreditNonQ": out["credit_nonq"],
    })
    return out


def simm_im(
    delta_buckets: dict[str, np.ndarray],
    vega_buckets: dict[str, np.ndarray] | None = None,
    fx_deltas: dict[str, float] | None = None,
    equity: dict | None = None,
    commodity: dict | None = None,
    credit_q: dict | None = None,
    credit_nonq: dict | None = None,
    equity_vega: dict | None = None,
    equity_cvr: dict | None = None,
) -> int:
    """Initial margin for {currency: [K] sensitivity ladder} IR inputs
    (delta, optionally vega — curvature follows from vega — and
    optionally FX spot / equity (delta + vega/curvature) / commodity /
    credit sensitivities), rounded to an integer ledger amount (both
    parties must agree bit-for-bit; every float op above has a fixed
    order, so IEEE-754 doubles give one answer on any host)."""
    return int(round(simm_breakdown(
        delta_buckets, vega_buckets, fx_deltas, equity, commodity,
        credit_q, credit_nonq, equity_vega, equity_cvr,
    )["total"]))
