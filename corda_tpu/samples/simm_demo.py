"""simm-valuation-demo: portfolio margin valuation agreed bilaterally.

Reference: samples/simm-valuation-demo/ — two parties value their
shared IRS portfolio under the ISDA SIMM (OpenGamma does the maths
there), then AGREE the valuation on ledger. Here the margin comes from
corda_tpu/samples/simm.py — a SIMM-structured IR-delta calculator
(tenor-bucketed PV01 ladders, risk weights, correlated intra-/cross-
bucket aggregation, the quadratic form as one TPU matmul) with openly
parameterised weights (ISDA's exact tables are versioned/licensed).
Both sides compute it independently and must agree bit-for-bit before
the mutually-signed valuation records.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core import serialization as ser
from ..core.contracts import register_contract, require_that
from ..core.identity import Party
from .irs_demo import InterestRateSwapState

SIMM_CONTRACT = "corda_tpu.samples.PortfolioValuation"


def initial_margin(
    swaps: list[InterestRateSwapState], now_micros: int = 0
) -> int:
    """ISDA-SIMM-structured IR-delta margin for the portfolio (the
    reference delegates to OpenGamma; corda_tpu/samples/simm.py carries
    the SIMM structure: tenor-bucketed PV01 ladders, risk weights,
    correlation-weighted intra- and cross-bucket aggregation, with the
    quadratic form as one TPU matmul). Deterministic: both parties run
    the same float64 op order and agree bit-for-bit."""
    from . import simm

    buckets: dict = {}
    for s in swaps:
        last = max(s.fixing_dates) if s.fixing_dates else now_micros
        years = max((last - now_micros) / (365.25 * 24 * 3600 * 1e6), 0.0)
        ladder = simm.bucket_pv01(s.notional, years)
        ccy = s.index_name.split("-")[0]   # index family as the bucket
        buckets[ccy] = buckets.get(ccy, 0) + ladder
    return simm.simm_im(buckets)


@ser.serializable
@dataclass(frozen=True)
class PortfolioValuationState:
    """The agreed margin for the portfolio between two parties at a
    valuation time."""

    party_a: Party
    party_b: Party
    valuation_micros: int
    portfolio_size: int
    margin: int

    @property
    def participants(self):
        return (self.party_a, self.party_b)

    def agreement_command(self):
        return AgreeValuation()


@ser.serializable
@dataclass(frozen=True)
class AgreeValuation:
    pass


class PortfolioValuation:
    def verify(self, ltx) -> None:
        outs = ltx.outputs_of_type(PortfolioValuationState)
        require_that("one valuation output", len(outs) == 1)
        cmds = ltx.commands_of_type(AgreeValuation)
        require_that("an agreement command", len(cmds) == 1)
        signers = set(cmds[0].signers)
        v = outs[0]
        require_that("margin is non-negative", v.margin >= 0)
        for p in v.participants:
            require_that(
                "both parties signed the valuation", p.owning_key in signers
            )


register_contract(SIMM_CONTRACT, PortfolioValuation())


def run(seed: int = 42, n_swaps: int = 3):
    """Build a small IRS portfolio, have both sides value it, agree it
    on ledger. Returns the recorded valuation state."""
    from ..finance.trade_flows import DealInstigatorFlow
    from ..samples.irs_demo import StartSwapFlow
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary", validating=True)
    a = net.create_node("PartyA")
    b = net.create_node("PartyB")
    oracle = net.create_node("Oracle")

    now = net.clock.now_micros()
    for i in range(n_swaps):
        swap = InterestRateSwapState(
            fixed_payer=a.party,
            floating_payer=b.party,
            oracle=oracle.party,
            notional=1_000_000 * (i + 1),
            fixed_rate_bps=400 + 25 * i,
            index_name="LIBOR-3M",
            # fixings out at (i+1) years: gives the portfolio real
            # PV01 mass on the SIMM tenor ladder
            fixing_dates=(now + (i + 1) * 31_557_600 * 10**6,),
        )
        fsm = a.start_flow(StartSwapFlow(swap, notary.party))
        net.run()
        fsm.result_or_throw()

    # both sides independently value their view of the shared portfolio
    portfolio_a = [
        s.state.data for s in a.vault.unconsumed_states(InterestRateSwapState)
    ]
    portfolio_b = [
        s.state.data for s in b.vault.unconsumed_states(InterestRateSwapState)
    ]
    margin_a = initial_margin(portfolio_a, now)
    margin_b = initial_margin(portfolio_b, now)
    assert margin_a == margin_b, "valuations must agree before signing"

    valuation = PortfolioValuationState(
        a.party, b.party, now, len(portfolio_a), margin_a
    )
    fsm = a.start_flow(
        DealInstigatorFlow(b.party, valuation, SIMM_CONTRACT, notary.party)
    )
    net.run()
    fsm.result_or_throw()
    recorded = b.vault.unconsumed_states(PortfolioValuationState)
    assert len(recorded) == 1
    return recorded[0].state.data


def main():
    v = run()
    print(
        f"portfolio of {v.portfolio_size} swaps valued: margin {v.margin}"
    )


if __name__ == "__main__":
    main()
