"""simm-valuation-demo: portfolio margin valuation agreed bilaterally.

Reference: samples/simm-valuation-demo/ — two parties value their
shared IRS portfolio under the ISDA SIMM (OpenGamma prices the trades
and produces bucketed delta/vega sensitivities there), then AGREE the
valuation on ledger. Here pricing comes from
`corda_tpu/samples/pricing.py` (zero curve + Black-76, bump-and-revalue
ladders on the SIMM vertices) and the margin from
`corda_tpu/samples/simm.py` — delta, vega AND curvature layers with
openly parameterised weights (ISDA's exact tables are versioned/
licensed). Both sides compute independently and must agree bit-for-bit
before the mutually-signed valuation records.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core import serialization as ser
from ..core.contracts import register_contract, require_that
from ..core.identity import Party
from .irs_demo import InterestRateSwapState

SIMM_CONTRACT = "corda_tpu.samples.PortfolioValuation"
SWAPTION_CONTRACT = "corda_tpu.samples.Swaption"
FX_FORWARD_CONTRACT = "corda_tpu.samples.FxForward"
CDS_CONTRACT = "corda_tpu.samples.CreditDefaultSwap"
EQUITY_OPTION_CONTRACT = "corda_tpu.samples.EquityOption"
COMMODITY_FORWARD_CONTRACT = "corda_tpu.samples.CommodityForward"

_YEAR_MICROS = 365.25 * 24 * 3600 * 1e6

# the demo's domestic IR bucket: swaps/swaptions key their ladders by
# index family (index_name.split("-")[0]) and every demo trade quotes
# the LIBOR family, which prices off the shared domestic curve
DOMESTIC_BUCKET = "LIBOR"


@ser.serializable
@dataclass(frozen=True)
class SwaptionState:
    """A European payer/receiver swaption between two parties — the
    portfolio's vega carrier (an IRS alone has no vol exposure, so the
    reference demo's vega sensitivities come from optionality like
    this)."""

    buyer: Party
    seller: Party
    notional: int
    strike_bps: int
    expiry_micros: int
    tenor_years: int
    index_name: str
    is_payer: bool = True

    @property
    def participants(self):
        return (self.buyer, self.seller)


class Swaption:
    def verify(self, ltx) -> None:
        outs = ltx.outputs_of_type(SwaptionState)
        require_that("one swaption output", len(outs) == 1)
        o = outs[0]
        require_that("positive notional", o.notional > 0)
        require_that("positive strike", o.strike_bps > 0)
        require_that("tenor at least a year", o.tenor_years >= 1)


register_contract(SWAPTION_CONTRACT, Swaption())


@ser.serializable
@dataclass(frozen=True)
class FxForwardState:
    """A deliverable FX forward: at maturity the buyer receives
    `notional_fgn` units of `foreign_ccy` against paying
    `notional_fgn * strike_milli / 1000` in the valuation currency.
    The portfolio's FX-risk-class carrier (an IRS book alone has no
    spot exposure, so the SIMM FX margin would be degenerate without
    cross-currency trades)."""

    buyer: Party
    seller: Party
    notional_fgn: int
    strike_milli: int          # domestic per foreign, in 1/1000ths
    maturity_micros: int
    foreign_ccy: str

    @property
    def participants(self):
        return (self.buyer, self.seller)


class FxForward:
    def verify(self, ltx) -> None:
        from . import pricing

        outs = ltx.outputs_of_type(FxForwardState)
        require_that("one forward output", len(outs) == 1)
        o = outs[0]
        require_that("positive foreign notional", o.notional_fgn > 0)
        require_that("positive strike", o.strike_milli > 0)
        require_that(
            "a known demo currency",
            o.foreign_ccy in pricing.DEMO_FX_SPOTS,
        )


register_contract(FX_FORWARD_CONTRACT, FxForward())


@ser.serializable
@dataclass(frozen=True)
class CdsState:
    """Single-name CDS: `buyer` pays `spread_bps` annually on
    `notional` for protection on `issuer` until maturity — the
    portfolio's CreditQ carrier (CS01 ladders on the five SIMM credit
    vertices price off the issuer's demo credit curve)."""

    buyer: Party
    seller: Party
    notional: int
    spread_bps: int
    maturity_micros: int
    issuer: str

    @property
    def participants(self):
        return (self.buyer, self.seller)


class CreditDefaultSwap:
    def verify(self, ltx) -> None:
        from . import pricing

        outs = ltx.outputs_of_type(CdsState)
        require_that("one cds output", len(outs) == 1)
        o = outs[0]
        require_that("positive notional", o.notional > 0)
        require_that("positive spread", o.spread_bps > 0)
        require_that(
            "a known reference issuer",
            o.issuer in pricing.DEMO_CREDIT_CURVES,
        )


register_contract(CDS_CONTRACT, CreditDefaultSwap())


@ser.serializable
@dataclass(frozen=True)
class EquityOptionState:
    """European equity option on `n_shares` of `name` — the Equity
    risk-class carrier (a rates book has no equity spot exposure)."""

    buyer: Party
    seller: Party
    n_shares: int
    strike_cents: int
    expiry_micros: int
    name: str
    is_call: bool = True

    @property
    def participants(self):
        return (self.buyer, self.seller)


class EquityOption:
    def verify(self, ltx) -> None:
        from . import pricing

        outs = ltx.outputs_of_type(EquityOptionState)
        require_that("one option output", len(outs) == 1)
        o = outs[0]
        require_that("positive share count", o.n_shares > 0)
        require_that("positive strike", o.strike_cents > 0)
        require_that(
            "a known equity name", o.name in pricing.DEMO_EQUITY_MARKET
        )


register_contract(EQUITY_OPTION_CONTRACT, EquityOption())


@ser.serializable
@dataclass(frozen=True)
class CommodityForwardState:
    """Deliverable commodity forward: buyer takes `units` of `name` at
    `strike_cents` per unit at maturity — the Commodity risk-class
    carrier."""

    buyer: Party
    seller: Party
    units: int
    strike_cents: int
    maturity_micros: int
    name: str

    @property
    def participants(self):
        return (self.buyer, self.seller)


class CommodityForward:
    def verify(self, ltx) -> None:
        from . import pricing

        outs = ltx.outputs_of_type(CommodityForwardState)
        require_that("one forward output", len(outs) == 1)
        o = outs[0]
        require_that("positive units", o.units > 0)
        require_that("positive strike", o.strike_cents > 0)
        require_that(
            "a known commodity", o.name in pricing.DEMO_COMMODITY_MARKET
        )


register_contract(COMMODITY_FORWARD_CONTRACT, CommodityForward())


@dataclass
class PortfolioSensitivities:
    """Every SIMM input family one pricing pass produces: IR delta /
    vega ladders and FX spot deltas keyed by currency, plus the
    bucketed equity / commodity spot deltas and per-issuer CreditQ
    CS01 ladders the round-3 carriers contribute."""

    delta: dict
    vega: dict
    fx: dict
    equity: dict
    commodity: dict
    credit_q: dict
    equity_vega: dict
    equity_cvr: dict


def portfolio_ladders(
    swaps: list[InterestRateSwapState],
    now_micros: int = 0,
    swaptions: list[SwaptionState] = (),
    market=None,
    fx_forwards: list[FxForwardState] = (),
    cds: list[CdsState] = (),
    equity_options: list[EquityOptionState] = (),
    commodity_forwards: list[CommodityForwardState] = (),
) -> PortfolioSensitivities:
    """Price the mixed portfolio into every SIMM sensitivity family
    off the shared market curves: per-trade bump-and-revalue IR delta
    ladders (swaps, swaptions, both legs of FX forwards, and the
    discounting legs of CDS / equity options / commodity forwards),
    swaption vega ladders, FX spot sensitivities, bucketed equity and
    commodity spot deltas, and per-issuer CreditQ CS01 ladders. The
    ONE pricing pass every margin consumer (demo, web API) shares."""
    from . import pricing, simm

    curve, vols = market if market is not None else pricing.demo_market()
    delta: dict = {}
    vega: dict = {}
    fx: dict = {}
    equity: dict = {}
    commodity: dict = {}
    credit_q: dict = {}
    equity_vega: dict = {}
    equity_cvr: dict = {}

    def add(buckets, ccy, ladder):
        buckets[ccy] = buckets.get(ccy, 0) + ladder

    def add_name(classed, bucket, name, value):
        classed.setdefault(bucket, {})
        classed[bucket][name] = classed[bucket].get(name, 0) + value

    for s in swaps:
        last = max(s.fixing_dates) if s.fixing_dates else now_micros
        years = max((last - now_micros) / _YEAR_MICROS, 0.0)
        ccy = s.index_name.split("-")[0]   # index family as the bucket
        add(
            delta, ccy,
            pricing.swap_delta_ladder(
                s.notional, s.fixed_rate_bps, years, curve
            ),
        )
    for o in swaptions:
        expiry = max((o.expiry_micros - now_micros) / _YEAR_MICROS, 0.0)
        ccy = o.index_name.split("-")[0]
        add(
            delta, ccy,
            pricing.swaption_delta_ladder(
                o.notional, o.strike_bps, expiry, o.tenor_years,
                curve, vols, o.is_payer,
            ),
        )
        add(
            vega, ccy,
            pricing.swaption_vega_ladder(
                o.notional, o.strike_bps, expiry, o.tenor_years,
                curve, vols, o.is_payer,
            ),
        )
    # foreign curves derive from the CALLER's domestic curve (basis
    # spread), built once per currency — a scenario-bumped market
    # moves both legs of every forward consistently
    fgn_curves: dict = {}
    for f in fx_forwards:
        years = max((f.maturity_micros - now_micros) / _YEAR_MICROS, 0.0)
        fgn_curve = fgn_curves.get(f.foreign_ccy)
        if fgn_curve is None:
            fgn_curve = fgn_curves[f.foreign_ccy] = (
                pricing.demo_foreign_curve(f.foreign_ccy, curve)
            )
        spot = pricing.DEMO_FX_SPOTS[f.foreign_ccy]
        strike = f.strike_milli / 1000.0
        add(
            fx, f.foreign_ccy,
            pricing.fx_forward_spot_delta(
                f.notional_fgn, strike, years, curve, fgn_curve, spot
            ),
        )
        dom_ladder, fgn_ladder = pricing.fx_forward_rate_ladders(
            f.notional_fgn, strike, years, curve, fgn_curve, spot
        )
        # the forward's domestic pay leg prices off the SAME curve as
        # the swaps/swaptions, so its delta must land in the same
        # bucket (DOMESTIC_BUCKET) to net intra-bucket — a separate
        # "USD" bucket would correlate identical-curve risk at the
        # 0.32 cross-bucket gamma instead of netting it
        add(delta, DOMESTIC_BUCKET, dom_ladder)
        add(delta, f.foreign_ccy, fgn_ladder)
    for c in cds:
        years = max((c.maturity_micros - now_micros) / _YEAR_MICROS, 0.0)
        bucket, credit_curve = pricing.DEMO_CREDIT_CURVES[c.issuer]
        add_name(
            credit_q, bucket, c.issuer,
            pricing.cds_cs01_ladder(
                c.notional, c.spread_bps, years, curve, credit_curve
            ),
        )
        add(
            delta, DOMESTIC_BUCKET,
            pricing.cds_rate_ladder(
                c.notional, c.spread_bps, years, curve, credit_curve
            ),
        )
    for e in equity_options:
        expiry = max((e.expiry_micros - now_micros) / _YEAR_MICROS, 0.0)
        bucket, spot, vol = pricing.DEMO_EQUITY_MARKET[e.name]
        strike = e.strike_cents / 100.0
        add_name(
            equity, bucket, e.name,
            pricing.equity_spot_delta(
                e.n_shares, strike, expiry, curve, spot, vol, e.is_call
            ),
        )
        ev = pricing.equity_vega(
            e.n_shares, strike, expiry, curve, spot, vol, e.is_call
        )
        add_name(equity_vega, bucket, e.name, ev)
        add_name(
            equity_cvr, bucket, e.name,
            simm.scaling_function(expiry) * ev,
        )
        add(
            delta, DOMESTIC_BUCKET,
            pricing.equity_option_rate_ladder(
                e.n_shares, strike, expiry, curve, spot, vol, e.is_call
            ),
        )
    for m in commodity_forwards:
        years = max((m.maturity_micros - now_micros) / _YEAR_MICROS, 0.0)
        bucket, spot, carry = pricing.DEMO_COMMODITY_MARKET[m.name]
        strike = m.strike_cents / 100.0
        add_name(
            commodity, bucket, m.name,
            pricing.commodity_spot_delta(
                m.units, strike, years, curve, spot, carry
            ),
        )
        add(
            delta, DOMESTIC_BUCKET,
            pricing.commodity_forward_rate_ladder(
                m.units, strike, years, curve, spot, carry
            ),
        )
    return PortfolioSensitivities(
        delta, vega, fx, equity, commodity, credit_q, equity_vega,
        equity_cvr,
    )


# the one registry of priced trade families: portfolio_ladders kwarg
# name -> state class. Every book enumerator (demo gather, web API
# vault sweep) iterates THIS mapping, so adding a seventh family is one
# entry + one pricing branch — not synchronized edits across call sites
TRADE_FAMILIES: dict[str, type] = {
    "swaps": InterestRateSwapState,
    "swaptions": SwaptionState,
    "fx_forwards": FxForwardState,
    "cds": CdsState,
    "equity_options": EquityOptionState,
    "commodity_forwards": CommodityForwardState,
}


def portfolio_ladders_book(
    book: dict, now_micros: int = 0, market=None
) -> PortfolioSensitivities:
    """`portfolio_ladders` over a {family_name: [states]} book keyed by
    `TRADE_FAMILIES` (unknown families raise — a misfiled family must
    not silently drop from the margin)."""
    unknown = set(book) - set(TRADE_FAMILIES)
    if unknown:
        raise ValueError(f"unknown trade families: {sorted(unknown)}")
    swaps = book.get("swaps", [])
    kwargs = {
        f: book[f] for f in TRADE_FAMILIES
        if f != "swaps" and f in book
    }
    return portfolio_ladders(swaps, now_micros, market=market, **kwargs)


def initial_margin_book(
    book: dict, now_micros: int = 0, market=None
) -> int:
    """SIMM margin for a {family_name: [states]} book: the priced
    sensitivities feed the IR (delta + vega + curvature), FX, Equity,
    Commodity and CreditQ risk classes of `simm.simm_im`,
    psi-aggregated across classes. Deterministic: both parties run the
    same fixed float64 op order and agree bit-for-bit."""
    from . import simm

    s = portfolio_ladders_book(book, now_micros, market)
    return simm.simm_im(
        s.delta, s.vega, s.fx,
        equity=s.equity, commodity=s.commodity, credit_q=s.credit_q,
        equity_vega=s.equity_vega, equity_cvr=s.equity_cvr,
    )


def initial_margin(
    swaps: list[InterestRateSwapState],
    now_micros: int = 0,
    swaptions: list[SwaptionState] = (),
    market=None,
    fx_forwards: list[FxForwardState] = (),
    cds: list[CdsState] = (),
    equity_options: list[EquityOptionState] = (),
    commodity_forwards: list[CommodityForwardState] = (),
) -> int:
    """`initial_margin_book` with one positional/keyword argument per
    family (the demo-facing spelling)."""
    return initial_margin_book(
        {
            "swaps": swaps,
            "swaptions": swaptions,
            "fx_forwards": fx_forwards,
            "cds": cds,
            "equity_options": equity_options,
            "commodity_forwards": commodity_forwards,
        },
        now_micros,
        market,
    )


@ser.serializable
@dataclass(frozen=True)
class PortfolioValuationState:
    """The agreed margin for the portfolio between two parties at a
    valuation time."""

    party_a: Party
    party_b: Party
    valuation_micros: int
    portfolio_size: int
    margin: int

    @property
    def participants(self):
        return (self.party_a, self.party_b)

    def agreement_command(self):
        return AgreeValuation()


@ser.serializable
@dataclass(frozen=True)
class AgreeValuation:
    pass


class PortfolioValuation:
    def verify(self, ltx) -> None:
        outs = ltx.outputs_of_type(PortfolioValuationState)
        require_that("one valuation output", len(outs) == 1)
        cmds = ltx.commands_of_type(AgreeValuation)
        require_that("an agreement command", len(cmds) == 1)
        signers = set(cmds[0].signers)
        v = outs[0]
        require_that("margin is non-negative", v.margin >= 0)
        for p in v.participants:
            require_that(
                "both parties signed the valuation", p.owning_key in signers
            )


register_contract(SIMM_CONTRACT, PortfolioValuation())


def run(
    seed: int = 42, n_swaps: int = 3, n_swaptions: int = 2,
    n_fx_forwards: int = 2, n_cds: int = 2, n_equity_options: int = 2,
    n_commodity_forwards: int = 2,
):
    """Build a mixed IRS + swaption + FX-forward + CDS + equity-option
    + commodity-forward portfolio, have both sides price it off the
    shared demo market and value it under SIMM across all the exposed
    risk classes (IR delta + vega + curvature, FX, CreditQ, Equity,
    Commodity; psi cross-class aggregation), agree the margin on
    ledger. Returns the recorded valuation state."""
    from ..finance.trade_flows import DealInstigatorFlow
    from ..samples.irs_demo import StartSwapFlow
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary", validating=True)
    a = net.create_node("PartyA")
    b = net.create_node("PartyB")
    oracle = net.create_node("Oracle")

    now = net.clock.now_micros()
    for i in range(n_swaps):
        swap = InterestRateSwapState(
            fixed_payer=a.party,
            floating_payer=b.party,
            oracle=oracle.party,
            notional=1_000_000 * (i + 1),
            fixed_rate_bps=400 + 25 * i,
            index_name="LIBOR-3M",
            # fixings out at (i+1) years: gives the portfolio real
            # PV01 mass on the SIMM tenor ladder
            fixing_dates=(now + (i + 1) * 31_557_600 * 10**6,),
        )
        fsm = a.start_flow(StartSwapFlow(swap, notary.party))
        net.run()
        fsm.result_or_throw()
    for i in range(n_swaptions):
        swaption = SwaptionState(
            buyer=a.party,
            seller=b.party,
            notional=2_000_000 * (i + 1),
            strike_bps=300 + 50 * i,
            expiry_micros=now + (i + 2) * 31_557_600 * 10**6,
            tenor_years=5,
            index_name="LIBOR-3M",
        )
        fsm = a.start_flow(
            DealInstigatorFlow(b.party, swaption, SWAPTION_CONTRACT, notary.party)
        )
        net.run()
        fsm.result_or_throw()
    fx_ccys = ("EUR", "GBP")
    for i in range(n_fx_forwards):
        fwd = FxForwardState(
            buyer=a.party,
            seller=b.party,
            notional_fgn=3_000_000 * (i + 1),
            strike_milli=1_100 + 120 * i,
            maturity_micros=now + (i + 1) * 31_557_600 * 10**6,
            foreign_ccy=fx_ccys[i % len(fx_ccys)],
        )
        fsm = a.start_flow(
            DealInstigatorFlow(b.party, fwd, FX_FORWARD_CONTRACT, notary.party)
        )
        net.run()
        fsm.result_or_throw()
    from . import pricing as _pricing

    issuers = tuple(sorted(_pricing.DEMO_CREDIT_CURVES))
    for i in range(n_cds):
        swap_cds = CdsState(
            buyer=a.party,
            seller=b.party,
            notional=5_000_000 * (i + 1),
            spread_bps=80 + 20 * i,
            maturity_micros=now + (i + 3) * 31_557_600 * 10**6,
            issuer=issuers[i % len(issuers)],
        )
        fsm = a.start_flow(
            DealInstigatorFlow(b.party, swap_cds, CDS_CONTRACT, notary.party)
        )
        net.run()
        fsm.result_or_throw()
    eq_names = tuple(sorted(_pricing.DEMO_EQUITY_MARKET))
    for i in range(n_equity_options):
        name = eq_names[i % len(eq_names)]
        _, spot, _ = _pricing.DEMO_EQUITY_MARKET[name]
        opt = EquityOptionState(
            buyer=a.party,
            seller=b.party,
            n_shares=10_000 * (i + 1),
            strike_cents=int(spot * 100 * (0.95 + 0.1 * i)),
            expiry_micros=now + (i + 1) * 31_557_600 * 10**6,
            name=name,
            is_call=(i % 2 == 0),
        )
        fsm = a.start_flow(
            DealInstigatorFlow(
                b.party, opt, EQUITY_OPTION_CONTRACT, notary.party
            )
        )
        net.run()
        fsm.result_or_throw()
    cm_names = tuple(sorted(_pricing.DEMO_COMMODITY_MARKET))
    for i in range(n_commodity_forwards):
        name = cm_names[i % len(cm_names)]
        _, spot, _ = _pricing.DEMO_COMMODITY_MARKET[name]
        cfwd = CommodityForwardState(
            buyer=a.party,
            seller=b.party,
            units=20_000 * (i + 1),
            strike_cents=int(spot * 100 * (0.98 + 0.05 * i)),
            maturity_micros=now + (i + 1) * 31_557_600 * 10**6,
            name=name,
        )
        fsm = a.start_flow(
            DealInstigatorFlow(
                b.party, cfwd, COMMODITY_FORWARD_CONTRACT, notary.party
            )
        )
        net.run()
        fsm.result_or_throw()

    # both sides independently price + value their view of the shared
    # portfolio against the shared market data
    def gather(node):
        return {
            family: [
                s.state.data for s in node.vault.unconsumed_states(cls)
            ]
            for family, cls in TRADE_FAMILIES.items()
        }

    book_a = gather(a)
    book_b = gather(b)
    margin_a = initial_margin_book(book_a, now)
    margin_b = initial_margin_book(book_b, now)
    assert margin_a == margin_b, "valuations must agree before signing"

    valuation = PortfolioValuationState(
        a.party, b.party, now,
        sum(len(v) for v in book_a.values()), margin_a,
    )
    fsm = a.start_flow(
        DealInstigatorFlow(b.party, valuation, SIMM_CONTRACT, notary.party)
    )
    net.run()
    fsm.result_or_throw()
    recorded = b.vault.unconsumed_states(PortfolioValuationState)
    assert len(recorded) == 1
    return recorded[0].state.data


def main():
    v = run()
    print(
        f"portfolio of {v.portfolio_size} trades valued: margin {v.margin}"
    )


if __name__ == "__main__":
    main()
