"""simm-valuation-demo: portfolio margin valuation agreed bilaterally.

Reference: samples/simm-valuation-demo/ — two parties value their
shared IRS portfolio under the ISDA SIMM (OpenGamma prices the trades
and produces bucketed delta/vega sensitivities there), then AGREE the
valuation on ledger. Here pricing comes from
`corda_tpu/samples/pricing.py` (zero curve + Black-76, bump-and-revalue
ladders on the SIMM vertices) and the margin from
`corda_tpu/samples/simm.py` — delta, vega AND curvature layers with
openly parameterised weights (ISDA's exact tables are versioned/
licensed). Both sides compute independently and must agree bit-for-bit
before the mutually-signed valuation records.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..core import serialization as ser
from ..core.contracts import register_contract, require_that
from ..core.identity import Party
from .irs_demo import InterestRateSwapState

SIMM_CONTRACT = "corda_tpu.samples.PortfolioValuation"
SWAPTION_CONTRACT = "corda_tpu.samples.Swaption"

_YEAR_MICROS = 365.25 * 24 * 3600 * 1e6


@ser.serializable
@dataclass(frozen=True)
class SwaptionState:
    """A European payer/receiver swaption between two parties — the
    portfolio's vega carrier (an IRS alone has no vol exposure, so the
    reference demo's vega sensitivities come from optionality like
    this)."""

    buyer: Party
    seller: Party
    notional: int
    strike_bps: int
    expiry_micros: int
    tenor_years: int
    index_name: str
    is_payer: bool = True

    @property
    def participants(self):
        return (self.buyer, self.seller)


class Swaption:
    def verify(self, ltx) -> None:
        outs = ltx.outputs_of_type(SwaptionState)
        require_that("one swaption output", len(outs) == 1)
        o = outs[0]
        require_that("positive notional", o.notional > 0)
        require_that("positive strike", o.strike_bps > 0)
        require_that("tenor at least a year", o.tenor_years >= 1)


register_contract(SWAPTION_CONTRACT, Swaption())


def portfolio_ladders(
    swaps: list[InterestRateSwapState],
    now_micros: int = 0,
    swaptions: list[SwaptionState] = (),
    market=None,
) -> tuple[dict, dict]:
    """Price the mixed portfolio into per-currency (delta, vega)
    sensitivity ladders off the shared market curve: per-trade
    bump-and-revalue delta ladders (swaps and swaptions) plus swaption
    vega ladders. The ONE pricing pass every margin consumer (demo,
    web API) shares."""
    from . import pricing

    curve, vols = market if market is not None else pricing.demo_market()
    delta: dict = {}
    vega: dict = {}

    def add(buckets, ccy, ladder):
        buckets[ccy] = buckets.get(ccy, 0) + ladder

    for s in swaps:
        last = max(s.fixing_dates) if s.fixing_dates else now_micros
        years = max((last - now_micros) / _YEAR_MICROS, 0.0)
        ccy = s.index_name.split("-")[0]   # index family as the bucket
        add(
            delta, ccy,
            pricing.swap_delta_ladder(
                s.notional, s.fixed_rate_bps, years, curve
            ),
        )
    for o in swaptions:
        expiry = max((o.expiry_micros - now_micros) / _YEAR_MICROS, 0.0)
        ccy = o.index_name.split("-")[0]
        add(
            delta, ccy,
            pricing.swaption_delta_ladder(
                o.notional, o.strike_bps, expiry, o.tenor_years,
                curve, vols, o.is_payer,
            ),
        )
        add(
            vega, ccy,
            pricing.swaption_vega_ladder(
                o.notional, o.strike_bps, expiry, o.tenor_years,
                curve, vols, o.is_payer,
            ),
        )
    return delta, vega


def initial_margin(
    swaps: list[InterestRateSwapState],
    now_micros: int = 0,
    swaptions: list[SwaptionState] = (),
    market=None,
) -> int:
    """SIMM margin for the mixed portfolio: the priced ladders feed the
    delta + vega + curvature layers of `simm.simm_im`. Deterministic:
    both parties run the same fixed float64 op order and agree
    bit-for-bit."""
    from . import simm

    delta, vega = portfolio_ladders(swaps, now_micros, swaptions, market)
    return simm.simm_im(delta, vega)


@ser.serializable
@dataclass(frozen=True)
class PortfolioValuationState:
    """The agreed margin for the portfolio between two parties at a
    valuation time."""

    party_a: Party
    party_b: Party
    valuation_micros: int
    portfolio_size: int
    margin: int

    @property
    def participants(self):
        return (self.party_a, self.party_b)

    def agreement_command(self):
        return AgreeValuation()


@ser.serializable
@dataclass(frozen=True)
class AgreeValuation:
    pass


class PortfolioValuation:
    def verify(self, ltx) -> None:
        outs = ltx.outputs_of_type(PortfolioValuationState)
        require_that("one valuation output", len(outs) == 1)
        cmds = ltx.commands_of_type(AgreeValuation)
        require_that("an agreement command", len(cmds) == 1)
        signers = set(cmds[0].signers)
        v = outs[0]
        require_that("margin is non-negative", v.margin >= 0)
        for p in v.participants:
            require_that(
                "both parties signed the valuation", p.owning_key in signers
            )


register_contract(SIMM_CONTRACT, PortfolioValuation())


def run(seed: int = 42, n_swaps: int = 3, n_swaptions: int = 2):
    """Build a mixed IRS + swaption portfolio, have both sides price it
    off the shared demo market and value it under SIMM (delta + vega +
    curvature), agree the margin on ledger. Returns the recorded
    valuation state."""
    from ..finance.trade_flows import DealInstigatorFlow
    from ..samples.irs_demo import StartSwapFlow
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary", validating=True)
    a = net.create_node("PartyA")
    b = net.create_node("PartyB")
    oracle = net.create_node("Oracle")

    now = net.clock.now_micros()
    for i in range(n_swaps):
        swap = InterestRateSwapState(
            fixed_payer=a.party,
            floating_payer=b.party,
            oracle=oracle.party,
            notional=1_000_000 * (i + 1),
            fixed_rate_bps=400 + 25 * i,
            index_name="LIBOR-3M",
            # fixings out at (i+1) years: gives the portfolio real
            # PV01 mass on the SIMM tenor ladder
            fixing_dates=(now + (i + 1) * 31_557_600 * 10**6,),
        )
        fsm = a.start_flow(StartSwapFlow(swap, notary.party))
        net.run()
        fsm.result_or_throw()
    for i in range(n_swaptions):
        swaption = SwaptionState(
            buyer=a.party,
            seller=b.party,
            notional=2_000_000 * (i + 1),
            strike_bps=300 + 50 * i,
            expiry_micros=now + (i + 2) * 31_557_600 * 10**6,
            tenor_years=5,
            index_name="LIBOR-3M",
        )
        fsm = a.start_flow(
            DealInstigatorFlow(b.party, swaption, SWAPTION_CONTRACT, notary.party)
        )
        net.run()
        fsm.result_or_throw()

    # both sides independently price + value their view of the shared
    # portfolio against the shared market data
    def gather(node):
        swaps = [
            s.state.data
            for s in node.vault.unconsumed_states(InterestRateSwapState)
        ]
        opts = [
            s.state.data for s in node.vault.unconsumed_states(SwaptionState)
        ]
        return swaps, opts

    swaps_a, opts_a = gather(a)
    swaps_b, opts_b = gather(b)
    margin_a = initial_margin(swaps_a, now, opts_a)
    margin_b = initial_margin(swaps_b, now, opts_b)
    assert margin_a == margin_b, "valuations must agree before signing"

    valuation = PortfolioValuationState(
        a.party, b.party, now, len(swaps_a) + len(opts_a), margin_a
    )
    fsm = a.start_flow(
        DealInstigatorFlow(b.party, valuation, SIMM_CONTRACT, notary.party)
    )
    net.run()
    fsm.result_or_throw()
    recorded = b.vault.unconsumed_states(PortfolioValuationState)
    assert len(recorded) == 1
    return recorded[0].state.data


def main():
    v = run()
    print(
        f"portfolio of {v.portfolio_size} trades valued: margin {v.margin}"
    )


if __name__ == "__main__":
    main()
