"""SIMM valuation demo web API (reference: the simm-valuation-demo's
REST surface, samples/simm-valuation-demo/src/main/kotlin/net/corda/
vega/api/PortfolioApi.kt — whoami :252, {party}/trades :119,
portfolio/summary :198, portfolio/valuations :181,
portfolio/valuations/calculate :275 — served to a TS frontend by the
reference webserver; here the same surface mounts on the terminal-first
NodeWebServer gateway).

Mounted at /api/simm:
  GET  /api/simm/whoami                 own identity + known peers
  GET  /api/simm/trades                 swap / swaption / FX forward /
                                        CDS / equity option / commodity
                                        forward trade summaries
  GET  /api/simm/portfolio/summary      counts and notional aggregates
  GET  /api/simm/portfolio/margin       SIMM breakdown (delta/vega/
                                        curvature/fx/equity/commodity/
                                        credit_q/total, psi cross-class
                                        aggregate) priced off the
                                        shared demo market;
                                        ?t=<micros> sets the valuation
                                        time
  GET  /api/simm/portfolio/valuations   recorded on-ledger valuations
  POST /api/simm/portfolio/valuations/calculate
        {"counterparty", "valuation_micros"?} -> price, agree and
        record the margin with the counterparty (both sign)
"""

from __future__ import annotations

from ..client.webserver import WebApiPlugin, register_web_api
from ..node.vault_query import VaultQueryCriteria
from .irs_demo import InterestRateSwapState
from .simm_demo import (
    SIMM_CONTRACT,
    CdsState,
    CommodityForwardState,
    EquityOptionState,
    FxForwardState,
    PortfolioValuationState,
    SwaptionState,
)


def _states(ctx, cls):
    page = ctx.wait(
        ctx.client.vault_query_by(
            VaultQueryCriteria(contract_state_types=(cls,))
        )
    )
    return [sar.state.data for sar in page.states]


def _whoami(ctx, query, body):
    me = ctx.wait(ctx.client.node_identity()).legal_identity
    peers = [
        info.legal_identity.name
        for info in ctx.wait(ctx.client.network_map_snapshot())
    ]
    return 200, {"me": me.name, "peers": sorted(peers)}


def _trades(ctx, query, body):
    swaps = [
        {
            "type": "swap",
            "fixed_payer": s.fixed_payer.name,
            "floating_payer": s.floating_payer.name,
            "notional": s.notional,
            "fixed_rate_bps": s.fixed_rate_bps,
            "index": s.index_name,
            "fixings": len(s.fixings),
        }
        for s in _states(ctx, InterestRateSwapState)
    ]
    swaptions = [
        {
            "type": "swaption",
            "buyer": o.buyer.name,
            "seller": o.seller.name,
            "notional": o.notional,
            "strike_bps": o.strike_bps,
            "tenor_years": o.tenor_years,
            "payer": o.is_payer,
            "index": o.index_name,
        }
        for o in _states(ctx, SwaptionState)
    ]
    forwards = [
        {
            "type": "fx_forward",
            "buyer": f.buyer.name,
            "seller": f.seller.name,
            "notional_fgn": f.notional_fgn,
            "strike_milli": f.strike_milli,
            "foreign_ccy": f.foreign_ccy,
        }
        for f in _states(ctx, FxForwardState)
    ]
    cds = [
        {
            "type": "cds",
            "buyer": c.buyer.name,
            "seller": c.seller.name,
            "notional": c.notional,
            "spread_bps": c.spread_bps,
            "issuer": c.issuer,
        }
        for c in _states(ctx, CdsState)
    ]
    options = [
        {
            "type": "equity_option",
            "buyer": o.buyer.name,
            "seller": o.seller.name,
            "n_shares": o.n_shares,
            "strike_cents": o.strike_cents,
            "name": o.name,
            "call": o.is_call,
        }
        for o in _states(ctx, EquityOptionState)
    ]
    commodities = [
        {
            "type": "commodity_forward",
            "buyer": m.buyer.name,
            "seller": m.seller.name,
            "units": m.units,
            "strike_cents": m.strike_cents,
            "name": m.name,
        }
        for m in _states(ctx, CommodityForwardState)
    ]
    return 200, {
        "trades": swaps + swaptions + forwards + cds + options + commodities
    }


def _summary(ctx, query, body):
    swaps = _states(ctx, InterestRateSwapState)
    swaptions = _states(ctx, SwaptionState)
    forwards = _states(ctx, FxForwardState)
    cds = _states(ctx, CdsState)
    options = _states(ctx, EquityOptionState)
    commodities = _states(ctx, CommodityForwardState)
    return 200, {
        "swaps": len(swaps),
        "swaptions": len(swaptions),
        "fx_forwards": len(forwards),
        "cds": len(cds),
        "equity_options": len(options),
        "commodity_forwards": len(commodities),
        "swap_notional": sum(s.notional for s in swaps),
        "swaption_notional": sum(o.notional for o in swaptions),
        "fx_forward_notional": sum(f.notional_fgn for f in forwards),
        "cds_notional": sum(c.notional for c in cds),
    }


def _parse_t(query) -> int:
    try:
        return int(query.get("t", ["0"])[0])
    except (TypeError, ValueError):
        return 0


def _book(ctx):
    """One vault sweep of every priced trade family (keyed by the
    simm_demo.TRADE_FAMILIES registry)."""
    from .simm_demo import TRADE_FAMILIES

    return {
        family: _states(ctx, cls) for family, cls in TRADE_FAMILIES.items()
    }


def _margin(ctx, query, body):
    from .simm_demo import portfolio_ladders_book
    from . import simm

    now = _parse_t(query)
    book = _book(ctx)
    s = portfolio_ladders_book(book, now)
    parts = simm.simm_breakdown(
        s.delta, s.vega, s.fx,
        equity=s.equity, commodity=s.commodity, credit_q=s.credit_q,
        equity_vega=s.equity_vega, equity_cvr=s.equity_cvr,
    )
    # the total IS the psi cross-class aggregate (simm.simm_im's
    # definition) — one pricing pass, no second computation to drift
    # from the parts
    return 200, {
        "delta": round(parts["delta"], 2),
        "vega": round(parts["vega"], 2),
        "curvature": round(parts["curvature"], 2),
        "fx": round(parts["fx"], 2),
        "equity": round(parts["equity"], 2),
        "equity_vega": round(parts["equity_vega"], 2),
        "equity_curvature": round(parts["equity_curvature"], 2),
        "commodity": round(parts["commodity"], 2),
        "credit_q": round(parts["credit_q"], 2),
        "margin": int(round(parts["total"])),
        "trades": sum(len(v) for v in book.values()),
    }


def _valuations(ctx, query, body):
    vals = [
        {
            "party_a": v.party_a.name,
            "party_b": v.party_b.name,
            "valuation_micros": v.valuation_micros,
            "portfolio_size": v.portfolio_size,
            "margin": v.margin,
        }
        for v in _states(ctx, PortfolioValuationState)
    ]
    return 200, {"valuations": vals}


def _calculate(ctx, query, body):
    from .simm_demo import initial_margin_book

    if not isinstance(body, dict):
        return 400, {"error": "JSON object body required"}
    counterparty = body.get("counterparty")
    if not isinstance(counterparty, str):
        return 400, {"error": "counterparty (party name) required"}
    raw_t = body.get("valuation_micros", 0)
    if not isinstance(raw_t, int) or isinstance(raw_t, bool):
        return 400, {"error": "valuation_micros must be an integer"}
    now = raw_t
    parties = {
        info.legal_identity.name: info.legal_identity
        for info in ctx.wait(ctx.client.network_map_snapshot())
    }
    if counterparty not in parties:
        return 400, {"error": f"unknown counterparty {counterparty!r}"}
    notaries = ctx.wait(ctx.client.notary_identities())
    if not notaries:
        return 400, {"error": "no notary on the network"}
    me = ctx.wait(ctx.client.node_identity()).legal_identity
    book = _book(ctx)
    margin = initial_margin_book(book, now)
    valuation = PortfolioValuationState(
        me, parties[counterparty], now,
        sum(len(v) for v in book.values()), margin,
    )
    handle = ctx.wait(
        ctx.client.start_flow(
            "corda_tpu.finance.trade_flows.DealInstigatorFlow",
            other=parties[counterparty],
            deal_state=valuation,
            contract=SIMM_CONTRACT,
            notary=notaries[0],
        )
    )
    stx = ctx.wait(handle.result)
    return 200, {"tx_id": stx.id.bytes_.hex(), "margin": margin}


_INDEX = b"""<!doctype html>
<title>corda_tpu simm-valuation-demo</title>
<h1>SIMM portfolio valuation</h1>
<p>GET <a href="/api/simm/portfolio/summary">summary</a> |
<a href="/api/simm/portfolio/margin">margin</a> |
<a href="/api/simm/portfolio/valuations">valuations</a> |
<a href="/api/simm/trades">trades</a> |
POST /api/simm/portfolio/valuations/calculate</p>
"""

SIMM_WEB_API = WebApiPlugin(
    prefix="simm",
    routes=(
        ("GET", "whoami", _whoami),
        ("GET", "trades", _trades),
        ("GET", "portfolio/summary", _summary),
        ("GET", "portfolio/margin", _margin),
        ("GET", "portfolio/valuations", _valuations),
        ("POST", "portfolio/valuations/calculate", _calculate),
    ),
    static=(("index.html", "text/html", _INDEX),),
)

register_web_api(SIMM_WEB_API)
