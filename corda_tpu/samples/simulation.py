"""network simulation + event trace: the network-visualiser's engine.

Reference: samples/network-visualiser/ — a JavaFX map animating an
`IRSSimulation` over a MockNetwork (simulation/Simulation.kt). The GUI
is out of scope; the simulation engine and its observable event stream
(what the visualiser renders) are here: run a scripted multi-party day
of activity and emit a structured trace of every message delivery and
flow lifecycle event.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any


@dataclass(frozen=True)
class SimEvent:
    kind: str          # "flow-added" | "flow-removed" | "progress" | "delivery"
    node: str
    detail: str


class NetworkSimulation:
    """Wraps a MockNetwork with event instrumentation (Simulation.kt's
    role): every node's flow lifecycle and progress steps, plus fabric
    deliveries, land in `events` in deterministic order."""

    def __init__(self, seed: int = 42):
        from ..testing.mock_network import MockNetwork

        self.net = MockNetwork(seed=seed)
        self.events: list[SimEvent] = []

    def add_node(self, name: str, **kw):
        node = self.net.create_node(name, **kw)
        self._instrument(node)
        return node

    def add_notary(self, name: str = "Notary", validating: bool = True):
        node = self.net.create_notary(name, validating=validating)
        self._instrument(node)
        return node

    def _instrument(self, node) -> None:
        def lifecycle(kind: str, fsm) -> None:
            self.events.append(
                SimEvent(
                    f"flow-{kind}", node.name, type(fsm.logic).__name__
                )
            )

        def progress(fsm, label: str) -> None:
            self.events.append(SimEvent("progress", node.name, label))

        node.smm.lifecycle.append(lifecycle)
        node.smm.changes.append(progress)

    def run(self) -> int:
        return self.net.run()

    def trace(self) -> list[str]:
        return [f"{e.node}: {e.kind} {e.detail}" for e in self.events]


def run_irs_simulation(seed: int = 42):
    """The IRSSimulation arc with full instrumentation: agree a swap,
    scheduler-driven fixings, oracle signatures — returning the event
    trace the visualiser would animate."""
    from ..samples.irs_demo import (
        FixOf,
        InterestRateSwapState,
        RateOracleService,
        StartSwapFlow,
    )

    sim = NetworkSimulation(seed=seed)
    notary = sim.add_notary()
    bank_a = sim.add_node("BankA")
    bank_b = sim.add_node("BankB")
    oracle_node = sim.add_node("RateOracle")

    now = sim.net.clock.now_micros()
    dates = tuple(now + (i + 1) * 1_000_000 for i in range(2))
    oracle_node.services.cordapp_service(RateOracleService).configure(
        {("LIBOR-3M", d): 500 + i for i, d in enumerate(dates)}
    )
    swap = InterestRateSwapState(
        bank_a.party, bank_b.party, oracle_node.party,
        5_000_000, 475, "LIBOR-3M", dates,
    )
    fsm = bank_a.start_flow(StartSwapFlow(swap, notary.party))
    sim.run()
    fsm.result_or_throw()
    for _ in dates:
        sim.net.clock.advance(1_000_000)
        sim.run()
    return sim


def main():
    sim = run_irs_simulation()
    for line in sim.trace():
        print(line)
    print(f"-- {len(sim.events)} events")


if __name__ == "__main__":
    main()
