"""trader-demo: cash-vs-commercial-paper DvP between two banks.

Reference: samples/trader-demo/ — Bank B self-issues commercial paper,
Bank A gets cash from the bank-of-corda issuer, then they trade
atomically through `TwoPartyTradeFlow` via a validating notary.
"""

from __future__ import annotations

from ..core.contracts import Amount, Issued, TimeWindow
from ..core.identity import PartyAndReference
from ..core.transactions import TransactionBuilder
from ..finance.cash import CashState
from ..finance.commercial_paper import CommercialPaperState, generate_issue
from ..finance.trade_flows import IssuanceRequesterFlow, SellerFlow
from ..flows.core_flows import FinalityFlow


def run(seed: int = 42, face: int = 100_000, price: int = 92_000):
    """The demo arc on a MockNetwork; returns (buyer_paper, seller_cash)."""
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary", validating=True)
    bank = net.create_node("BankOfCorda")
    seller = net.create_node("BankA")    # sells paper
    buyer = net.create_node("BankB")     # pays cash

    # 1. buyer funds itself from the central issuer
    buyer.run_flow(IssuanceRequesterFlow(bank.party, price + 8_000, "USD"))
    bank_usd = Issued(PartyAndReference(bank.party, b"\x01"), "USD")

    # 2. seller self-issues paper maturing in 30 days
    now = net.clock.now_micros()
    builder = TransactionBuilder(notary.party)
    builder.set_time_window(TimeWindow(until_time=now + 60_000_000))
    generate_issue(
        builder,
        PartyAndReference(seller.party, b"\x01"),
        Amount(face, bank_usd),
        now + 30 * 24 * 3600 * 1_000_000,
    )
    seller.run_flow(
        FinalityFlow(seller.services.sign_initial_transaction(builder))
    )
    paper = seller.vault.unconsumed_states(CommercialPaperState)[0]

    # 3. the trade
    fsm = seller.start_flow(
        SellerFlow(buyer.party, paper, Amount(price, bank_usd))
    )
    net.run()
    fsm.result_or_throw()

    buyer_paper = buyer.vault.unconsumed_states(CommercialPaperState)
    seller_cash = sum(
        s.state.data.amount.quantity
        for s in seller.vault.unconsumed_states(CashState)
    )
    return buyer_paper, seller_cash


def run_via_rpc(seed: int = 42, face: int = 100_000, price: int = 92_000):
    """The demo arc with the buyer's funding, the trade itself, and
    every report query driven over CordaRPCOps (the
    TraderDemoClientApi.runBuyer/runSeller shape from
    samples/trader-demo/). The seller's one-off paper self-issue stays
    in-process — it is demo fixture setup, not part of the client
    pattern. Returns a report dict assembled from RPC vault queries."""
    from ..client.common import wait_rpc
    from ..node import rpc as rpclib
    from ..node.vault_query import VaultQueryCriteria
    from ..testing.mock_network import MockNetwork

    net = MockNetwork(seed=seed)
    notary = net.create_notary("Notary", validating=True)
    bank = net.create_node("BankOfCorda")
    seller = net.create_node("BankA")
    buyer = net.create_node("BankB")

    users = rpclib.RPCUserService(rpclib.RpcUser("demo", "demo", ("ALL",)))
    for node in (seller, buyer):
        rpclib.RPCServer(
            rpclib.CordaRPCOpsImpl(node.services, node.smm),
            node.messaging,
            users,
        )

    def client(node_name: str) -> rpclib.RPCClient:
        return rpclib.RPCClient(
            net.fabric.endpoint(f"{node_name}-console"),
            node_name,
            "demo",
            "demo",
        )

    def wait(fut):
        return wait_rpc(fut, lambda: net.run(), 60.0)

    buyer_rpc = client("BankB")
    seller_rpc = client("BankA")

    # buyer: request issuance from the bank (runBuyer)
    handle = wait(
        buyer_rpc.start_flow(
            "corda_tpu.finance.trade_flows.IssuanceRequesterFlow",
            issuer=bank.party,
            quantity=price + 8_000,
            currency="USD",
        )
    )
    wait(handle.result)

    # seller: self-issue paper, then offer it (runSeller)
    bank_usd = Issued(PartyAndReference(bank.party, b"\x01"), "USD")
    now = net.clock.now_micros()
    builder = TransactionBuilder(notary.party)
    builder.set_time_window(TimeWindow(until_time=now + 60_000_000))
    generate_issue(
        builder,
        PartyAndReference(seller.party, b"\x01"),
        Amount(face, bank_usd),
        now + 30 * 24 * 3600 * 1_000_000,
    )
    seller.run_flow(
        FinalityFlow(seller.services.sign_initial_transaction(builder))
    )
    paper = seller.vault.unconsumed_states(CommercialPaperState)[0]
    handle = wait(
        seller_rpc.start_flow(
            SellerFlow,
            buyer=buyer.party,
            asset=paper,
            price=Amount(price, bank_usd),
        )
    )
    wait(handle.result)

    # the report comes from RPC vault queries, not node internals
    def holdings(rpc, cls):
        page = wait(
            rpc.vault_query_by(VaultQueryCriteria(contract_state_types=(cls,)))
        )
        return page.states

    return {
        "buyer_paper": len(holdings(buyer_rpc, CommercialPaperState)),
        "seller_cash": sum(
            s.state.data.amount.quantity
            for s in holdings(seller_rpc, CashState)
        ),
        "buyer_cash": sum(
            s.state.data.amount.quantity
            for s in holdings(buyer_rpc, CashState)
        ),
    }


def main():
    paper, cash = run()
    print(f"in-process: buyer holds {len(paper)} paper, seller has {cash}")
    report = run_via_rpc()
    print(
        "via RPC:    buyer holds "
        f"{report['buyer_paper']} paper + {report['buyer_cash']} change, "
        f"seller has {report['seller_cash']}"
    )


if __name__ == "__main__":
    main()
