"""Test kit: MockNetwork (Ring 3), test identities, ledger DSL."""

from .mock_network import MockNetwork, MockNode

__all__ = ["MockNetwork", "MockNode"]
