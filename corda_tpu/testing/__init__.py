"""Test kit: MockNetwork (Ring 3), test identities, ledger DSL, and
the simulated-time fleet soak (fleet.py)."""

from .fleet import (
    ChaosEvent,
    ChaosPlane,
    FleetScenario,
    FleetSim,
    InvariantChecker,
    Phase,
    TrafficMix,
)
from .mock_network import MockNetwork, MockNode

__all__ = [
    "ChaosEvent",
    "ChaosPlane",
    "FleetScenario",
    "FleetSim",
    "InvariantChecker",
    "MockNetwork",
    "MockNode",
    "Phase",
    "TrafficMix",
]
