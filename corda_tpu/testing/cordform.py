"""Cordform: generate a deployable node-directory tree from a network spec.

Reference: the `cordformation` gradle plugin (`deployNodes` task —
gradle-plugins/cordformation/.../Cordform.groovy + Node.groovy, shared
model in cordform-common): a DSL describing the nodes of a network is
turned into per-node directories with their config files, ready to
launch.

Here the spec is data (NodeSpec list), the output is a directory per
node containing node.toml plus a run.sh, with static ports assigned
from a base and every node pointed at the map host. `python -m
corda_tpu.node --config <dir>/node.toml` boots each one.
"""

from __future__ import annotations

import os
import stat
from dataclasses import dataclass, field
from typing import Optional

from ..node.config import NodeConfig, RpcUserConfig, write_config


@dataclass(frozen=True)
class NodeSpec:
    """One node in the network DSL (Node.groovy's fields)."""

    name: str
    notary: str = ""
    cluster_peers: tuple[str, ...] = ()
    cluster_name: str = "DistributedNotary"
    rpc_users: tuple[RpcUserConfig, ...] = (
        RpcUserConfig("user1", "password", ("ALL",)),
    )
    cordapps: tuple[str, ...] = ("corda_tpu.finance",)
    extra: dict = field(default_factory=dict)


def deploy_nodes(
    specs: list[NodeSpec],
    out_dir: str,
    base_port: int = 10000,
    host: str = "127.0.0.1",
    map_host_name: Optional[str] = None,
) -> dict[str, NodeConfig]:
    """Write one directory per node under `out_dir` (the deployNodes
    task). The first spec (or `map_host_name`) becomes the network map
    host; every other node is configured against its static port.
    Returns name -> NodeConfig."""
    if not specs:
        raise ValueError("no nodes in the network spec")
    names = [s.name for s in specs]
    if len(set(names)) != len(names):
        raise ValueError("duplicate node names in the network spec")
    map_name = map_host_name or specs[0].name
    if map_name not in names:
        raise ValueError(f"map host {map_name!r} is not in the spec")
    ports = {s.name: base_port + i for i, s in enumerate(specs)}

    # Pre-generate the map host's TLS identity so every other config can
    # pin its fingerprint statically (at runtime the node finds the
    # material already in its database and reuses it — the cert-
    # distribution role of the reference's generated node directories).
    from ..node.fabric import TlsIdentity
    from ..node.persistence import NodeDatabase, PersistentKVStore

    map_dir = os.path.join(out_dir, map_name)
    os.makedirs(map_dir, exist_ok=True)
    db = NodeDatabase(os.path.join(map_dir, "node.db"))
    try:
        store = PersistentKVStore(db, "node_tls")
        cert, key = store.get(b"cert"), store.get(b"key")
        if cert is None or key is None:   # partial writes regenerate
            tls = TlsIdentity.generate(map_name)
            store.put(b"cert", tls.cert_pem)
            store.put(b"key", tls.key_pem)
        else:
            tls = TlsIdentity(bytes(cert), bytes(key))
    finally:
        db.close()

    configs: dict[str, NodeConfig] = {}
    for spec in specs:
        node_dir = os.path.join(out_dir, spec.name)
        os.makedirs(node_dir, exist_ok=True)
        kw = dict(spec.extra)
        if spec.name != map_name:
            kw.update(
                network_map_peer=map_name,
                network_map_host=host,
                network_map_port=ports[map_name],
                network_map_fingerprint=tls.fingerprint,
            )
        cfg = NodeConfig(
            name=spec.name,
            base_dir=node_dir,
            p2p_host=host,
            p2p_port=ports[spec.name],
            notary=spec.notary,
            cluster_peers=spec.cluster_peers,
            cluster_name=spec.cluster_name,
            rpc_users=spec.rpc_users,
            cordapps=spec.cordapps,
            **kw,
        )
        conf_path = os.path.join(node_dir, "node.toml")
        write_config(cfg, conf_path)
        run_path = os.path.join(node_dir, "run.sh")
        with open(run_path, "w") as f:
            f.write(
                "#!/bin/sh\n"
                f'exec python -m corda_tpu.node --config "{conf_path}" "$@"\n'
            )
        os.chmod(run_path, os.stat(run_path).st_mode | stat.S_IEXEC)
        configs[spec.name] = cfg
    return configs
