"""Driver DSL: spawn real node processes, drive them over RPC, tear down.

Reference: the Driver DSL (test-utils/.../testing/driver/Driver.kt:
64-70) — spawns actual node JVMs (ProcessUtilities.kt), starts the
network-map node first, waits on handshakes, allocates ports, and tears
everything down via a ShutdownManager; `startNodesInProcess` exists for
debugging. Specialised drivers (RPCDriver, VerifierDriver) build on it.

Usage:
    with driver(base_dir) as d:
        notary = d.start_node("Notary", notary="validating")
        alice = d.start_node("Alice")
        cli = d.rpc(alice)
        handle = d.wait(cli.start_flow(...))
        d.wait(handle.result)

Nodes run `python -m corda_tpu.node` as real OS processes; the driver
holds one console fabric endpoint that can reach every node (TLS
fingerprints read from each node's database after boot).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from ..crypto import schemes
from ..node import rpc as rpclib
from ..node.config import NodeConfig, RpcUserConfig, write_config
from ..node.fabric import FabricEndpoint, PeerAddress, TlsIdentity
from ..node.persistence import NodeDatabase, PersistentKVStore

DEFAULT_USER = RpcUserConfig("driver", "driver-pw", ("ALL",))


def _stable_seed(name: str) -> int:
    """Process-independent (PYTHONHASHSEED-proof) dev key seed: a new
    driver session over an existing base_dir must regenerate the SAME
    config a previous session wrote."""
    import hashlib

    return int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "big") + 1


@dataclass
class NodeHandle:
    """One spawned node process (Driver.kt NodeHandle)."""

    name: str
    config: NodeConfig
    process: subprocess.Popen
    p2p_port: int
    tls_fingerprint: Optional[bytes]
    stderr_path: str

    @property
    def address(self) -> PeerAddress:
        return PeerAddress("127.0.0.1", self.p2p_port, self.tls_fingerprint)

    def kill(self) -> None:
        """SIGKILL — the crash-test move (Disruption.kt 'kill')."""
        if self.process.poll() is None:
            self.process.kill()
            self.process.wait(timeout=10)

    def sigstop(self) -> None:
        """Hang the process without killing it (Disruption.kt:17)."""
        self.process.send_signal(signal.SIGSTOP)

    def sigcont(self) -> None:
        self.process.send_signal(signal.SIGCONT)

    def terminate(self) -> int:
        if self.process.poll() is None:
            self.process.send_signal(signal.SIGTERM)
            try:
                return self.process.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.process.kill()
                return self.process.wait(timeout=5)
        return self.process.returncode

    @property
    def alive(self) -> bool:
        return self.process.poll() is None

    def stderr_tail(self, n: int = 2000) -> str:
        try:
            with open(self.stderr_path) as f:
                return f.read()[-n:]
        except OSError:
            return ""


class DriverTimeout(AssertionError):
    pass


class Driver:
    """The running driver session (use via the `driver()` context
    manager). Starts a map-host first; later nodes register with it."""

    def __init__(self, base_dir: str, env_overrides: Optional[dict] = None):
        self.base_dir = str(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.nodes: dict[str, NodeHandle] = {}
        self.map_host: Optional[NodeHandle] = None
        self._env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        self._env["PYTHONPATH"] = (
            os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
            + ":" + self._env.get("PYTHONPATH", "")
        )
        if env_overrides:
            self._env.update(env_overrides)
        # the console endpoint (created lazily: needs no node)
        self._console_db = NodeDatabase(
            os.path.join(self.base_dir, "driver-console.db")
        )
        self._console = FabricEndpoint(
            "driver-console",
            schemes.generate_keypair(seed=0xD214E2),
            self._console_db,
            resolve=self._resolve,
        )
        self._console.start()
        self._clients: dict[str, rpclib.RPCClient] = {}

    # -- node lifecycle ------------------------------------------------------

    def start_node(
        self,
        name: str,
        timeout: float = 120.0,
        **config_kw,
    ) -> NodeHandle:
        """Spawn one node process; the first node becomes the network
        map host, later ones register with it (Driver.kt starts the
        map node first the same way)."""
        if self.map_host is not None and "network_map_peer" not in config_kw:
            config_kw.update(
                network_map_peer=self.map_host.name,
                network_map_host="127.0.0.1",
                network_map_port=self.map_host.p2p_port,
                network_map_fingerprint=self.map_host.tls_fingerprint,
            )
        cfg = NodeConfig(
            name=name,
            base_dir=os.path.join(self.base_dir, name),
            rpc_users=config_kw.pop("rpc_users", (DEFAULT_USER,)),
            key_seed=config_kw.pop("key_seed", _stable_seed(name)),
            # CPU reference verifier by default: driver tests exercise
            # node orchestration, not the kernels; per-process jit
            # compiles would dominate the run (pass "tpu" to override)
            verifier_backend=config_kw.pop("verifier_backend", "cpu"),
            **config_kw,
        )
        conf_path = os.path.join(self.base_dir, f"{name}.toml")
        write_config(cfg, conf_path)
        return self._spawn(cfg, conf_path, timeout)

    def restart_node(self, handle: NodeHandle, timeout: float = 120.0) -> NodeHandle:
        """Boot a replacement process over the same base_dir (state
        recovery drills — StabilityTest.kt's crash-restart soak). The
        replacement re-binds the SAME port: peers (and, for a restarted
        map host, statically-configured clients) keep routing to it."""
        import dataclasses

        if handle.alive:
            handle.terminate()
        cfg = dataclasses.replace(handle.config, p2p_port=handle.p2p_port)
        conf_path = os.path.join(self.base_dir, f"{handle.name}.toml")
        write_config(cfg, conf_path)
        replacement = self._spawn(cfg, conf_path, timeout)
        if self.map_host is not None and self.map_host.name == handle.name:
            self.map_host = replacement
        return replacement

    def _spawn(self, cfg: NodeConfig, conf_path: str, timeout: float) -> NodeHandle:
        stderr_path = os.path.join(self.base_dir, f"{cfg.name}.stderr")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "corda_tpu.node",
                "--config", conf_path, "--print-port",
            ],
            stdout=subprocess.PIPE,   # binary: read raw, never block
            stderr=open(stderr_path, "a"),
            env=self._env,
        )
        import selectors

        port = None
        deadline = time.monotonic() + timeout
        sel = selectors.DefaultSelector()
        sel.register(proc.stdout, selectors.EVENT_READ)
        buf = ""
        try:
            while time.monotonic() < deadline:
                # poll, never block: a node wedged WITHOUT printing must
                # still hit the startup deadline
                if not sel.select(timeout=0.2):
                    if proc.poll() is not None:
                        break
                    continue
                chunk = os.read(proc.stdout.fileno(), 4096).decode(
                    errors="replace"
                )
                if not chunk and proc.poll() is not None:
                    break
                buf += chunk
                while port is None and "\n" in buf:
                    line, buf = buf.split("\n", 1)
                    if line.startswith("P2P_PORT="):
                        port = int(line.strip().split("=")[1])
                if port is not None:
                    break
        finally:
            sel.close()
        if port is None:
            proc.kill()
            raise DriverTimeout(
                f"node {cfg.name} failed to start; stderr: "
                + open(stderr_path).read()[-2000:]
            )
        handle = NodeHandle(
            cfg.name, cfg, proc, port,
            self._read_tls_fingerprint(cfg), stderr_path,
        )
        self.nodes[cfg.name] = handle
        if self.map_host is None:
            self.map_host = handle
        for key in [
            k for k in self._clients if k.split(":", 1)[0] == cfg.name
        ]:
            del self._clients[key]   # stale clients after restart
        return handle

    @staticmethod
    def _read_tls_fingerprint(cfg: NodeConfig) -> Optional[bytes]:
        if not cfg.use_tls:
            return None
        db = NodeDatabase(os.path.join(cfg.base_dir, "node.db"))
        try:
            store = PersistentKVStore(db, "node_tls")
            cert = store.get(b"cert")
            key = store.get(b"key")
            if cert is None:
                return None
            return TlsIdentity(bytes(cert), bytes(key)).fingerprint
        finally:
            db.close()

    def _resolve(self, peer: str) -> Optional[PeerAddress]:
        handle = self.nodes.get(peer)
        return handle.address if handle else None

    # -- RPC -----------------------------------------------------------------

    def rpc(
        self,
        node: NodeHandle,
        username: str = DEFAULT_USER.username,
        password: str = DEFAULT_USER.password,
    ) -> rpclib.RPCClient:
        key = f"{node.name}:{username}"
        if key not in self._clients:
            self._clients[key] = rpclib.RPCClient(
                self._console, node.name, username, password
            )
        return self._clients[key]

    def wait(self, fut, timeout: float = 90.0):
        """Pump the console until the RPC future resolves."""
        deadline = time.monotonic() + timeout
        while not fut.done and time.monotonic() < deadline:
            self._console.pump()
            time.sleep(0.01)
        if not fut.done:
            raise DriverTimeout("RPC future did not resolve")
        return fut.get()

    def wait_until(self, predicate, timeout: float = 90.0, poll: float = 0.05):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self._console.pump()
            if predicate():
                return True
            time.sleep(poll)
        raise DriverTimeout("condition not reached")

    def wait_for_network(self, n: int, timeout: float = 90.0) -> None:
        """Wait until some node's map shows n nodes (registration
        settled — Driver.kt's networkMapStartStrategy wait)."""
        any_node = next(iter(self.nodes.values()))
        cli = self.rpc(any_node)

        def settled():
            fut = cli.network_map_snapshot()
            try:
                self.wait(fut, timeout=10)
            except DriverTimeout:
                return False
            return len(fut.get()) >= n

        self.wait_until(settled, timeout=timeout)

    def identity_of(self, node: NodeHandle):
        """The node's legal identity Party, via RPC."""
        return self.wait(self.rpc(node).node_identity()).legal_identity

    def notary_identity(self, name: Optional[str] = None):
        any_node = next(iter(self.nodes.values()))
        ids = self.wait(self.rpc(any_node).notary_identities())
        if name is not None:
            ids = [p for p in ids if p.name == name]
        if not ids:
            raise DriverTimeout("no notary identity visible")
        return ids[0]

    # -- teardown ------------------------------------------------------------

    def shutdown(self) -> None:
        for handle in self.nodes.values():
            try:
                handle.terminate()
            except Exception:
                pass
        self._console.stop()
        self._console_db.close()


class driver:
    """Context manager entry point (the `driver { ... }` DSL)."""

    def __init__(self, base_dir: str, **kw):
        self._driver = Driver(base_dir, **kw)

    def __enter__(self) -> Driver:
        return self._driver

    def __exit__(self, exc_type, exc, tb) -> None:
        self._driver.shutdown()
