"""Expect DSL: structured assertions over event streams.

Reference: test-utils/.../testing/Expect.kt:10-34 (SURVEY.md §4 Ring 3)
— tests declare the *shape* of an expected event sequence with
`expect` / `sequence` / `parallel` / `replicate` combinators and run it
against an Rx stream (vault updates, state-machine feed, …). Here the
fabric is deterministically pumped, so events are recorded first and
the combinator tree is matched as a nondeterministic automaton:
`sequence` requires in-order matches, `parallel` any interleaving,
`replicate(n)` = n parallel copies. In strict mode (the reference's
default) every observed event must be consumed by some expectation.

    events = record(vault.updates, lambda: run_network())
    expect_events(
        events,
        sequence(
            expect(VaultUpdate, lambda u: len(u.produced) == 1),
            parallel(
                expect(VaultUpdate, lambda u: u.consumed),
                expect(VaultUpdate),
            ),
        ),
    )

Matched (expectation, event) pairs fire each `expect`'s action callback
once a full match is found (actions run post-hoc so backtracking never
fires an action on a dead branch).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple


@dataclass(frozen=True)
class ExpectCompose:
    """Base marker for expectation-tree nodes."""


@dataclass(frozen=True)
class _Single(ExpectCompose):
    cls: type
    predicate: Optional[Callable[[Any], bool]]
    action: Optional[Callable[[Any], None]]

    def matches(self, event: Any) -> bool:
        if not isinstance(event, self.cls):
            return False
        return self.predicate is None or bool(self.predicate(event))


@dataclass(frozen=True)
class _Sequence(ExpectCompose):
    children: Tuple[ExpectCompose, ...]


@dataclass(frozen=True)
class _Parallel(ExpectCompose):
    children: Tuple[ExpectCompose, ...]


def expect(
    cls: type = object,
    predicate: Optional[Callable[[Any], bool]] = None,
    action: Optional[Callable[[Any], None]] = None,
) -> ExpectCompose:
    """Expect a single event of `cls` satisfying `predicate`; on a full
    match, `action(event)` runs (assertions live there)."""
    return _Single(cls, predicate, action)


def sequence(*expectations: ExpectCompose) -> ExpectCompose:
    return _Sequence(tuple(expectations))


def parallel(*expectations: ExpectCompose) -> ExpectCompose:
    return _Parallel(tuple(expectations))


def replicate(n: int, template: Callable[[int], ExpectCompose]) -> ExpectCompose:
    """n structurally-identical expectations in parallel
    (Expect.kt `replicate`)."""
    return _Parallel(tuple(template(i) for i in range(n)))


# -- the matcher -------------------------------------------------------------
#
# A state is (node-or-None, matches) where node is the *residual*
# expectation tree and matches the (single, event-index) pairs consumed
# on this branch. consume() expands one event into successor states.


def _consume(node, event, idx):
    """Yield (residual_node_or_None, matched_pairs) successors after
    `node` consumes `event`."""
    if isinstance(node, _Single):
        if node.matches(event):
            yield None, ((node, idx),)
        return
    if isinstance(node, _Sequence):
        if not node.children:
            return
        head, rest = node.children[0], node.children[1:]
        for residual, pairs in _consume(head, event, idx):
            tail: Tuple[ExpectCompose, ...]
            tail = ((residual,) if residual is not None else ()) + rest
            if not tail:
                yield None, pairs
            elif len(tail) == 1:
                yield tail[0], pairs
            else:
                yield _Sequence(tail), pairs
        return
    if isinstance(node, _Parallel):
        for i, child in enumerate(node.children):
            for residual, pairs in _consume(child, event, idx):
                rest = (
                    node.children[:i]
                    + ((residual,) if residual is not None else ())
                    + node.children[i + 1:]
                )
                if not rest:
                    yield None, pairs
                elif len(rest) == 1:
                    yield rest[0], pairs
                else:
                    yield _Parallel(rest), pairs
        return
    raise TypeError(f"not an expectation node: {node!r}")


def expect_events(
    events: Sequence[Any],
    expectation: ExpectCompose,
    strict: bool = True,
) -> None:
    """Match the recorded `events` against the expectation tree; raise
    AssertionError if no interleaving satisfies it. strict=True (the
    reference default) additionally requires every event to be consumed
    by some expect()."""
    # frontier of (residual, matches); None residual == complete
    frontier = [(expectation, ())]
    for idx, event in enumerate(events):
        nxt = []
        seen = set()
        for residual, pairs in frontier:
            if residual is not None:
                for r2, new_pairs in _consume(residual, event, idx):
                    key = (r2, pairs + new_pairs)
                    if key not in seen:
                        seen.add(key)
                        nxt.append((r2, pairs + new_pairs))
            if not strict:
                key = (residual, pairs)
                if key not in seen:
                    seen.add(key)
                    nxt.append((residual, pairs))
        if strict and not nxt:
            raise AssertionError(
                f"unexpected event at index {idx}: {event!r} "
                f"(no live expectation branch consumes it)"
            )
        if nxt:
            frontier = nxt
    for residual, pairs in frontier:
        if residual is None:
            for single, idx in pairs:
                if single.action is not None:
                    single.action(events[idx])
            return
    remaining = [r for r, _ in frontier if r is not None]
    raise AssertionError(
        f"expectation not satisfied after {len(events)} events; "
        f"unmatched residue (one branch shown): {remaining[0]!r}"
    )


def record(observable, pump: Callable[[], Any]) -> list:
    """Subscribe to `observable`, run `pump()` (e.g. mock-network
    run_network), return the events emitted during it."""
    events: list = []
    unsubscribe = observable.subscribe(events.append)
    try:
        pump()
    finally:
        unsubscribe()
    return events
