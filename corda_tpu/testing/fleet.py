"""Fleet soak: a deterministic, simulated-time fleet simulator.

The serving control plane grew piecewise — deadlines/admission/brownout
(node/qos.py), watchdogs/SLO alerts//cluster (utils/health.py), the
sharded commit plane (node/notary.py), perf attribution (utils/perf.py)
— but nothing drove them TOGETHER at production shape. This module is
that driver, the ROADMAP's "acceptance bar for 'millions of users'
claims, runnable in CI": thousands of client identities multiplexed
against a multi-node notary cluster in all three flavours (batching
single-node, Raft, BFT), with churn injected through first-class fabric
hooks and the ledger reconciled bit-exact against a model afterwards.

Reference shape: `tools/loadtest` (LoadTest.kt's generate/apply/gather/
reconcile loop, Disruption.kt's kill/restart/slow interleavings,
CrossCashTest's invariant) — but where the reference drives real
processes over SSH for minutes, this runs on the shared `TestClock`:
a thousand-node-second soak executes in CI seconds, deterministically.

Three cooperating pieces:

  `FleetSim` — the scenario engine. A declarative `FleetScenario`
      (client count, phases of ramp/steady/spike traffic, a
      `TrafficMix` of deadline distributions, bulk traffic, injected
      double-spends and cross-shard conflicts) executes round by
      round: each round submits through the REAL notary entry points
      (`NotaryService.process` generators, stepped exactly the way the
      flow state machine steps them), pumps the fabric to quiescence,
      beats/ticks every member's health plane, samples the
      healthz//cluster story into a timeline, and advances the clock.

  `ChaosPlane` — fault scheduling at stream fractions (the
      `Disruption.at_fraction` idiom). Faults act through the
      first-class seams — `messaging.FabricFaults` for partitions/
      slow links/drops, member kill+rebuild for crash-restart — never
      by monkeypatching. Every application/revert is logged with its
      simulated-time window: the "injected reality" the invariant
      checker reconciles the control plane's story against.

  `InvariantChecker` — reconciliation. After the soak: every alive
      replica's committed map must agree; every injected double-spend
      must have exactly one winner ON THE LEDGER; signed answers must
      match the ledger exactly (no phantom commits, no lost value);
      nothing admitted-then-expired; the steady-state admitted p99
      must hold the SLO; brownout must have shed ONLY bulk/
      deadline-less traffic; and the health plane must have told the
      truth — healthz flipped while the fault was live, /cluster
      marked the victim, both recovered after the heal.

Throughput with reconciliation is a claim; without it, just a number.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..core.contracts import StateRef
from ..core.identity import Party
from ..core.transactions import WireTransaction
from ..crypto import schemes
from ..crypto.hashes import SecureHash
from ..node import qos as qoslib
from ..node.messaging import FabricFaults, Message
from ..node.notary import NotaryError
from ..utils import tracing as tracelib
from ..utils.health import (
    AlertRule,
    ClusterHealth,
    HealthMonitor,
    HealthPolicy,
    IncidentRecorder,
)
from .mock_network import MockNetwork


def _metric_count(registry, name: str) -> int:
    """Read a counter/meter total WITHOUT registering it: the fleet
    reconciles against series OWNED by the services it drives, and a
    `registry.counter(name)` read would create the series when the
    owner has not — a second registration site for every dashboard
    name the checker touches (tools/lint metrics pass)."""
    m = registry.get(name)
    return m.count if m is not None else 0

# outcome vocabulary — one set for records, reports and assertions
OUT_SIGNED = "signed"
OUT_CONFLICT = "conflict"
OUT_SHED = "shed"
OUT_UNAVAILABLE = "unavailable"
OUT_LOST = "lost"          # future never resolved (in flight at a kill)

FLAVOURS = ("batching", "raft", "bft", "distributed")


# ---------------------------------------------------------------------------
# scenario DSL


@dataclass(frozen=True)
class TrafficMix:
    """What one phase's offered traffic looks like.

    `bulk_fraction` of the offer is deadline-less bulk (resolution-
    flood-shaped) traffic routed through the QoS lane seam (batching
    flavour only — cluster flavours have no lane router and ignore
    it). `conflict_fraction` of interactive spends ALSO submit a rival
    transaction claiming the same input — the injected double-spends
    the ledger must resolve to exactly one winner. `cross_shard_
    fraction` of spends carry two inputs routed to different commit-
    plane shards (sharded batching only)."""

    deadline_micros: int = 60_000
    deadline_jitter_micros: int = 0
    bulk_fraction: float = 0.0
    conflict_fraction: float = 0.0
    cross_shard_fraction: float = 0.0


@dataclass(frozen=True)
class Phase:
    """One traffic phase: `offered_per_round` requests injected each of
    `rounds` rounds. Ramp/steady/spike arcs are just phase sequences."""

    name: str
    rounds: int
    offered_per_round: int
    mix: Optional[TrafficMix] = None     # None = the scenario default


@dataclass(frozen=True)
class FleetScenario:
    """The declarative soak: who offers how much, when, for how long.

    `clients` identities are minted up front (names `fleet-c<k>` over a
    small keypair pool — non-validating notaries authenticate requesters
    by name, so the pool keeps thousand-client fleets cheap) and
    round-robined through the traffic, so a long enough stream touches
    EVERY identity. `round_micros` is the simulated wall step between
    delivery rounds; total simulated soak time is
    sum(phase rounds) * round_micros."""

    clients: int = 1000
    phases: tuple[Phase, ...] = (
        Phase("ramp", 4, 8),
        Phase("steady", 12, 16),
        Phase("spike", 4, 48),
        Phase("steady2", 8, 16),
    )
    mix: TrafficMix = field(default_factory=TrafficMix)
    round_micros: int = 20_000
    drain_rounds: int = 60
    # rounds run AFTER the last answer lands: consensus followers
    # apply the replicated tail (raft commit-index propagation, BFT
    # checkpoint execution) so the replica-agreement reconciliation
    # reads converged ledgers, and health alerts get room to resolve
    settle_rounds: int = 10
    seed: int = 0
    key_pool: int = 8

    def total_offered(self) -> int:
        return sum(p.rounds * p.offered_per_round for p in self.phases)

    def mix_of(self, phase: Phase) -> TrafficMix:
        return phase.mix or self.mix


@dataclass
class FleetClient:
    name: str
    party: Party
    submitted: int = 0


@dataclass
class RequestRecord:
    """One request's life, model-side: what was asked, what came back,
    when — the reconciliation input."""

    rid: int
    client: str
    tx_id: Any
    inputs: tuple
    kind: str                  # "interactive" | "rival"
    phase: str
    member: str                # gateway member it was submitted to
    deadline: Optional[int]
    submitted_at: int
    answered_at: Optional[int] = None
    outcome: Optional[str] = None
    shed_reason: Optional[str] = None
    rival_of: Optional[int] = None   # rid of the spend this one contests
    trace_id: Optional[int] = None   # tracing-enabled runs: the root trace


# ---------------------------------------------------------------------------
# chaos plane


@dataclass
class ChaosEvent:
    """One fault: `apply(sim)` fires when the offered stream crosses
    `at_fraction` (Disruption.kt's scheduling), `revert(sim)` when it
    crosses `revert_at_fraction` (None = never — one-shot actions).
    `member` names the victim by cluster index; the plane resolves it
    to a member name in the injected-reality log."""

    name: str
    kind: str                  # "kill" | "partition" | "slow" | custom
    at_fraction: float
    apply: Callable[["FleetSim"], None]
    revert_at_fraction: Optional[float] = None
    revert: Optional[Callable[["FleetSim"], None]] = None
    member: Optional[int] = None


def kill_restart(member: int, at: float, restart_at: float) -> ChaosEvent:
    """SIGKILL member `member` (by cluster index) at `at` of the
    stream; boot a replacement over the same fabric endpoint at
    `restart_at`. The replacement starts EMPTY and must be restored by
    the cluster's own state transfer; the endpoint's dedupe set
    survives, so frames redelivered across the outage are absorbed."""

    return ChaosEvent(
        f"kill-restart[{member}]", "kill", at,
        lambda sim: sim.kill_member(member),
        restart_at,
        lambda sim: sim.restart_member(member),
        member=member,
    )


def partition(member: int, at: float, heal_at: float) -> ChaosEvent:
    """Split member `member` away from the rest of the fleet (minority
    partition) at `at`; heal at `heal_at`. Queued frames redeliver on
    heal — nothing is lost, consensus just waited."""

    def apply(sim: "FleetSim") -> None:
        victim = sim.members[member].name
        rest = {n.name for n in sim.net.nodes if n.name != victim}
        sim.faults.partition({victim}, rest)
        sim._partitioned = victim

    def revert(sim: "FleetSim") -> None:
        sim.faults.heal()
        sim._partitioned = None

    return ChaosEvent(
        f"partition[{member}]", "partition", at, apply, heal_at, revert,
        member=member,
    )


def freeze(member: int, at: float, until: float) -> ChaosEvent:
    """Wedge member `member`'s serving loop (the SIGSTOP/stuck-flush
    analogue): the node stays reachable and consensus keeps running,
    but its pump heartbeat stops beating — the watchdog must flip its
    /healthz to unhealthy within one deadline and recover after."""

    def apply(sim: "FleetSim") -> None:
        sim.frozen.add(sim.members[member].name)

    def thaw(sim: "FleetSim") -> None:
        sim.frozen.discard(sim.members[member].name)

    return ChaosEvent(
        f"freeze[{member}]", "freeze", at, apply, until, thaw, member=member
    )


def slow_peer(
    member: int, at: float, until: float, delay_micros: int = 60_000
) -> ChaosEvent:
    """Add `delay_micros` of per-frame latency on every link touching
    member `member` between `at` and `until` of the stream — the
    straggler replica that lags consensus without ever dying."""

    return ChaosEvent(
        f"slow-peer[{member}]", "slow", at,
        lambda sim: sim.faults.slow_peer(
            sim.members[member].name, delay_micros
        ),
        until,
        lambda sim: sim.faults.slow_peer(sim.members[member].name, 0),
        member=member,
    )


def kill_verifier(worker: int, at: float, revive_at: Optional[float] = None) -> ChaosEvent:
    """Kill out-of-process verifier worker `worker` (by pool index) at
    `at` of the stream, mid-batch — its in-flight nonces must
    re-dispatch to a survivor via the lease/redispatch machinery
    (node/verifier.py round 9) and every verify future still resolve.
    `revive_at` optionally brings the worker back (re-attaching under
    the same name; stale answers from before the kill are rejected by
    the attempt binding). Requires FleetSim(verifier_pool=N>=2)."""

    return ChaosEvent(
        f"kill-verifier[{worker}]", "kill_verifier", at,
        lambda sim: sim.kill_verifier_worker(worker),
        revive_at,
        (lambda sim: sim.revive_verifier_worker(worker))
        if revive_at is not None else None,
        member=0,
    )


def device_fault(
    at: float, heal_at: Optional[float] = None, flushes: int = 2
) -> ChaosEvent:
    """Inject a device/XLA failure into the notary's verify dispatch
    for the next `flushes` dispatches (the DispatchFaultInjector seam,
    crypto/batch_verifier.py) — the degraded-mode guard must retry,
    fall back to the CPU reference bit-exact, fire
    `notary.degraded_mode`, and auto-recover once the injector drains.
    `heal_at` bounds the logged fault window (disarming any leftover
    failures) so the checker can reconcile the alert story against it.
    Batching flavour only."""

    return ChaosEvent(
        f"device-fault[x{flushes}]", "device_fault", at,
        lambda sim: sim.inject_device_fault(flushes),
        heal_at,
        (lambda sim: sim.device_injector.disarm())
        if heal_at is not None else None,
        member=0,
    )


def kill_notary_mid_flush(at: float, restart_at: float) -> ChaosEvent:
    """SIGKILL the (single-node batching) notary with a non-empty
    pending queue at `at`; boot a replacement over the same persistent
    state at `restart_at`. In-flight requests die with the process —
    the intent WAL (FleetSim(intent_wal=True)) replays them through
    the replacement's normal flush path, and the re-attached futures
    resolve every still-waiting client: zero admitted-then-lost."""

    return ChaosEvent(
        f"kill-notary", "kill_notary", at,
        lambda sim: sim.kill_notary(),
        restart_at,
        lambda sim: sim.restart_notary(),
        member=0,
    )


class ChaosPlane:
    """Applies scheduled faults as the stream crosses their fractions
    and records each one's simulated-time window — the injected-reality
    log `InvariantChecker.check_health_story` reconciles against."""

    def __init__(self, events: tuple[ChaosEvent, ...] = ()):
        self.events = sorted(events, key=lambda e: e.at_fraction)
        self.log: list[dict] = []
        self._applied: list[tuple[ChaosEvent, dict]] = []

    def step(self, sim: "FleetSim", fraction: float) -> None:
        while self.events and fraction >= self.events[0].at_fraction:
            ev = self.events.pop(0)
            ev.apply(sim)
            entry = {
                "name": ev.name,
                "kind": ev.kind,
                "target": (
                    sim.members[ev.member].name
                    if ev.member is not None else None
                ),
                "applied_at_micros": sim.now(),
                "applied_round": sim.round_no,
                "reverted_at_micros": None,
                "reverted_round": None,
                "revert_at_fraction": ev.revert_at_fraction,
            }
            self.log.append(entry)
            if ev.revert is not None:
                self._applied.append((ev, entry))
        for ev, entry in list(self._applied):
            revert_at = (
                ev.revert_at_fraction
                if ev.revert_at_fraction is not None else float("inf")
            )
            if fraction >= revert_at:
                ev.revert(sim)
                entry["reverted_at_micros"] = sim.now()
                entry["reverted_round"] = sim.round_no
                self._applied.remove((ev, entry))

    def finish(self, sim: "FleetSim") -> None:
        """Revert anything still live (drain must run on a healed
        fleet) and apply anything never reached."""
        self.step(sim, float("inf"))


# ---------------------------------------------------------------------------
# traffic sources


class TearOffSource:
    """Synthetic non-validating traffic: per-client coins as fabricated
    StateRefs, spent via minimal WireTransactions torn off for the
    notary (inputs + notary + meta revealed — everything a
    non-validating flavour checks). Cheap enough to mint thousands in
    CI; the uniqueness semantics are EXACTLY production's, because the
    notary never sees more than the tear-off either way."""

    def __init__(self, notary_party: Party, seed: int = 0):
        self.notary = notary_party
        self._counter = 0
        self._rng = random.Random(seed)

    def _wtx(self, ref: StateRef, nonce: bytes) -> WireTransaction:
        return WireTransaction(
            inputs=(ref,),
            outputs=(),
            commands=(),
            # the attachment hash is a pure nonce: two rivals spending
            # the same ref need DIFFERENT transaction ids
            attachments=(SecureHash.sha256(nonce),),
            notary=self.notary,
            time_window=None,
        )

    def spend(self, client: FleetClient):
        """(ftx, inputs, tx_id) consuming a fresh client-owned coin."""
        self._counter += 1
        ref = StateRef(
            SecureHash.sha256(
                f"fleet:{client.name}:coin:{client.submitted}".encode()
            ),
            0,
        )
        wtx = self._wtx(ref, b"spend:%d" % self._counter)
        return (
            wtx.build_filtered_transaction(lambda c: True),
            wtx.inputs,
            wtx.id,
        )

    def rival(self, inputs: tuple):
        """A DIFFERENT transaction claiming the same inputs — the
        injected double-spend."""
        self._counter += 1
        wtx = WireTransaction(
            inputs=tuple(inputs),
            outputs=(),
            commands=(),
            attachments=(SecureHash.sha256(b"rival:%d" % self._counter),),
            notary=self.notary,
            time_window=None,
        )
        return (
            wtx.build_filtered_transaction(lambda c: True),
            wtx.inputs,
            wtx.id,
        )


class CashSpendSource:
    """Real signed cash spends for the VALIDATING batching flavour —
    issues recorded at the notary, spends signed by the owner, rivals
    built against the same issue (tests/test_qos.py's `_rig`
    discipline), plus two-input cross-shard spends for the sharded
    commit plane."""

    def __init__(
        self,
        net: MockNetwork,
        notary_node,
        count: int,
        cross_shard_fraction: float = 0.0,
        seed: int = 0,
        extra_record_nodes=(),
        notary_party: Optional[Party] = None,
    ):
        from ..core.contracts import Amount, Issued
        from ..core.identity import PartyAndReference
        from ..core.transactions import TransactionBuilder
        from ..finance.cash import CASH_CONTRACT, CashIssue, CashState

        self._rng = random.Random(seed)
        self._notary_party = notary_party
        bank = net.create_node(
            "FleetBank", scheme_id=schemes.ECDSA_SECP256R1_SHA256
        )
        owner = net.create_node(
            "FleetOwner", scheme_id=schemes.ECDSA_SECP256R1_SHA256
        )
        self.bank, self.owner = bank, owner
        self.notary_node = notary_node
        token = Issued(PartyAndReference(bank.party, b"\x01"), "USD")
        self._token = token
        self._issues = []
        # a two-input (cross-shard) spend consumes TWO issues for one
        # request: provision the extras up front
        n_cross = int(count * cross_shard_fraction) // 2
        count = count + n_cross
        for i in range(count):
            ib = TransactionBuilder(self.notary_party)
            ib.add_output_state(
                CashState(Amount(100 + i, token), owner.party.owning_key),
                CASH_CONTRACT,
            )
            ib.add_command(CashIssue(i + 1), bank.party.owning_key)
            issue = bank.services.sign_initial_transaction(ib)
            notary_node.services.record_transactions([issue])
            owner.services.record_transactions([issue])
            for extra in extra_record_nodes:
                # distributed flavour: every member validates, so the
                # backchain must resolve on all of them
                extra.services.record_transactions([issue])
            self._issues.append(issue)
        self._next = 0
        self._cross_budget = n_cross

    @property
    def notary_party(self) -> Party:
        """The party transactions name as notary: the cluster service
        identity when one was passed (distributed flavour), the notary
        node's own otherwise."""
        return self._notary_party or self.notary_node.party

    def _spend_of(self, issues: list):
        from ..core.contracts import Amount
        from ..core.transactions import TransactionBuilder
        from ..finance.cash import CASH_CONTRACT, CashMove, CashState

        sb = TransactionBuilder(self.notary_party)
        total = 0
        for issue in issues:
            sb.add_input_state(
                self.owner.vault.state_and_ref(StateRef(issue.id, 0))
            )
            total += issue.wtx.outputs[0].data.amount.quantity
        sb.add_output_state(
            CashState(
                Amount(total, self._token), self.bank.party.owning_key
            ),
            CASH_CONTRACT,
            self.notary_party,
        )
        sb.add_command(CashMove(), self.owner.party.owning_key)
        return self.owner.services.sign_initial_transaction(sb)

    def spend(self, client: FleetClient):
        """(stx, inputs, tx_id): the next prebuilt issue spent — a
        two-input spend while the cross-shard budget lasts."""
        take = 2 if self._cross_budget > 0 and self._next + 1 < len(
            self._issues
        ) and self._rng.random() < 0.5 else 1
        if self._next + take > len(self._issues):
            raise RuntimeError(
                "CashSpendSource exhausted: size the fixture to the "
                "scenario's total interactive offer"
            )
        issues = self._issues[self._next:self._next + take]
        self._next += take
        if take == 2:
            self._cross_budget -= 1
        stx = self._spend_of(issues)
        return stx, stx.wtx.inputs, stx.id

    def rival(self, inputs: tuple):
        """A contract-VALID double spend: same inputs, value conserved,
        but paid back to the owner instead of the bank — a different
        transaction id claiming the same states, so only the
        uniqueness layer can reject it."""
        from ..core.contracts import Amount
        from ..core.transactions import TransactionBuilder
        from ..finance.cash import CASH_CONTRACT, CashMove, CashState

        sb = TransactionBuilder(self.notary_party)
        total = 0
        for ref in inputs:
            sar = self.owner.vault.state_and_ref(ref)
            sb.add_input_state(sar)
            total += sar.state.data.amount.quantity
        sb.add_output_state(
            CashState(
                Amount(total, self._token), self.owner.party.owning_key
            ),
            CASH_CONTRACT,
            self.notary_party,
        )
        sb.add_command(CashMove(), self.owner.party.owning_key)
        stx = self.owner.services.sign_initial_transaction(sb)
        return stx, stx.wtx.inputs, stx.id


class SyntheticSpendSource:
    """Unsigned, command-less spends over an always-pass contract —
    the ten-thousand-identity scale source. A command-less transaction
    has no required signers, so the validating flush accepts it with
    zero signatures; per-spend pure-python ECDSA (~10 ms each) would
    otherwise dominate a 10k-request soak wall a hundred to one. The
    uniqueness semantics under test — cross-shard routing, two-phase
    reserve→commit, double-spend rivalry — depend only on the input
    refs, which are as real as the cash source's."""

    def __init__(
        self,
        members,
        notary_party: Party,
        count: int,
        cross_shard_fraction: float = 0.0,
        seed: int = 0,
    ):
        from ..core.contracts import UniqueIdentifier, register_contract
        from ..core.contracts import StateAndRef
        from ..core.transactions import (
            SignedTransaction,
            TransactionBuilder,
        )
        from .flows import (
            DUMMY_LINEAR_CONTRACT,
            DummyLinearState,
            _DummyLinearContract,
        )

        register_contract(DUMMY_LINEAR_CONTRACT, _DummyLinearContract())
        self._rng = random.Random(seed)
        self.notary_party = notary_party
        self._contract = DUMMY_LINEAR_CONTRACT
        self._state_cls = DummyLinearState
        self._uid_cls = UniqueIdentifier
        self._sar_cls = StateAndRef
        self._builder_cls = TransactionBuilder
        self._stx_cls = SignedTransaction
        # one well-known key as every synthetic state's owner: states
        # carry participants but nothing signs, and nothing needs to
        owner_kp = schemes.generate_keypair(
            schemes.ECDSA_SECP256R1_SHA256, seed=seed * 31 + 5
        )
        self._owner_key = owner_kp.public
        n_cross = int(count * cross_shard_fraction) // 2
        total = count + n_cross
        self._issues = []
        batch = []
        for i in range(total):
            b = TransactionBuilder(notary_party)
            b.add_output_state(
                DummyLinearState(
                    UniqueIdentifier(seed.to_bytes(8, "big")
                                     + i.to_bytes(8, "big")),
                    f"issue-{i}",
                    self._owner_key,
                ),
                DUMMY_LINEAR_CONTRACT,
            )
            stx = SignedTransaction(b.to_wire_transaction(), ())
            batch.append(stx)
            self._issues.append(stx)
        for m in members:
            m.services.record_transactions(batch)
        # rival() looks issues up by their output ref; build the index
        # ONCE — at 10k+ issues a per-call rebuild would cost millions
        # of dict inserts across a soak's injected double-spends
        self._by_ref = {
            StateRef(issue.id, 0): issue for issue in self._issues
        }
        self._next = 0
        self._cross_budget = n_cross
        self._seq = 0

    def _spend_of(self, issues, info: str):
        b = self._builder_cls(self.notary_party)
        for issue in issues:
            b.add_input_state(
                self._sar_cls(
                    issue.wtx.outputs[0], StateRef(issue.id, 0)
                )
            )
        self._seq += 1
        b.add_output_state(
            self._state_cls(
                self._uid_cls(b"synth-out" + self._seq.to_bytes(7, "big")),
                info,
                self._owner_key,
            ),
            self._contract,
        )
        return self._stx_cls(b.to_wire_transaction(), ())

    def spend(self, client: FleetClient):
        take = 2 if self._cross_budget > 0 and self._next + 1 < len(
            self._issues
        ) and self._rng.random() < 0.5 else 1
        if self._next + take > len(self._issues):
            raise RuntimeError(
                "SyntheticSpendSource exhausted: size the fixture to "
                "the scenario's total interactive offer"
            )
        issues = self._issues[self._next:self._next + take]
        self._next += take
        if take == 2:
            self._cross_budget -= 1
        stx = self._spend_of(issues, f"spend-by-{client.name}")
        return stx, stx.wtx.inputs, stx.id

    def rival(self, inputs: tuple):
        """Contract-valid double spend: the same input refs, a
        different output — a different id claiming the same states."""
        issues = [self._by_ref[ref] for ref in inputs]
        stx = self._spend_of(issues, "rival")
        return stx, stx.wtx.inputs, stx.id


# ---------------------------------------------------------------------------
# the simulator


@dataclass
class FleetReport:
    """Everything the invariant checker (and bench) reads."""

    flavour: str
    scenario: FleetScenario
    records: list
    timeline: list
    chaos_log: list
    ledgers: dict            # member name -> {StateRef: tx_id}
    members: list            # member names, cluster order
    monitors: dict           # member name -> HealthMonitor
    qos: Optional[qoslib.NotaryQos]
    started_micros: int
    finished_micros: int
    bulk_offered: int = 0
    bulk_shed_brownout: int = 0
    bulk_served: int = 0
    distinct_clients: int = 0
    # round-9 fault plane: intent-WAL + verifier-pool reconciliation
    intent_wal: bool = False
    intent_unresolved: int = 0
    intent_replayed: int = 0
    verify_offered: int = 0
    verify_resolved: int = 0
    verify_failed: int = 0
    verify_redispatched: int = 0
    verify_workers_lost: int = 0
    device_faults: int = 0
    degraded_flushes: int = 0
    # round-15 device plane: the end-of-run GET /device-shaped
    # snapshot from the member plane (None when the fault arc never
    # built one) — the telemetry side of the device_fault story
    device_telemetry: Any = None
    # round-17 wire plane: the end-of-run GET /wire-shaped snapshot
    # from the notary's fabric seam (None when the fault arc never
    # built one) — per-link accounting under the same chaos schedule
    wire_telemetry: Any = None
    # round-11 tracing plane: per-member tracers, the cross-node
    # assembler and the incident recorder (None when not enabled)
    tracers: dict = field(default_factory=dict)
    cluster_traces: Any = None
    incidents: Any = None
    # round-13 provenance plane: the shared TxStory lifecycle ledger
    # (None when FleetSim(txstory=True) was not requested) — the
    # lifecycle-ledger reconciliation's input
    txstory: Any = None
    # round-12 distributed uniqueness: the ownership map, the shared
    # decision log (true serialisation order — the serial-replay
    # reference), and end-of-run reservation/orphan depths per member
    # (the reservation-ledger reconciliation inputs)
    cluster_shards: int = 0
    shard_map: dict = field(default_factory=dict)
    xshard_decisions: list = field(default_factory=list)
    reservations_live: dict = field(default_factory=dict)
    xshard_orphans: dict = field(default_factory=dict)

    @property
    def sim_seconds(self) -> float:
        return (self.finished_micros - self.started_micros) / 1e6

    def outcomes(self, kind: Optional[str] = None) -> dict:
        out: dict[str, int] = {}
        for r in self.records:
            if kind is not None and r.kind != kind:
                continue
            out[r.outcome or "?"] = out.get(r.outcome or "?", 0) + 1
        return out


class FleetSim:
    """Scenario engine: one soak = `FleetSim(scenario, flavour,
    chaos=...).run()` -> FleetReport. See the module docstring."""

    def __init__(
        self,
        scenario: FleetScenario,
        flavour: str = "batching",
        chaos: tuple[ChaosEvent, ...] = (),
        cluster_size: Optional[int] = None,
        notary_shards: int = 1,
        qos_policy: Optional[qoslib.QosPolicy] = None,
        heartbeat_deadline_rounds: int = 3,
        lag_alert_threshold: int = 8,
        verifier_pool: int = 0,
        intent_wal: bool = False,
        txstory: bool = False,
        tracing: bool = False,
        incident_dir: Optional[str] = None,
        cluster_shards: int = 8,
        batch_verifier=None,
        spend_source: str = "cash",
        statestore: str = "sqlite",
        statestore_dir: Optional[str] = None,
    ):
        """`verifier_pool` (batching only): attach N out-of-process
        VerifierWorkers on the fabric and an
        OutOfProcessTransactionVerifierService on the notary — one
        spend per round additionally round-trips the pool, so
        kill_verifier() chaos drives the lease/redispatch machinery at
        fleet shape. `intent_wal` (batching only): a NotaryIntentJournal
        under the notary's intake, which is what lets
        kill_notary_mid_flush() complete with ZERO lost admitted
        requests and tightens the checker's loss bound to an equality
        (check_exact_accounting).

        `tracing` (cluster flavours): every member gets its OWN
        enabled Tracer, each submitted request opens a root span whose
        context rides the consensus protocol, and `cluster_traces`
        assembles any request's cross-node tree over a simulated
        /traces pull. `incident_dir`: an IncidentRecorder under it —
        firing member alerts snapshot forensics bundles (assembled
        cross-node traces included when tracing is on) and failed
        reconciliations cite a bundle id."""
        if flavour not in FLAVOURS:
            raise ValueError(f"unknown fleet flavour {flavour!r}")
        if verifier_pool and flavour != "batching":
            raise ValueError("verifier_pool is a batching-flavour seam")
        if intent_wal and flavour not in ("batching", "distributed"):
            raise ValueError(
                "intent_wal needs a batching-notary intake "
                "(batching or distributed flavour)"
            )
        if txstory and flavour != "batching":
            raise ValueError(
                "txstory is a batching-flavour seam (the lifecycle "
                "ledger reconciliation rides the batching intake)"
            )
        # round 19: the distributed flavour can swap its members'
        # committed-state registry from the sqlite tables to the
        # commit-log store (node/statestore.py) — per-member store
        # DIRECTORIES play the role the per-member NodeDatabase plays
        # for sqlite (durable state surviving kill/restart), so
        # restart_member() becomes a real boot replay over segments +
        # snapshot and a joiner can install a member's snapshot file
        # set
        if statestore not in ("sqlite", "commitlog"):
            raise ValueError(
                f"unknown statestore backend {statestore!r} "
                "(sqlite | commitlog)"
            )
        if statestore == "commitlog":
            if flavour != "distributed":
                raise ValueError(
                    "statestore='commitlog' is a distributed-flavour "
                    "seam"
                )
            if not statestore_dir:
                raise ValueError(
                    "statestore='commitlog' needs statestore_dir: the "
                    "per-member store directories must survive "
                    "kill/restart"
                )
        self.statestore = statestore
        self._statestore_dir = statestore_dir
        self._member_stores: dict = {}
        self.scenario = scenario
        self.flavour = flavour
        self.chaos = ChaosPlane(chaos)
        self.faults = FabricFaults(seed=scenario.seed)
        self.net = MockNetwork(
            seed=scenario.seed, faults=self.faults,
            batch_verifier=batch_verifier,
        )
        self.round_no = 0
        self._partitioned: Optional[str] = None
        self._rng = random.Random(scenario.seed ^ 0x5EED)
        scheme = schemes.ECDSA_SECP256R1_SHA256

        # -- per-member tracing (cluster-wide trace assembly) ---------------
        self._tracing = bool(tracing)
        self.tracers: dict[str, tracelib.Tracer] = {}
        self._spans: dict[int, Any] = {}   # rid -> open root span

        def tracer_for(name: str) -> tracelib.Tracer:
            # memoized: a kill/restart rebuild re-attaches the SAME
            # member tracer (the sim's stand-in for a node's recorder
            # surviving in the assembly story). Recorders are sized to
            # the soak: each consensus phase span completes as its own
            # recorder entry, and a 64-deep recent ring would evict a
            # follower's µs-scale spans long before the incident
            # bundle pulls them.
            t = self.tracers.get(name)
            if t is None:
                t = tracelib.Tracer(
                    enabled=True,
                    recorder=tracelib.FlightRecorder(
                        keep_recent=4096, keep_slowest=64
                    ),
                )
                self.tracers[name] = t
            return t

        self._tracer_for = tracer_for

        # -- the cluster ----------------------------------------------------
        if flavour == "batching":
            notary = self.net.create_notary(
                "FleetNotary", batching=True, shards=notary_shards
            )
            self.members = [notary]
            self.service_party = notary.party
            svc = notary.services.notary_service
            self.qos = qoslib.NotaryQos(
                qos_policy or qoslib.QosPolicy(), clock=self.net.clock
            )
            if notary_shards > 1:
                self.qos.ensure_shards(notary_shards)
            svc.qos = self.qos
            # THE capacity model: the sim's round is the pump tick.
            # MockNetwork.run()'s tick-until-quiescent loop would hand
            # the notary unbounded flushes per simulated instant —
            # infinite hardware, no backlog, no overload, nothing for
            # the QoS plane to do. Pull the tick out of the run loop
            # and drive it ONCE per round instead (the loadtest.md
            # overload-scenario discipline): served depth per round is
            # then the adaptive controller's batch, and sustained
            # over-offer builds the real backlog brownout walks on.
            notary.ticks = [t for t in notary.ticks if t != svc.tick]
            self._drive_tick = svc.tick
        elif flavour == "raft":
            self.service_party, self.members = (
                self.net.create_raft_notary_cluster(
                    cluster_size or 3, scheme_id=scheme,
                    tracer_factory=self._tracer_for if tracing else None,
                )
            )
            self.qos = None
            self._drive_tick = None
            self.net.elect(self.members)
        elif flavour == "bft":
            self.service_party, self.members = (
                self.net.create_bft_notary_cluster(
                    cluster_size or 4, scheme_id=scheme,
                    tracer_factory=self._tracer_for if tracing else None,
                )
            )
            self.qos = None
            self._drive_tick = None
        else:
            # distributed sharded uniqueness (round 12): N members,
            # each a batching notary over a
            # DistributedUniquenessProvider — the state-ref space
            # partitioned ACROSS the members, cross-member commits
            # riding the fabric two-phase reserve→commit under the
            # same FabricFaults plane the chaos events drive. Durable
            # state (store, coordinator WAL, reservation journal,
            # intent WAL) lives on a per-member NodeDatabase that
            # SURVIVES kill/restart, exactly like a real process's
            # sqlite file.
            self.cluster_shards = max(1, int(cluster_shards))
            self.xshard_decisions: list = []
            self._xshard_dbs: dict = {}
            self._xshard_providers: dict = {}
            self._member_intents: dict = {}
            n = cluster_size or 3
            R = scenario.round_micros
            from ..node.distributed_uniqueness import XShardPolicy

            self._xshard_policy = XShardPolicy(
                timeout_micros=4 * R,
                backoff_base_micros=max(R // 4, 1),
                backoff_cap_micros=2 * R,
                reservation_ttl_micros=6 * R,
            )
            member_names = [f"DistNotary-{i}" for i in range(n)]
            # one shared service identity, the raft-cluster discipline:
            # every member holds the cluster key and answers (and
            # signs) for the cluster party the clients name as notary
            shared_kp = schemes.generate_keypair(
                scheme, seed=self._rng.getrandbits(256)
            )
            self.service_party = Party("DistNotary", shared_kp.public)
            self.members = []
            for mname in member_names:
                node = self.net.create_node(mname, scheme_id=scheme)
                node.services.key_management.register_keypair(shared_kp)
                from ..node.persistence import NodeDatabase

                self._xshard_dbs[mname] = NodeDatabase(":memory:")
                node.rebuild_cluster_member = (
                    lambda _node=node, _names=member_names:
                    self._build_distributed_member(
                        _node, _names, wal=intent_wal
                    )
                )
                node.rebuild_cluster_member()
                self.members.append(node)
            self.qos = None
            self._drive_tick = None
        self.alive = {m.name: True for m in self.members}
        self.frozen: set[str] = set()   # wedged-pump members (freeze())

        # -- client identities ----------------------------------------------
        # a small keypair pool shared across many NAMED identities:
        # non-validating notaries record the requester by identity, and
        # admission gates key on the name, so the pool keeps a
        # thousand-client fleet's keygen cost negligible
        pool = [
            schemes.generate_keypair(scheme, seed=scenario.seed * 7919 + k)
            for k in range(max(1, scenario.key_pool))
        ]
        self.clients = [
            FleetClient(
                f"fleet-c{k:04d}", Party(f"fleet-c{k:04d}", pool[k % len(pool)].public)
            )
            for k in range(scenario.clients)
        ]

        # -- traffic source -------------------------------------------------
        if spend_source == "synthetic" and flavour == "distributed":
            # the 10k-identity scale source: command-less unsigned
            # spends (no per-spend ECDSA) with fully real input refs
            self.source = SyntheticSpendSource(
                self.members,
                self.service_party,
                self._interactive_budget(),
                cross_shard_fraction=max(
                    scenario.mix_of(p).cross_shard_fraction
                    for p in scenario.phases
                ),
                seed=scenario.seed,
            )
        elif flavour in ("batching", "distributed"):
            self.source = CashSpendSource(
                self.net,
                self.members[0],
                self._interactive_budget(),
                cross_shard_fraction=max(
                    scenario.mix_of(p).cross_shard_fraction
                    for p in scenario.phases
                ),
                seed=scenario.seed,
                # every distributed member validates: the backchain
                # must resolve wherever the gateway round-robin lands,
                # and transactions name the shared cluster identity
                extra_record_nodes=(
                    self.members[1:] if flavour == "distributed" else ()
                ),
                notary_party=(
                    self.service_party if flavour == "distributed"
                    else None
                ),
            )
        else:
            self.source = TearOffSource(self.service_party, scenario.seed)

        # -- health plane ---------------------------------------------------
        hb_deadline = heartbeat_deadline_rounds * scenario.round_micros
        policy = HealthPolicy(
            heartbeat_deadline_micros=hb_deadline,
            livelock_deadline_micros=4 * hb_deadline,
            alert_for_micros=scenario.round_micros,
            alert_clear_for_micros=scenario.round_micros,
        )
        self.monitors: dict[str, HealthMonitor] = {}
        self._beats = {}
        for m in self.members:
            mon = HealthMonitor(
                clock=self.net.clock, policy=policy,
                # with tracing on, alert evidence cites the member's
                # OWN slowest traces — what the incident bundle's
                # cross-node assembly starts from
                tracer=self.tracers.get(m.name),
            )
            self.monitors[m.name] = mon
            self._beats[m.name] = mon.heartbeat(f"{m.name}.pump")
            if self.flavour in ("raft", "bft"):
                mon.add_rule(
                    AlertRule(
                        "consensus.lag",
                        check=(
                            lambda now, _name=m.name: self._lag_check(
                                _name, lag_alert_threshold
                            )
                        ),
                        for_micros=scenario.round_micros,
                        clear_for_micros=scenario.round_micros,
                        # evidence: traces that actually carry this
                        # flavour's consensus phase spans
                        trace_filter=self.flavour,
                    )
                )
        if flavour == "distributed":
            # per-member serving heartbeat + the distributed-plane
            # rules (shard.unreachable, reservation.orphaned) — so a
            # partitioned owner and an orphaned reservation show in
            # the same alert story the checker reconciles
            for m in self.members:
                m.services.notary_service.attach_health(
                    self.monitors[m.name]
                )
                self._xshard_providers[m.name].attach_health(
                    self.monitors[m.name]
                )
        rollup_home = self.members[0].name
        self.cluster = ClusterHealth(
            rollup_home,
            local_summary=lambda: self.monitors[rollup_home].snapshot(
                summary=True
            ),
            peers_fn=lambda: {
                m.name: f"fleet://{m.name}/health?summary=1"
                for m in self.members
            },
            fetch=self._fetch_peer_summary,
            clock_fn=self.net.clock.now_micros,
            cache_ttl_micros=0,      # every sample is a fresh pull
        )

        # -- cross-node trace assembly + incident forensics -----------------
        self.cluster_traces = None
        if self._tracing:
            home = self.members[0].name
            self.cluster_traces = tracelib.ClusterTraces(
                home,
                self._tracer_for(home),
                peers_fn=lambda: {
                    m.name: f"fleet://{m.name}" for m in self.members
                },
                fetch=self._fetch_peer_traces,
            )
        self.incidents = None
        if incident_dir is not None:
            self.incidents = IncidentRecorder(
                incident_dir,
                clock_fn=self.net.clock.now_micros,
                assemble=(
                    self.cluster_traces.assemble
                    if self.cluster_traces is not None else None
                ),
                chaos_log=lambda: self.chaos.log,
            )
            for m in self.members:
                self.monitors[m.name].attach_incidents(
                    self.incidents, node=m.name
                )

        # -- round-9 fault plane (batching seams) ---------------------------
        self._fault_arc = bool(verifier_pool or intent_wal) or any(
            e.kind in ("kill_verifier", "device_fault", "kill_notary")
            for e in self.chaos.events
        )
        self.device_injector = None
        self.device_plane = None
        self.wire_plane = None
        self.intent_journal = None
        self.verify_pool = None
        self._verify_workers: list = []
        self._verify_worker_alive: list[bool] = []
        self.verify_futures: list = []
        self._notary_down = False
        self._degraded_flushes_base = 0   # carried across notary restarts
        if flavour == "batching" and self._fault_arc:
            notary = self.members[0]
            svc = notary.services.notary_service
            # device-fault seam: the injector IS the installed hub
            # verifier — disarmed it is a passthrough, armed it raises
            # exactly where a real XLA failure would
            from ..crypto.batch_verifier import DispatchFaultInjector

            self.device_injector = DispatchFaultInjector(
                notary.services.batch_verifier
            )
            notary.services._batch_verifier = self.device_injector
            if intent_wal:
                from ..node.persistence import (
                    NodeDatabase,
                    NotaryIntentJournal,
                )

                self.intent_journal = NotaryIntentJournal(
                    NodeDatabase(":memory:")
                )
                svc.attach_intent_journal(self.intent_journal)
            # flush heartbeat + the degraded-mode alert land on the
            # member's monitor, so kill/device faults show in the same
            # healthz/alert story the checker reconciles
            svc.attach_health(self.monitors[notary.name])
            # device-telemetry plane (round 15): the fleet reads the
            # plane the production node serves at GET /device, so the
            # device_fault chaos events assert the TELEMETRY story too
            # — device.fallback_active fires with device evidence
            # while the degraded flush serves off the CPU reference,
            # and resolves when the recovery probe re-arms the chip.
            # Lambdas read THROUGH to the current notary service: a
            # kill/restart replaces the service object under the same
            # plane.
            from ..utils.device_telemetry import (
                DevicePlane,
                DevicePolicy,
            )

            self.device_plane = DevicePlane(
                clock=self.net.clock,
                policy=DevicePolicy(
                    sample_gap_micros=0, live_buffer_census=False
                ),
                install_default_accounting=False,
            )
            self.device_plane.attach_queues(
                [lambda: self._notary_service().backlog()], [None]
            )
            self.device_plane.watch_fallback(
                lambda: self._notary_service().degraded,
                lambda: self._notary_service().degraded_evidence,
            )
            self.monitors[notary.name].watch_device(self.device_plane)
            # wire plane (round 17): the same accounting the node
            # serves at GET /wire, attached to the notary's in-memory
            # fabric seam — the chaos arcs exercise frame/dedupe/
            # backlog bookkeeping under faults, and the wire alerts
            # ride the member's monitor
            from ..utils.wire_telemetry import WirePlane, WirePolicy

            self.wire_plane = WirePlane(
                clock=self.net.clock,
                policy=WirePolicy(sample_gap_micros=0),
            )
            self.wire_plane.attach_fabric(notary.messaging)
            self.monitors[notary.name].watch_wire(self.wire_plane)
            if verifier_pool:
                from ..crypto.batch_verifier import CpuBatchVerifier
                from ..node.verifier import (
                    OutOfProcessTransactionVerifierService,
                    RedispatchPolicy,
                    VerifierWorker,
                )

                R = scenario.round_micros
                self.verify_pool = OutOfProcessTransactionVerifierService(
                    notary.messaging,
                    clock=self.net.clock,
                    policy=RedispatchPolicy(
                        lease_micros=3 * R,
                        request_timeout_micros=60 * R,
                        backoff_base_micros=max(R // 2, 1),
                        backoff_cap_micros=4 * R,
                        max_attempts=6,
                    ),
                )
                self.verify_pool.watch_health(self.monitors[notary.name])
                for k in range(verifier_pool):
                    ep = self.net.fabric.endpoint(f"fleet-verifier-w{k}")
                    self._verify_workers.append(
                        VerifierWorker(
                            ep,
                            notary.name,
                            batch_verifier=CpuBatchVerifier(),
                            clock=self.net.clock,
                            heartbeat_micros=R,
                        )
                    )
                    self._verify_worker_alive.append(True)
                self.net.run()   # deliver the WorkerReady attaches

        # -- round-13 provenance plane (lifecycle ledger) -------------------
        self.txstory_plane = None
        if txstory:
            from ..utils.txstory import TxStory

            notary = self.members[0]
            svc = notary.services.notary_service
            # the ledger is an OBSERVER that survives kill/restart
            # (like the monitors): sized so a whole soak's stories
            # stay resident for the end-of-run reconciliation
            cap = max(4096, 2 * scenario.total_offered())
            self.txstory_plane = TxStory(
                metrics=svc.metrics,
                clock=self.net.clock,
                max_open=cap,
                keep_done=cap,
            )
            svc.attach_txstory(self.txstory_plane)
            if self.qos is not None:
                self.qos.txstory = self.txstory_plane
            if self.verify_pool is not None:
                self.verify_pool.txstory = self.txstory_plane

        # -- bookkeeping ----------------------------------------------------
        self.records: list[RequestRecord] = []
        self.timeline: list[dict] = []
        self._live: list[list] = []   # [generator, parked _WaitFuture, record]
        self._next_rid = 0
        self._next_uid = 1
        # interactive traffic round-robins the WHOLE fleet: a stream at
        # least `clients` long touches every identity exactly once per
        # lap (rivals draw from a shifted cursor so they never skew it)
        self._client_cursor = 0
        self.bulk_offered = 0
        self.bulk_served = 0

    # -- plumbing ------------------------------------------------------------

    def _build_distributed_member(self, node, member_names, wal=False):
        """(Re)build one distributed-uniqueness member over its
        surviving durable state: a fresh DistributedUniquenessProvider
        + BatchingNotaryService on the member's own NodeDatabase (the
        store, coordinator WAL, reservation journal and intent WAL all
        live there, like a real process's sqlite file). The kill/
        restart seam: recovery re-drives commit-marked intents,
        presumed-aborts the rest, reloads journaled reservations, and
        replays the intent WAL with futures re-attached to
        still-waiting clients by transaction id."""
        from ..node.distributed_uniqueness import (
            DistributedUniquenessProvider,
        )
        from ..node.notary import BatchingNotaryService
        from ..node.persistence import (
            NotaryIntentJournal,
            ShardedPersistentUniquenessProvider,
            XShardCoordinatorJournal,
            XShardReservationJournal,
        )

        db = self._xshard_dbs[node.name]
        old = self._xshard_providers.get(node.name)
        if old is not None:
            old.stop()
        if self.statestore == "commitlog":
            # close the dead incarnation's handles, then reopen the
            # SAME directory: recovery replays manifest + snapshot +
            # segment tail — the boot-replay path, under fleet chaos.
            # Tiny segments so a soak actually seals, compacts and
            # replays multi-segment logs; fsync off matches the
            # simulated-time discipline (writes survive like the
            # per-member NodeDatabase does).
            import os as _os

            from ..node.statestore import (
                ShardedCommitLogUniquenessProvider,
            )

            old_store = self._member_stores.pop(node.name, None)
            if old_store is not None:
                old_store.close()
            store = ShardedCommitLogUniquenessProvider(
                _os.path.join(self._statestore_dir, node.name),
                self.cluster_shards,
                segment_max_records=16,
                compact_min_segments=4,
                fsync=False,
            )
            self._member_stores[node.name] = store
        else:
            store = ShardedPersistentUniquenessProvider(
                db, self.cluster_shards
            )
        provider = DistributedUniquenessProvider(
            node.name,
            member_names,
            node.messaging,
            self.net.clock,
            n_partitions=self.cluster_shards,
            store=store,
            journal=XShardCoordinatorJournal(db),
            reservations=XShardReservationJournal(db),
            policy=self._xshard_policy,
            seed=(self.scenario.seed << 8) ^ (hash(node.name) & 0xFFFF),
            decision_log=self.xshard_decisions,
            tracer=self._tracer_for(node.name) if self._tracing else None,
        )
        self._xshard_providers[node.name] = provider
        journal = self._member_intents.get(node.name)
        if wal and journal is None:
            journal = self._member_intents[node.name] = NotaryIntentJournal(
                db
            )
        old_svc = getattr(node.services, "notary_service", None)
        svc = BatchingNotaryService(
            node.services, provider, intent_journal=journal,
            service_identity=self.service_party,
        )
        node.services.notary_service = svc
        node.ticks = [
            t for t in node.ticks
            if getattr(t, "__self__", None) not in (old_svc, old)
        ]
        node.ticks.append(svc.tick)
        node.ticks.append(provider.tick)
        monitor = getattr(self, "monitors", {}).get(node.name)
        if monitor is not None:
            svc.attach_health(monitor)
            provider.attach_health(monitor)
        provider.recover()
        if journal is not None:
            replayed = svc.replay_intents()
            by_tx = {tx_id: fut for _seq, tx_id, fut in replayed}
            for entry in getattr(self, "_live", []):
                gen, _wait, rec = entry
                if gen is None and rec.outcome is None:
                    fut = by_tx.get(rec.tx_id)
                    if fut is not None:
                        entry[1] = fut
        return svc

    def now(self) -> int:
        return self.net.clock.now_micros()

    def _interactive_budget(self) -> int:
        """Upper bound of interactive spends the scenario can ask for
        (sizes the batching cash fixture; rivals reuse rival-builders,
        not fresh issues)."""
        s = self.scenario
        total = 0
        for p in s.phases:
            mix = s.mix_of(p)
            total += p.rounds * max(
                0, p.offered_per_round - int(
                    p.offered_per_round * mix.bulk_fraction
                )
            )
        return total + 2

    def _fetch_peer_summary(self, url: str) -> dict:
        """The /cluster transport, simulated: a down or partitioned-
        away peer is unreachable exactly as HTTP would be."""
        name = url.split("//", 1)[1].split("/", 1)[0]
        home = self.cluster.self_name
        if not self.alive.get(name, False):
            raise ConnectionError(f"{name} is down")
        if self.faults.blocked(home, name) or self.faults.blocked(name, home):
            raise ConnectionError(f"{name} unreachable from {home}")
        return self.monitors[name].snapshot(summary=True)

    def _fetch_peer_traces(self, url: str) -> dict:
        """The /cluster/trace transport, simulated: the peer's filtered
        GET /traces payload, with the same reachability rules as the
        health pull."""
        from urllib.parse import parse_qs, urlparse

        parsed = urlparse(url)
        name = parsed.netloc
        home = self.cluster_traces.self_name
        if not self.alive.get(name, False):
            raise ConnectionError(f"{name} is down")
        if self.faults.blocked(home, name) or self.faults.blocked(name, home):
            raise ConnectionError(f"{name} unreachable from {home}")
        tid = tracelib.parse_trace_id(
            parse_qs(parsed.query).get("trace_id", [None])[0]
        )
        return self._tracer_for(name).export(trace_id=tid)

    def _lag_check(self, name: str, threshold: int):
        lag = self.consensus_lag(name)
        return lag is not None and lag > threshold, {"lag": lag}

    def consensus_lag(self, name: str) -> Optional[int]:
        """How far member `name`'s applied state trails the fleet's
        front — entries for raft, executed sequence numbers for BFT."""
        node = next(m for m in self.members if m.name == name)
        if self.flavour == "raft":
            front = max(
                m.raft.commit_index
                for m in self.members
                if self.alive[m.name]
            )
            return front - node.raft.last_applied
        if self.flavour == "bft":
            front = max(
                m.bft.exec_seq for m in self.members if self.alive[m.name]
            )
            return front - node.bft.exec_seq
        return None

    # -- chaos actions (called by ChaosEvents) --------------------------------

    def kill_member(self, idx: int) -> None:
        if self.flavour == "batching":
            raise ValueError(
                "kill_restart needs a cluster flavour (raft/bft/"
                "distributed): the batching sim is single-node — use "
                "freeze() for the wedged-pump fault"
            )
        node = self.members[idx]
        self.faults.kill(node.name)
        node.messaging.running = False
        if self.flavour == "distributed":
            # process death mid-serving: queued-but-unflushed requests
            # die with the heap, in-flight coordinator state machines
            # die (their WAL survives), unflushed intent-WAL
            # resolutions die (those intents replay + dedupe), and the
            # member stops ticking — the durable NodeDatabase is the
            # only thing that survives, like a real sqlite file
            svc = node.services.notary_service
            svc._pending.clear()
            journal = self._member_intents.get(node.name)
            if journal is not None:
                journal.lose_unflushed_resolutions()
            provider = self._xshard_providers[node.name]
            provider.stop()
            node.ticks = [
                t for t in node.ticks
                if getattr(t, "__self__", None) not in (svc, provider)
            ]
        if getattr(node, "raft", None) is not None:
            node.raft.stop()
        if getattr(node, "bft", None) is not None:
            node.bft.stop()
        self.alive[node.name] = False

    def restart_member(self, idx: int) -> None:
        """Boot a replacement state machine over the same endpoint: the
        consensus layer restores it (AppendEntries/InstallSnapshot for
        raft, checkpoint catch-up for BFT, WAL recovery + intent
        replay for the distributed uniqueness plane); the endpoint's
        dedupe set absorbs frames redelivered across the outage."""
        node = self.members[idx]
        rebuild = getattr(node, "rebuild_cluster_member", None)
        if rebuild is None:
            raise ValueError(
                f"{node.name} is not a cluster member — only cluster "
                f"members carry a rebuild seam"
            )
        old = getattr(node, "raft", None) or getattr(node, "bft", None)
        if old is not None:
            node.ticks = [
                t for t in node.ticks
                if getattr(t, "__self__", None) is not old
            ]
        # revive the endpoint BEFORE recovery: the rebuild's WAL
        # re-drives send protocol frames that must queue for delivery
        node.messaging.running = True
        self.faults.revive(node.name)
        rebuild()
        self.alive[node.name] = True
        # a restarted process reports live from its first pump
        self._beats[node.name].beat()

    # -- round-9 fault-plane actions ------------------------------------------

    def _notary_service(self):
        """The CURRENT batching notary service — read through on every
        call, so the device plane's fallback/backlog lambdas survive a
        kill_notary/restart_notary swap of the service object."""
        return self.members[0].services.notary_service

    def _worker_name(self, idx: int) -> str:
        return f"fleet-verifier-w{idx}"

    def kill_verifier_worker(self, idx: int) -> None:
        """SIGKILL one pool worker mid-batch: its endpoint stops
        pumping and the fault plane blackholes it — the node-side lease
        expires, the worker detaches, and its in-flight nonces
        re-dispatch to a survivor."""
        if self.verify_pool is None:
            raise ValueError(
                "kill_verifier needs FleetSim(verifier_pool=N>=2)"
            )
        name = self._worker_name(idx)
        self.faults.kill(name)
        self.net.fabric.endpoint(name).running = False
        self._verify_worker_alive[idx] = False

    def revive_verifier_worker(self, idx: int) -> None:
        """Bring a killed worker back under the SAME name: revive the
        endpoint and re-announce WorkerReady. Answers it computed
        before the kill were re-dispatched away in the meantime; the
        attempt binding rejects them as a stale incarnation."""
        name = self._worker_name(idx)
        self.faults.revive(name)
        self.net.fabric.endpoint(name).running = True
        self._verify_worker_alive[idx] = True
        self._verify_workers[idx]._send_ready()

    def inject_device_fault(self, flushes: int = 2) -> None:
        """Arm the dispatch-seam injector: the next `flushes` verify
        dispatches raise a DeviceFaultError; after that the device
        path serves again (which is what the notary's recovery probe
        re-arms on)."""
        if self.device_injector is None:
            raise ValueError(
                "device_fault needs the batching flavour (the injector "
                "wraps the notary hub's batch verifier)"
            )
        self.device_injector.arm(flushes)

    def kill_notary(self) -> None:
        """Process death for the single-node batching notary, mid
        serving: every queued-but-unflushed request vanishes with the
        heap, the journal's unflushed resolution buffer is lost (those
        intents will REPLAY and dedupe), and the pump freezes — the
        watchdog flips healthz exactly as a real crash would."""
        if self.flavour == "distributed":
            # the distributed fleet's "kill the notary mid-flush" is a
            # full member kill of the round-robin home member — the
            # coordinator most in-flight cross-shard reserves ran on
            self.kill_member(0)
            return
        if self.flavour != "batching":
            raise ValueError("kill_notary is the batching-flavour crash")
        node = self.members[0]
        svc = node.services.notary_service
        if getattr(svc, "_shards", None) is not None:
            for shard in svc._shards:
                with shard.cond:
                    shard.pending.clear()
        else:
            svc._pending.clear()
        if self.intent_journal is not None:
            self.intent_journal.lose_unflushed_resolutions()
        self.frozen.add(node.name)
        self._notary_down = True

    def restart_notary(self) -> None:
        """Boot a replacement notary over the same durable state (the
        uniqueness provider and intent WAL survive the process), replay
        unresolved intents through its normal flush path, and re-attach
        every still-waiting client future to its replayed twin by
        transaction id — the restarted service answers requests the
        dead one admitted."""
        from ..node.notary import BatchingNotaryService

        if self.flavour == "distributed":
            self.restart_member(0)
            return
        node = self.members[0]
        old = node.services.notary_service
        self._degraded_flushes_base += _metric_count(
            old.metrics, "Notary.DegradedFlushes"
        )
        had_workers = bool(old._workers)
        old.stop()   # dead worker threads must not keep flushing
        svc = BatchingNotaryService(
            node.services,
            old.uniqueness,
            max_batch=old.max_batch,
            max_wait_micros=old.max_wait_micros,
            qos=self.qos,
            # the replacement boots with the SAME plane shape the dead
            # process ran — a sharded scenario must stay sharded or the
            # post-restart half of the soak tests a different notary
            shards=old.n_shards,
            shard_workers=had_workers,
            degraded_fallback=old.degraded_fallback,
            intent_journal=self.intent_journal,
        )
        node.services.notary_service = svc
        self._drive_tick = svc.tick
        svc.attach_health(self.monitors[node.name])
        # the lifecycle ledger survives the restart (observer plane):
        # attach BEFORE replay so every replayed intent stamps its
        # wal.replay event onto the story the dead process admitted
        svc.attach_txstory(self.txstory_plane)
        replayed = svc.replay_intents()
        by_tx = {tx_id: fut for _seq, tx_id, fut in replayed}
        for entry in self._live:
            gen, _wait, rec = entry
            if gen is None and rec.outcome is None:
                fut = by_tx.get(rec.tx_id)
                if fut is not None:
                    entry[1] = fut
        self.frozen.discard(node.name)
        self._notary_down = False
        self._beats[node.name].beat()

    # -- submission ----------------------------------------------------------

    def _gateway(self, k: int):
        alive = [m for m in self.members if self.alive[m.name]]
        return alive[k % len(alive)]

    def _submit(self, client, kind, phase, deadline, payload, rival_of=None):
        ftx, inputs, tx_id = payload
        member = self._gateway(self._next_rid)
        rec = RequestRecord(
            rid=self._next_rid,
            client=client.name,
            tx_id=tx_id,
            inputs=tuple(inputs),
            kind=kind,
            phase=phase,
            member=member.name,
            deadline=deadline,
            submitted_at=self.now(),
            rival_of=rival_of,
        )
        self._next_rid += 1
        self.records.append(rec)
        if self.flavour in ("batching", "distributed"):
            # the embedded-driver entry: enqueue without the flow
            # machinery (the flow-path entry gates are pinned by
            # tests/test_qos.py; here the round-rationed tick IS the
            # capacity model, and process()'s flush-at-full-batch
            # fast path would defeat it in zero-cost simulated time)
            fut = member.services.notary_service.submit(
                ftx, client.party,
                deadline=deadline, arrival_micros=self.now(),
            )
            self._live.append([None, fut, rec])
        else:
            trace = None
            if self._tracing:
                # the trace is born at the gateway member (the fleet's
                # stand-in for the client node): a root span whose
                # context the consensus layer threads to every member
                span = self._tracer_for(member.name).start_trace(
                    "notarise.fleet",
                    tx_id=str(tx_id), requester=client.name,
                )
                self._spans[rec.rid] = span
                rec.trace_id = span.trace_id
                trace = tuple(span.context)
            gen = member.services.notary_service.process(
                ftx, client.party, deadline=deadline, trace=trace
            )
            self._live.append([gen, None, rec])
        client.submitted += 1
        return rec

    def _inject_round(self, phase: Phase) -> None:
        s = self.scenario
        mix = s.mix_of(phase)
        n_bulk = int(phase.offered_per_round * mix.bulk_fraction)
        n_interactive = phase.offered_per_round - n_bulk
        now = self.now()
        for i in range(n_interactive):
            client = self.clients[self._client_cursor % len(self.clients)]
            self._client_cursor += 1
            jitter = (
                self._rng.randrange(mix.deadline_jitter_micros + 1)
                if mix.deadline_jitter_micros else 0
            )
            deadline = now + mix.deadline_micros + jitter
            payload = self.source.spend(client)
            if self.verify_pool is not None and i == 0:
                # one spend per round additionally round-trips the
                # out-of-process pool (the verification sidecar the
                # kill_verifier chaos acts on): EVERY one of these
                # futures must resolve, worker churn or not
                stx = payload[0]
                ltx = self.source.owner.services.resolve_transaction(
                    stx.wtx
                )
                self.verify_futures.append(
                    self.verify_pool.verify(ltx, stx)
                )
            rec = self._submit(client, "interactive", phase.name, deadline, payload)
            # deterministic injection: every floor(1/fraction)-th spend
            # gets a rival, so the double-spend count never flakes
            if mix.conflict_fraction and (
                self._next_rid % max(1, round(1 / mix.conflict_fraction)) == 0
            ):
                rival_client = self.clients[
                    (self._next_rid * 31 + 7) % len(self.clients)
                ]
                self._submit(
                    rival_client, "rival", phase.name, deadline,
                    self.source.rival(payload[1]), rival_of=rec.rid,
                )
        for _ in range(n_bulk):
            self._offer_bulk(phase)

    def _offer_bulk(self, phase: Phase) -> None:
        """Bulk (resolution-flood-shaped) traffic enters at the QoS
        lane seam — deadline-less by definition, so brownout level 1
        sheds it there. Batching flavour only."""
        if self.qos is None:
            return
        client = self.clients[self._rng.randrange(len(self.clients))]
        self.bulk_offered += 1
        self._next_uid += 1
        self.qos.lanes.offer(
            Message("tx.resolution", b"", client.name, self._next_uid)
        )

    # -- the loop ------------------------------------------------------------

    def _step_generators(self) -> None:
        from ..flows.api import _WaitFuture

        still = []
        for entry in self._live:
            gen, wait, rec = entry
            if gen is None:
                # future-parked (batching submit path)
                if wait.done:
                    try:
                        self._record_answer(rec, wait.result())
                    except Exception as e:   # noqa: BLE001
                        self._record_answer(
                            rec, NotaryError("unavailable", repr(e))
                        )
                else:
                    still.append(entry)
                continue
            try:
                if wait is None:
                    step = gen.send(None)
                elif wait.future.done:
                    try:
                        value = wait.future.result()
                    except Exception as e:   # noqa: BLE001 - flow-shaped
                        step = gen.throw(e)
                    else:
                        step = gen.send(value)
                else:
                    still.append(entry)
                    continue
                if isinstance(step, _WaitFuture):
                    entry[1] = step
                    still.append(entry)
                else:
                    # notary process() generators only ever park on
                    # futures; anything else is a service bug
                    gen.close()
                    self._record_answer(
                        rec,
                        NotaryError(
                            "unavailable", f"unexpected yield {step!r}"
                        ),
                    )
            except StopIteration as stop:
                self._record_answer(rec, stop.value)
            except Exception as e:   # noqa: BLE001 - service-side failure
                self._record_answer(
                    rec, NotaryError("unavailable", repr(e))
                )
        self._live = still

    def _record_answer(self, rec: RequestRecord, value) -> None:
        rec.answered_at = self.now()
        span = self._spans.pop(rec.rid, None)
        if span is not None:
            span.end()
        if isinstance(value, NotaryError):
            if value.kind == qoslib.SHED_KIND:
                rec.outcome = OUT_SHED
                # ONE canonicalizer (utils/txstory.shed_reason): the
                # model's attribution and the ledger's terminal reason
                # derive from the same function, so a reworded shed
                # message cannot fork the reconciliation
                from ..utils.txstory import shed_reason

                rec.shed_reason = shed_reason(value.message)
            elif value.kind == "conflict":
                rec.outcome = OUT_CONFLICT
            else:
                rec.outcome = OUT_UNAVAILABLE
                rec.shed_reason = value.kind
        elif value is None:
            rec.outcome = OUT_UNAVAILABLE
        else:
            # TransactionSignature (simple/raft) or [sigs] (bft)
            rec.outcome = OUT_SIGNED

    def _sample(self, phase_name: str) -> None:
        healthz = {}
        alerts = {}
        for name, mon in self.monitors.items():
            if self.alive[name]:
                ok, _ = mon.healthz()
                healthz[name] = ok
                alerts[name] = mon.alerts_firing()
            else:
                healthz[name] = False     # a dead node serves nothing
                alerts[name] = None
        rollup = self.cluster.snapshot()
        self.timeline.append({
            "round": self.round_no,
            "at_micros": self.now(),
            "phase": phase_name,
            "healthz": healthz,
            "alerts_firing": alerts,
            "cluster_worst": rollup["worst"],
            "cluster_stale": rollup["stale_peers"],
            "cluster_alerts": rollup["alerts_firing"],
            "brownout_level": (
                self.qos.brownout_level if self.qos is not None else None
            ),
            "lag": {
                m.name: self.consensus_lag(m.name) for m in self.members
            } if self.flavour != "batching" else {},
        })

    def _round(self, phase_name: str) -> None:
        self._step_generators()
        if self._drive_tick is not None and (
            self.members[0].name not in self.frozen
        ):
            # the batching notary's pump tick, exactly once per round
            # (see __init__: the round IS the pump cadence); a frozen
            # pump flushes nothing — requests queue, and anything whose
            # deadline passes while wedged sheds at the thaw
            self._drive_tick()
        self.net.run()
        if self.verify_pool is not None:
            # worker pump round: drain (which heartbeats), deliver the
            # answers, then walk the pool's lease/redispatch state
            for alive, w in zip(
                self._verify_worker_alive, self._verify_workers
            ):
                if alive:
                    w.drain()
            self.net.run()
            self.verify_pool.tick()
        self._step_generators()
        if self.qos is not None:
            # the lane consumer: drain what a real ring consumer would
            self.bulk_served += len(self.qos.lanes.drain(budget=64))
        for name, hb in self._beats.items():
            if self.alive[name] and name not in self.frozen:
                hb.beat(progress=1)
        if self.device_plane is not None and (
            self.alive[self.members[0].name] and not self._notary_down
        ):
            # sample BEFORE the monitor walk so the device rules judge
            # this round's state (sample_gap 0: every round samples)
            self.device_plane.tick()
        if self.wire_plane is not None and (
            self.alive[self.members[0].name] and not self._notary_down
        ):
            self.wire_plane.tick()
        for name, mon in self.monitors.items():
            if self.alive[name]:
                mon.tick()
        self._sample(phase_name)
        self.net.clock.advance(self.scenario.round_micros)
        self.round_no += 1

    def run(self) -> FleetReport:
        s = self.scenario
        started = self.now()
        total = float(s.total_offered())
        offered = 0
        for phase in s.phases:
            for _ in range(phase.rounds):
                self.chaos.step(self, offered / total)
                self._inject_round(phase)
                offered += phase.offered_per_round
                self._round(phase.name)
        self.chaos.finish(self)
        for _ in range(s.drain_rounds):
            self._round("drain")
            if not self._live:
                break
        for gen, wait, rec in self._live:
            rec.outcome = OUT_LOST
            span = self._spans.pop(rec.rid, None)
            if span is not None:
                span.end()
        self._live = []
        for _ in range(s.settle_rounds):
            self._round("settle")
        shed_brownout = 0
        if self.qos is not None:
            shed_brownout = self.qos.snapshot()["shed"].get(
                qoslib.SHED_BROWNOUT_BULK, 0
            )
        verify_resolved = verify_failed = 0
        for fut in self.verify_futures:
            if fut.done:
                try:
                    fut.result()
                    verify_resolved += 1
                except Exception:   # noqa: BLE001 - reconciled below
                    verify_failed += 1
        intent_unresolved = intent_replayed = 0
        if self.intent_journal is not None:
            self.intent_journal.flush_resolved()
            intent_unresolved = self.intent_journal.unresolved_count
            intent_replayed = self.intent_journal.replayed
        has_member_wals = bool(getattr(self, "_member_intents", None))
        if has_member_wals:
            for j in self._member_intents.values():
                j.flush_resolved()
                intent_unresolved += j.unresolved_count
                intent_replayed += j.replayed
        xshard_extra = {}
        if self.flavour == "distributed":
            from ..node.distributed_uniqueness import ShardMap

            sm = ShardMap(
                [m.name for m in self.members], self.cluster_shards
            )
            xshard_extra = dict(
                cluster_shards=self.cluster_shards,
                shard_map={
                    row["partition"]: row["owner"]
                    for row in sm.snapshot()["partitions"]
                },
                xshard_decisions=list(self.xshard_decisions),
                reservations_live={
                    name: p.reservation_count()
                    for name, p in self._xshard_providers.items()
                },
                xshard_orphans={
                    name: p.orphan_count()
                    for name, p in self._xshard_providers.items()
                },
            )
        pool = self.verify_pool
        svc = self.members[0].services.notary_service
        return FleetReport(
            flavour=self.flavour,
            scenario=s,
            records=self.records,
            timeline=self.timeline,
            chaos_log=self.chaos.log,
            ledgers=self.gather_ledgers(),
            members=[m.name for m in self.members],
            monitors=dict(self.monitors),
            qos=self.qos,
            started_micros=started,
            finished_micros=self.now(),
            bulk_offered=self.bulk_offered,
            bulk_shed_brownout=shed_brownout,
            bulk_served=self.bulk_served,
            distinct_clients=len(
                {r.client for r in self.records}
            ),
            intent_wal=self.intent_journal is not None or has_member_wals,
            intent_unresolved=intent_unresolved,
            intent_replayed=intent_replayed,
            verify_offered=len(self.verify_futures),
            verify_resolved=verify_resolved,
            verify_failed=verify_failed,
            verify_redispatched=(
                _metric_count(pool.metrics, "Verifier.Redispatched")
                if pool is not None else 0
            ),
            verify_workers_lost=(
                _metric_count(pool.metrics, "Verifier.WorkersLost")
                if pool is not None else 0
            ),
            device_faults=(
                self.device_injector.faults_raised
                if self.device_injector is not None else 0
            ),
            degraded_flushes=(
                self._degraded_flushes_base
                + _metric_count(svc.metrics, "Notary.DegradedFlushes")
                if self.flavour == "batching" else 0
            ),
            device_telemetry=(
                self.device_plane.snapshot()
                if self.device_plane is not None else None
            ),
            wire_telemetry=(
                self.wire_plane.snapshot()
                if self.wire_plane is not None else None
            ),
            tracers=dict(self.tracers),
            cluster_traces=self.cluster_traces,
            incidents=self.incidents,
            txstory=self.txstory_plane,
            **xshard_extra,
        )

    # -- reconciliation inputs ----------------------------------------------

    def gather_ledgers(self) -> dict:
        """Every ALIVE member's committed map (the reference's
        gather-state step). Batching reads the uniqueness provider;
        raft reads each member's replicated provider map; BFT reads
        each replica's service map."""
        out = {}
        for m in self.members:
            if not self.alive[m.name]:
                continue
            svc = m.services.notary_service
            if self.flavour == "bft":
                out[m.name] = dict(svc.committed)
            else:
                out[m.name] = dict(svc.uniqueness.committed)
        return out


# ---------------------------------------------------------------------------
# invariant checking


class InvariantChecker:
    """Reconciles a FleetReport against the model: the CrossCash
    discipline (value neither lost nor duplicated), extended with the
    control-plane truth checks the ROADMAP calls for. Each method
    raises AssertionError with enough detail to debug; `check_all`
    runs the set that applies to the report's flavour."""

    def __init__(self, report: FleetReport):
        self.report = report

    # -- ledger --------------------------------------------------------------

    def check_replica_agreement(self) -> None:
        """Every alive replica holds the SAME committed map after the
        drain — kill/restart, partition and slow links included."""
        ledgers = self.report.ledgers
        names = sorted(ledgers)
        base = ledgers[names[0]]
        for name in names[1:]:
            if ledgers[name] != base:
                only_a = set(base) - set(ledgers[name])
                only_b = set(ledgers[name]) - set(base)
                raise AssertionError(
                    f"replica ledgers diverged: {names[0]} has "
                    f"{len(base)} entries, {name} has "
                    f"{len(ledgers[name])}; only-{names[0]}={only_a!r} "
                    f"only-{name}={only_b!r}"
                )

    def _ledger(self) -> dict:
        if self.report.flavour == "distributed":
            # the cluster ledger is the UNION of the members' partition
            # slices; a ref claimed by two members with different
            # consumers is a partition-ownership breach, surfaced here
            # before any downstream check trips on it confusingly
            merged: dict = {}
            claimed_by: dict = {}
            for name in sorted(self.report.ledgers):
                for ref, tx in self.report.ledgers[name].items():
                    prior = merged.get(ref)
                    assert prior is None or prior == tx, (
                        f"{ref} committed to {prior} on "
                        f"{claimed_by[ref]} but {tx} on {name} — two "
                        f"members both think they own the ref"
                    )
                    merged[ref] = tx
                    claimed_by[ref] = name
            return merged
        names = sorted(self.report.ledgers)
        return self.report.ledgers[names[0]]

    def check_ledger_vs_answers(self) -> None:
        """Signed answers and the ledger agree EXACTLY:
        - every signed tx's inputs are consumed by that tx on-ledger;
        - every conflict answer's tx is NOT on the ledger;
        - every on-ledger consumer is a transaction somebody submitted
          (no phantom commits);
        - no input consumed by two transactions (no double-spend)."""
        ledger = self._ledger()
        submitted = {r.tx_id for r in self.report.records}
        for ref, tx in ledger.items():
            assert tx in submitted, (
                f"phantom commit: {ref} consumed by never-submitted {tx}"
            )
        for r in self.report.records:
            if r.outcome == OUT_SIGNED:
                for ref in r.inputs:
                    got = ledger.get(ref)
                    assert got == r.tx_id, (
                        f"signed {r.tx_id} but ledger consumes {ref} "
                        f"by {got}"
                    )
            elif r.outcome == OUT_CONFLICT:
                on_ledger = [
                    ref for ref in r.inputs if ledger.get(ref) == r.tx_id
                ]
                assert not on_ledger, (
                    f"conflict answered for {r.tx_id} yet it consumed "
                    f"{on_ledger} on-ledger"
                )
            elif r.outcome == OUT_SHED:
                committed = [
                    ref for ref in r.inputs if ledger.get(ref) == r.tx_id
                ]
                assert not committed, (
                    f"shed {r.tx_id} still committed {committed} — a "
                    f"shed must never spend verify/commit work"
                )

    def check_exactly_one_winner(self) -> None:
        """Every injected double-spend resolved to EXACTLY one winner
        on the ledger, and at most one of the rivals was signed."""
        ledger = self._ledger()
        by_rid = {r.rid: r for r in self.report.records}
        pairs = [
            (by_rid[r.rival_of], r)
            for r in self.report.records
            if r.rival_of is not None
        ]
        assert pairs, "scenario injected no double-spends to check"
        for orig, rival in pairs:
            contested = set(orig.inputs) & set(rival.inputs)
            assert contested, "rival shares no input with its original"
            for ref in contested:
                winner = ledger.get(ref)
                # both shed is legal (overload); both COMMITTED is not
                assert winner in (orig.tx_id, rival.tx_id, None), (
                    f"{ref} consumed by a third transaction {winner}"
                )
            signed = [
                r for r in (orig, rival) if r.outcome == OUT_SIGNED
            ]
            assert len(signed) <= 1, (
                f"double-spend double-signed: {orig.tx_id} AND "
                f"{rival.tx_id}"
            )

    # -- QoS -----------------------------------------------------------------

    def check_no_admitted_then_expired(self) -> None:
        """A signed answer at or before its deadline, always — nothing
        verified-then-useless."""
        for r in self.report.records:
            if r.outcome == OUT_SIGNED and r.deadline is not None:
                assert r.answered_at <= r.deadline, (
                    f"admitted-then-expired: {r.tx_id} signed at "
                    f"{r.answered_at}, deadline {r.deadline}"
                )

    def check_slo(
        self, target_p99_micros: int, phases: tuple[str, ...] = ("steady",)
    ) -> None:
        """Admitted p99 (simulated time) within the SLO for requests
        submitted during the named phases."""
        lat = sorted(
            r.answered_at - r.submitted_at
            for r in self.report.records
            if r.outcome == OUT_SIGNED
            and any(r.phase.startswith(p) for p in phases)
        )
        assert lat, f"no signed steady-state traffic in phases {phases}"
        p99 = lat[min(len(lat) - 1, int(0.99 * len(lat)))]
        assert p99 <= target_p99_micros, (
            f"steady-state admitted p99 {p99} us exceeds the "
            f"{target_p99_micros} us SLO"
        )

    def check_brownout_classes(self) -> None:
        """Brownout shed ONLY the right traffic: bulk at the lane seam
        and deadline-less requests at entry — never an interactive
        request that carried a deadline."""
        for r in self.report.records:
            if r.shed_reason == "brownout":
                assert r.deadline is None, (
                    f"brownout shed deadline-carrying {r.kind} request "
                    f"{r.tx_id}"
                )
        qos = self.report.qos
        assert qos is not None, "brownout check needs the QoS flavour"
        shed = qos.snapshot()["shed"]
        brownout_sheds = {
            k: v for k, v in shed.items() if k.startswith("Brownout")
        }
        assert set(brownout_sheds) <= {
            qoslib.SHED_BROWNOUT_BULK, qoslib.SHED_BROWNOUT_NO_DEADLINE
        }
        assert brownout_sheds, "the spike browned nothing out"

    def check_brownout_engaged_during_spike(self) -> None:
        """The brownout level rose during the spike phase and returned
        to 0 by the end of the drain (the transition history is the
        assertion surface, node/qos.py)."""
        spike = [
            t for t in self.report.timeline if t["phase"].startswith("spike")
        ]
        after = self.report.timeline[-1]
        assert any(t["brownout_level"] >= 1 for t in spike), (
            "brownout never engaged during the spike"
        )
        assert after["brownout_level"] == 0, (
            f"brownout stuck at level {after['brownout_level']} after "
            f"recovery"
        )
        assert self.report.qos.brownout_transitions, (
            "no brownout transitions recorded"
        )

    # -- health truth --------------------------------------------------------

    def _window(self, entry: dict) -> tuple[int, Optional[int]]:
        return entry["applied_at_micros"], entry["reverted_at_micros"]

    def _alert_of(self, member: str, name: str) -> Optional[dict]:
        mon = self.report.monitors.get(member)
        if mon is None:
            return None
        return mon.snapshot().get("alerts", {}).get(name)

    def _samples_between(self, start, end):
        return [
            t for t in self.report.timeline
            if t["at_micros"] >= start and (
                end is None or t["at_micros"] < end
            )
        ]

    def check_health_story(self) -> None:
        """The control plane told the truth about every injected fault:

          kill      — the victim read unhealthy and /cluster marked it
                      stale while down; both recovered after restart.
          freeze    — the victim's WATCHDOG flipped its healthz to
                      unhealthy while its pump was wedged (the node
                      was still reachable — this is the true 503
                      path), and it recovered after the thaw.
          partition — /cluster (served from the majority side) marked
                      the minority member stale during the split and
                      fresh after heal.
          slow      — the victim's consensus-lag alert fired during
                      the window and resolved after.
        """
        tl = self.report.timeline
        assert tl, "no timeline samples"
        final = tl[-1]
        for entry in self.report.chaos_log:
            start, end = self._window(entry)
            during = self._samples_between(start, end)
            victim = entry.get("target")
            if entry["kind"] == "kill":
                assert during, f"no samples during {entry['name']}"
                assert any(
                    not t["healthz"].get(victim, True) for t in during
                ), f"{entry['name']}: victim {victim} never read unhealthy"
                if victim == self.report.members[0]:
                    # the rollup is SERVED from the victim: a dead home
                    # cannot mark itself stale — the outage shows as
                    # everyone ELSE going stale in its view
                    assert any(t["cluster_stale"] for t in during), (
                        f"{entry['name']}: the dead rollup home's "
                        f"/cluster never lost its peers"
                    )
                else:
                    assert any(
                        victim in t["cluster_stale"] for t in during
                    ), (
                        f"{entry['name']}: /cluster never marked "
                        f"{victim} stale"
                    )
            elif entry["kind"] == "freeze":
                assert any(
                    not t["healthz"].get(victim, True) for t in during
                ), (
                    f"{entry['name']}: the watchdog never flipped "
                    f"{victim}'s healthz while its pump was wedged"
                )
                # the victim's own event log carries the flip — the
                # health plane's forensic surface (utils/health.py)
                events = [
                    e for e in self.report.monitors[victim].events.tail(64)
                    if e.get("event") == "healthz"
                ] if self.report.monitors else []
                if self.report.monitors:
                    assert any(not e["ok"] for e in events), (
                        f"{victim}'s health event log never recorded "
                        f"the healthz flip"
                    )
            elif entry["kind"] == "partition":
                if victim == self.report.members[0]:
                    # the rollup is SERVED from the victim: the split
                    # shows as everyone ELSE going stale in its view
                    assert any(t["cluster_stale"] for t in during), (
                        f"{entry['name']}: the minority-side /cluster "
                        f"never marked the majority stale"
                    )
                else:
                    assert any(
                        victim in t["cluster_stale"] for t in during
                    ), (
                        f"{entry['name']}: /cluster never marked the "
                        f"minority {victim} stale"
                    )
            elif entry["kind"] == "slow":
                assert any(
                    (t["cluster_alerts"].get(victim) or 0) > 0
                    or (t["alerts_firing"].get(victim) or 0) > 0
                    for t in during
                ), (
                    f"{entry['name']}: the lag alert never fired for "
                    f"{victim}"
                )
            elif entry["kind"] == "kill_notary":
                # a dead pump is a stalled flush heartbeat: the
                # watchdog must flip healthz while the notary is down
                assert any(
                    not t["healthz"].get(victim, True) for t in during
                ), (
                    f"{entry['name']}: healthz never flipped while the "
                    f"notary was dead"
                )
            elif entry["kind"] == "device_fault":
                # the monitor's fire_count is authoritative unless a
                # notary restart re-registered the rule (wiping its
                # state); the timeline's per-round alert samples carry
                # the firing either way
                alert = self._alert_of(victim, "notary.degraded_mode")
                fired = (
                    alert is not None and alert["fire_count"] >= 1
                ) or any(
                    (t["alerts_firing"].get(victim) or 0) > 0
                    for t in during
                )
                assert fired, (
                    f"{entry['name']}: notary.degraded_mode never fired"
                )
                assert alert is None or alert["state"] != "firing", (
                    f"{entry['name']}: degraded mode never auto-"
                    f"resolved (the recovery probe is not re-arming "
                    f"the device path)"
                )
                # round 15: the device-telemetry plane must tell the
                # SAME story — device.fallback_active bridges the
                # degraded gauge with device evidence, fires while the
                # flushes serve off the CPU reference, and resolves
                # with the probe
                dev_alert = self._alert_of(
                    victim, "device.fallback_active"
                )
                if dev_alert is not None:
                    assert dev_alert["fire_count"] >= 1, (
                        f"{entry['name']}: device.fallback_active "
                        f"never fired while the notary served "
                        f"degraded flushes"
                    )
                    assert dev_alert["state"] != "firing", (
                        f"{entry['name']}: device.fallback_active "
                        f"never resolved after the device path "
                        f"recovered"
                    )
                if self.report.device_telemetry is not None:
                    assert not self.report.device_telemetry[
                        "fallback_active"
                    ], (
                        f"{entry['name']}: the device plane still "
                        f"reports fallback_active at the end of the "
                        f"soak"
                    )
            elif entry["kind"] == "kill_verifier":
                alert = self._alert_of(victim, "verifier.pool_degraded")
                assert alert is not None and alert["fire_count"] >= 1, (
                    f"{entry['name']}: verifier.pool_degraded never "
                    f"fired on the worker loss"
                )
                assert alert["state"] != "firing", (
                    f"{entry['name']}: the pool never recovered "
                    f"(pool_degraded still firing at the end)"
                )
            # recovery: the LAST sample shows a clean fleet
            if victim is not None:
                assert final["healthz"].get(victim, False), (
                    f"{victim} still unhealthy after {entry['name']} "
                    f"reverted"
                )
                assert victim not in final["cluster_stale"], (
                    f"/cluster still stale on {victim} after "
                    f"{entry['name']} reverted"
                )

    def check_lost_bounded(self, max_fraction: float = 0.05) -> None:
        """WITHOUT the intent WAL, requests in flight at a kill may
        lose their reply; the fraction must stay small and the ledger
        invariants above already bound their effect. (With the WAL,
        check_exact_accounting replaces this allowance with an
        equality — check_all picks automatically.)"""
        lost = sum(1 for r in self.report.records if r.outcome == OUT_LOST)
        frac = lost / max(1, len(self.report.records))
        assert frac <= max_fraction, (
            f"{lost}/{len(self.report.records)} requests lost "
            f"({frac:.1%} > {max_fraction:.1%})"
        )

    def check_partition_ownership(self) -> None:
        """Distributed flavour: every committed ref lives on the
        member the ownership map says owns its partition (a replicated
        copy elsewhere is legal IF it agrees — _ledger already rejects
        disagreement)."""
        from ..node.notary import shard_of_ref

        rep = self.report
        assert rep.flavour == "distributed" and rep.shard_map, (
            "partition-ownership check needs the distributed flavour"
        )
        n = rep.cluster_shards
        for name, ledger in rep.ledgers.items():
            for ref in ledger:
                owner = rep.shard_map[shard_of_ref(ref, n)]
                owner_ledger = rep.ledgers.get(owner)
                assert owner_ledger is None or ref in owner_ledger, (
                    f"{ref} committed on {name} but MISSING on its "
                    f"owner {owner} — a commit landed off-partition"
                )

    def check_reservation_ledger(self) -> None:
        """The round-12 reservation-ledger reconciliation:

        1. ZERO live reservations (and zero orphans) on every member
           after the drain — every reserve the chaos window stranded
           was driven to commit or release, nothing leaked.
        2. The shared decision log replayed SERIALLY through a
           reference uniqueness map reproduces the cluster ledger
           bit-exact: accepts commit their inputs (same-tx re-commits
           — WAL replays — are idempotent, like the provider), each
           recorded conflict names a consumer the replay had already
           committed, and the final replay map EQUALS the merged
           ledger."""
        rep = self.report
        assert rep.flavour == "distributed", (
            "reservation-ledger reconciliation is the distributed "
            "flavour's check"
        )
        for name, count in rep.reservations_live.items():
            assert count == 0, (
                f"{name} still holds {count} reservation(s) after the "
                f"drain — orphan recovery leaked"
            )
        for name, count in rep.xshard_orphans.items():
            assert count == 0, f"{name} reports {count} orphan(s)"
        inputs_of = {r.tx_id: r.inputs for r in rep.records}
        replay: dict = {}
        for tx_id, conflict in rep.xshard_decisions:
            refs = inputs_of.get(tx_id, ())
            if conflict is None:
                for ref in refs:
                    prior = replay.get(ref)
                    assert prior is None or prior == tx_id, (
                        f"decision log accepted {tx_id} but the serial "
                        f"replay already committed {ref} to {prior} — "
                        f"the log is out of serialisation order"
                    )
                    replay[ref] = tx_id
            else:
                for ref, consumer in conflict.items():
                    got = replay.get(ref)
                    assert got == consumer, (
                        f"decision log rejected {tx_id} against "
                        f"{consumer} on {ref}, but the serial replay "
                        f"holds {got} — the loser saw a consumer that "
                        f"was not serialised before it"
                    )
        ledger = self._ledger()
        # replay may carry refs of canary-shaped input-less accepts
        # (none in the fleet); the ledger must match the replay EXACTLY
        assert replay == ledger, (
            f"serial replay of the decision log diverges from the "
            f"cluster ledger: {len(replay)} replayed vs {len(ledger)} "
            f"committed; only-replay="
            f"{list(set(replay) - set(ledger))[:3]!r} only-ledger="
            f"{list(set(ledger) - set(replay))[:3]!r}"
        )

    def check_exact_accounting(self) -> None:
        """The intent-WAL-era loss bound, tightened to an EQUALITY:
        every admitted request is committed, rejected or shed — never
        silently dropped, kill-restarts included — and the WAL itself
        drained (no intent is still pending recovery). The in-flight-
        at-kill allowance check_lost_bounded tolerated is gone."""
        assert self.report.intent_wal, (
            "exact accounting needs the intent WAL "
            "(FleetSim(intent_wal=True)); without it use "
            "check_lost_bounded"
        )
        lost = [
            r for r in self.report.records
            if r.outcome in (None, OUT_LOST)
        ]
        assert not lost, (
            f"{len(lost)} admitted request(s) silently dropped despite "
            f"the intent WAL (first: rid={lost[0].rid} "
            f"tx={lost[0].tx_id} phase={lost[0].phase})"
        )
        assert self.report.intent_unresolved == 0, (
            f"{self.report.intent_unresolved} intent(s) still "
            f"unresolved in the WAL after the drain"
        )

    def check_lifecycle_ledger(self) -> None:
        """The round-13 lifecycle-ledger reconciliation — strictly
        stronger than the counter-based accounting above, because it
        replays PER-TRANSACTION stories against the model:

        1. Every submitted request's transaction has a story, and a
           story that reached a terminal reached EXACTLY ONE (the
           intent-WAL replay window's re-answers record `tx.reanswer`,
           never a second terminal).
        2. The terminal kind AGREES with the model's outcome — signed
           <-> committed, conflict <-> rejected, shed <-> shed with
           the MATCHING reason, unavailable <-> unavailable or
           quarantined — and every shed/quarantined/unavailable
           terminal is attributed by a non-empty reason.
        3. Every ADMITTED story (one carrying an admit/replay event)
           reached a terminal; without the intent WAL the only excuse
           is a request the model itself recorded LOST at a kill.
        4. Nothing fell off the ledger (zero evictions): the soak's
           accounting surface is complete, not sampled."""
        from ..utils.txstory import ADMIT_EVENTS, TERMINALS

        rep = self.report
        assert rep.txstory is not None, (
            "lifecycle reconciliation needs FleetSim(txstory=True)"
        )
        assert rep.txstory.evicted == 0, (
            f"{rep.txstory.evicted} stories evicted mid-soak — the "
            f"ledger was sized too small to reconcile against"
        )
        stories = {s["tx_id"]: s for s in rep.txstory.stories()}
        terminal_names = set(TERMINALS.values())
        for tid, s in stories.items():
            terms = [
                e["name"] for e in s["events"]
                if e["name"] in terminal_names
            ]
            assert len(terms) <= 1, (
                f"{tid} recorded {len(terms)} terminal events {terms} "
                f"— exactly-once broken"
            )
        lost_ok = {
            str(r.tx_id) for r in rep.records
            if r.outcome in (None, OUT_LOST)
        }
        for tid, s in stories.items():
            admitted = any(
                e["name"] in ADMIT_EVENTS for e in s["events"]
            )
            if admitted and s["terminal"] is None:
                assert not rep.intent_wal and tid in lost_ok, (
                    f"admitted transaction {tid} never reached a "
                    f"terminal event (events: "
                    f"{[e['name'] for e in s['events']]})"
                )
        expected = {
            OUT_SIGNED: ("committed",),
            OUT_CONFLICT: ("rejected",),
            OUT_SHED: ("shed",),
            # the model folds EVERY non-shed/non-conflict NotaryError
            # into OUT_UNAVAILABLE — typed rejections (invalid-
            # transaction, time-window-invalid) included, which the
            # ledger rightly closes as `rejected`
            OUT_UNAVAILABLE: ("unavailable", "quarantined", "rejected"),
        }
        for r in rep.records:
            tid = str(r.tx_id)
            s = stories.get(tid)
            assert s is not None, (
                f"no lifecycle story for submitted {tid} "
                f"(outcome {r.outcome})"
            )
            if r.outcome in (None, OUT_LOST):
                continue   # rule 3 already bounded these
            kinds = expected[r.outcome]
            assert s["terminal"] in kinds, (
                f"{tid}: model says {r.outcome} but the story closed "
                f"{s['terminal']!r} (reason {s['reason']!r})"
            )
            if r.outcome in (OUT_SHED, OUT_UNAVAILABLE):
                assert s["reason"], (
                    f"{tid}: {s['terminal']} terminal carries no "
                    f"reason attribution"
                )
            if r.outcome == OUT_SHED and r.shed_reason is not None:
                assert s["reason"] == r.shed_reason, (
                    f"{tid}: shed attributed {s['reason']!r} on the "
                    f"ledger but {r.shed_reason!r} in the model"
                )

    def check_verifier_pool(self) -> None:
        """Every verify shipped to the out-of-process pool resolved —
        worker kills included: the lease/redispatch machinery moved
        in-flight nonces to a survivor instead of stranding them."""
        rep = self.report
        assert rep.verify_offered > 0, (
            "verifier-pool check needs FleetSim(verifier_pool=N) traffic"
        )
        unresolved = rep.verify_offered - rep.verify_resolved - (
            rep.verify_failed
        )
        assert unresolved == 0, (
            f"{unresolved}/{rep.verify_offered} pool verifications "
            f"never resolved (stranded in flight)"
        )
        assert rep.verify_failed == 0, (
            f"{rep.verify_failed} pool verifications failed (all fleet "
            f"spends are valid — a failure means a lost/duplicated "
            f"answer path)"
        )
        killed = [
            e for e in rep.chaos_log if e["kind"] == "kill_verifier"
        ]
        if killed:
            assert rep.verify_workers_lost >= len(killed), (
                "a worker was killed but the pool never detached it "
                "(lease expiry broken)"
            )
            assert rep.verify_redispatched > 0, (
                "a worker was killed mid-batch yet nothing re-dispatched"
            )

    # -- the bundle ----------------------------------------------------------

    def check_all(
        self,
        slo_p99_micros: Optional[int] = None,
        expect_conflicts: bool = True,
        expect_brownout: bool = False,
    ) -> dict:
        """The full reconciliation; returns a JSON-safe verdict dict
        (bench.py's fleet metric embeds it). With an IncidentRecorder
        on the report, a FAILED check snapshots a reconciliation
        bundle (the failure text, the chaos log, the monitors' event
        story) and the re-raised AssertionError CITES its id — the
        forensics artifact is minted at the moment the invariant
        broke, not reconstructed from memory later."""
        try:
            self._check_all_inner(
                slo_p99_micros, expect_conflicts, expect_brownout
            )
        except AssertionError as e:
            incident_id = self._record_reconciliation_failure(e)
            if incident_id is not None:
                raise AssertionError(
                    f"{e} [incident {incident_id}]"
                ) from e
            raise
        return self._verdict()

    def _check_all_inner(
        self,
        slo_p99_micros: Optional[int],
        expect_conflicts: bool,
        expect_brownout: bool,
    ) -> None:
        if self.report.flavour == "distributed":
            # partition-disjoint slices, not replicas: ownership and
            # the reservation-ledger reconciliation replace replica
            # agreement
            self.check_partition_ownership()
            self.check_reservation_ledger()
        else:
            self.check_replica_agreement()
        self.check_ledger_vs_answers()
        if expect_conflicts:
            self.check_exactly_one_winner()
        self.check_no_admitted_then_expired()
        if self.report.intent_wal:
            # the WAL turns the loss allowance into an equality
            self.check_exact_accounting()
        else:
            self.check_lost_bounded()
        if self.report.txstory is not None:
            # per-transaction accounting, strictly stronger than the
            # counter equality above
            self.check_lifecycle_ledger()
        if self.report.verify_offered:
            self.check_verifier_pool()
        if slo_p99_micros is not None:
            self.check_slo(slo_p99_micros)
        if expect_brownout:
            self.check_brownout_classes()
            self.check_brownout_engaged_during_spike()
        if self.report.chaos_log:
            self.check_health_story()

    def _record_reconciliation_failure(self, exc) -> Optional[str]:
        recorder = self.report.incidents
        if recorder is None:
            return None
        # the slowest signed requests' trace ids: the bundle pulls
        # their cross-node assemblies when the sim traced them
        traced = sorted(
            (
                r for r in self.report.records
                if r.trace_id is not None and r.answered_at is not None
            ),
            key=lambda r: r.answered_at - r.submitted_at,
            reverse=True,
        )
        evidence = {
            "traces": [
                {"trace_id": f"{r.trace_id:#x}"} for r in traced[:3]
            ],
        }
        monitors = self.report.monitors
        home = self.report.members[0] if self.report.members else None
        try:
            return recorder.record(
                "reconciliation",
                "fleet.invariant_failed",
                detail={"failure": str(exc)},
                severity="critical",
                evidence=evidence,
                monitor=monitors.get(home) if home else None,
                node=home,
            )
        except Exception:
            return None   # forensics must not mask the real failure

    def _verdict(self) -> dict:
        out = self.report.outcomes()
        return {
            "reconciled": True,
            "flavour": self.report.flavour,
            "requests": len(self.report.records),
            "distinct_clients": self.report.distinct_clients,
            "outcomes": out,
            "sim_seconds": round(self.report.sim_seconds, 6),
            "goodput_per_sim_sec": round(
                out.get(OUT_SIGNED, 0) / max(self.report.sim_seconds, 1e-9),
                3,
            ),
            "faults": [e["name"] for e in self.report.chaos_log],
            "lifecycle_ledger": (
                self.report.txstory.snapshot()
                if self.report.txstory is not None else None
            ),
            "fault_plane": {
                "intent_wal": self.report.intent_wal,
                "intent_replayed": self.report.intent_replayed,
                "intent_unresolved": self.report.intent_unresolved,
                "verify_offered": self.report.verify_offered,
                "verify_resolved": self.report.verify_resolved,
                "verify_redispatched": self.report.verify_redispatched,
                "verify_workers_lost": self.report.verify_workers_lost,
                "device_faults": self.report.device_faults,
                "degraded_flushes": self.report.degraded_flushes,
            },
        }
