"""Tiny flows used by Ring-3 tests and demos (ping/pong, echo).

Module-level so checkpoint restore can re-import them
(statemachine._reconstruct_logic).
"""

from __future__ import annotations

from ..core.identity import Party
from ..flows.api import FlowLogic, initiated_by, initiating_flow


@initiating_flow
class PingFlow(FlowLogic):
    """Send `count` pings, expect incremented replies."""

    def __init__(self, other: Party, count: int = 1):
        self.other = other
        self.count = count

    def call(self):
        total = 0
        for i in range(self.count):
            reply = yield from self.send_and_receive(self.other, i, int)
            if reply != i + 1:
                raise AssertionError(f"bad pong {reply} for ping {i}")
            total += reply
        return total


@initiated_by(PingFlow)
class PongFlow(FlowLogic):
    def __init__(self, other: Party):
        self.other = other

    def call(self):
        while True:
            try:
                n = yield from self.receive(self.other, int)
            except Exception:
                return None   # session ended
            yield from self.send(self.other, n + 1)


@initiating_flow
class OneShotPingFlow(FlowLogic):
    """Single round-trip (responder ends after one reply)."""

    def __init__(self, other: Party, value: int = 7):
        self.other = other
        self.value = value

    def call(self):
        reply = yield from self.send_and_receive(self.other, self.value, int)
        return reply


@initiated_by(OneShotPingFlow)
class OneShotPongFlow(FlowLogic):
    def __init__(self, other: Party):
        self.other = other

    def call(self):
        n = yield from self.receive(self.other, int)
        yield from self.send(self.other, n * 2)
        return n


from dataclasses import dataclass

from ..core import serialization as ser
from ..core.contracts import UniqueIdentifier
from ..core.transactions import TransactionBuilder


@ser.serializable
@dataclass(frozen=True)
class DummyLinearState:
    """Minimal LinearState for vault/scheduler tests (reference:
    test-utils DummyLinearContract.State)."""

    linear_id: UniqueIdentifier
    info: str
    owner: object   # PublicKey

    @property
    def participants(self):
        return (self.owner,)


class _DummyLinearContract:
    def verify(self, ltx) -> None:
        pass


DUMMY_LINEAR_CONTRACT = "test.DummyLinear"


def make_linear_state_tx(node, notary: Party, linear_id, info: str):
    """Build, self-sign and record a tx issuing one DummyLinearState."""
    from ..core.contracts import register_contract

    register_contract(DUMMY_LINEAR_CONTRACT, _DummyLinearContract())
    b = TransactionBuilder(notary=notary)
    b.add_output_state(
        DummyLinearState(linear_id, info, node.party.owning_key),
        DUMMY_LINEAR_CONTRACT,
    )
    stx = node.services.sign_initial_transaction(b)
    node.services.record_transactions([stx])
    return stx


@ser.serializable
@dataclass(frozen=True)
class HeartbeatState:
    """SchedulableState test fixture: beats `count` up to `target`, one
    beat every `period_micros` (reference: NodeSchedulerServiceTest's
    TestState + ScheduledFlow in samples/irs-demo fixing logic)."""

    owner: object                  # PublicKey
    count: int
    target: int
    due_micros: int
    period_micros: int

    @property
    def participants(self):
        return (self.owner,)

    def next_scheduled_activity(self, this_state_ref):
        if self.count >= self.target:
            return None
        from ..core.contracts import ScheduledActivity

        return ScheduledActivity(
            "corda_tpu.testing.flows.HeartbeatFlow",
            (this_state_ref,),
            self.due_micros,
        )


class _HeartbeatContract:
    def verify(self, ltx) -> None:
        pass


HEARTBEAT_CONTRACT = "test.Heartbeat"


def make_heartbeat_tx(node, notary: Party, *, target: int, period: int):
    """Issue a HeartbeatState due `period` micros from now."""
    from ..core.contracts import register_contract

    register_contract(HEARTBEAT_CONTRACT, _HeartbeatContract())
    now = node.services.clock.now_micros()
    b = TransactionBuilder(notary=notary)
    b.add_output_state(
        HeartbeatState(node.party.owning_key, 0, target, now + period, period),
        HEARTBEAT_CONTRACT,
    )
    stx = node.services.sign_initial_transaction(b)
    node.services.record_transactions([stx])
    return stx


@initiating_flow
class HeartbeatFlow(FlowLogic):
    """Scheduler-launched: consume the heartbeat state, emit the next
    beat (count+1) due one period later. Constructor args = (StateRef,)
    per the FlowLogicRef discipline."""

    def __init__(self, ref):
        self.ref = ref

    def call(self):
        from ..flows.core_flows import FinalityFlow

        sar = self.services.vault.state_and_ref(self.ref)
        if sar is None:
            return None   # already consumed (double-fire guard)
        beat: HeartbeatState = sar.state.data
        now = self.services.clock.now_micros()
        b = TransactionBuilder(notary=sar.state.notary)
        b.add_input_state(sar)
        b.add_output_state(
            HeartbeatState(
                beat.owner,
                beat.count + 1,
                beat.target,
                now + beat.period_micros,
                beat.period_micros,
            ),
            HEARTBEAT_CONTRACT,
        )
        stx = self.services.sign_initial_transaction(b)
        stx = yield from self.sub_flow(FinalityFlow(stx))
        return stx.id


@initiating_flow
class NoResponderFlow(FlowLogic):
    """No @initiated_by counterpart: used to test SessionReject."""

    def __init__(self, other: Party):
        self.other = other

    def call(self):
        reply = yield from self.send_and_receive(self.other, 1, int)
        return reply


@initiating_flow
class NoOpFlow(FlowLogic):
    """The empty flow: no IO, returns immediately — the
    NodePerformanceTests round-trip probe (NodePerformanceTests.kt:59)."""

    def call(self):
        return "done"
