"""Generator combinators + the random transaction-graph fuzzer.

Reference: the `Generator` combinator library (client/mock/ — random
tx/event generation for loadtest and the explorer simulation) and
`GeneratedLedger` (verifier/src/integration-test/.../GeneratedLedger.kt
— a property-based random transaction-graph generator: issuance / move
/ exit over random states signed with random-scheme keys, used to fuzz
the out-of-process verifier with 100-tx ledgers, VerifierTests.kt:24-34).

Here the fuzzer doubles as the CPU-vs-TPU bit-exactness instrument
(SURVEY §4 mapping): generated ledgers must verify identically through
the reference CPU path and the batch kernels, including mutated
(corrupted) transactions.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterable, Optional

from ..core.contracts import Amount, Issued, StateAndRef, StateRef
from ..core.identity import Party, PartyAndReference
from ..core.transactions import SignedTransaction, TransactionBuilder
from ..crypto import schemes
from ..finance.cash import (
    CASH_CONTRACT,
    CashExit,
    CashIssue,
    CashMove,
    CashState,
)


# ---------------------------------------------------------------------------
# combinators (client/mock/Generator.kt)


class Generator:
    """A deterministic random-value recipe: `generate(rng)` draws one
    value. Composes with map/flat_map/choice/frequency like the
    reference's monadic Generator."""

    def __init__(self, fn: Callable[[random.Random], Any]):
        self._fn = fn

    def generate(self, rng: random.Random) -> Any:
        return self._fn(rng)

    # -- composition ---------------------------------------------------------

    @staticmethod
    def pure(value: Any) -> "Generator":
        return Generator(lambda rng: value)

    def map(self, f: Callable[[Any], Any]) -> "Generator":
        return Generator(lambda rng: f(self.generate(rng)))

    def flat_map(self, f: Callable[[Any], "Generator"]) -> "Generator":
        return Generator(lambda rng: f(self.generate(rng)).generate(rng))

    @staticmethod
    def combine(*gens: "Generator", f: Callable = lambda *xs: xs) -> "Generator":
        return Generator(lambda rng: f(*(g.generate(rng) for g in gens)))

    # -- primitives ----------------------------------------------------------

    @staticmethod
    def int_range(lo: int, hi: int) -> "Generator":
        """Uniform integer in [lo, hi] inclusive."""
        return Generator(lambda rng: rng.randint(lo, hi))

    @staticmethod
    def bytes_of(n: int) -> "Generator":
        return Generator(lambda rng: rng.getrandbits(8 * n).to_bytes(n, "big"))

    @staticmethod
    def sampled_from(items: Iterable[Any]) -> "Generator":
        items = list(items)
        return Generator(lambda rng: items[rng.randrange(len(items))])

    @staticmethod
    def choice(gens: Iterable["Generator"]) -> "Generator":
        gens = list(gens)
        return Generator(
            lambda rng: gens[rng.randrange(len(gens))].generate(rng)
        )

    @staticmethod
    def frequency(weighted: Iterable[tuple[int, "Generator"]]) -> "Generator":
        weighted = list(weighted)
        total = sum(w for w, _ in weighted)

        def draw(rng: random.Random):
            roll = rng.randrange(total)
            acc = 0
            for w, g in weighted:
                acc += w
                if roll < acc:
                    return g.generate(rng)
            raise AssertionError("unreachable")

        return Generator(draw)

    def list_of(self, count) -> "Generator":
        count_gen = (
            count if isinstance(count, Generator) else Generator.pure(count)
        )

        def draw(rng: random.Random):
            return [
                self.generate(rng) for _ in range(count_gen.generate(rng))
            ]

        return Generator(draw)


# ---------------------------------------------------------------------------
# the ledger fuzzer (GeneratedLedger.kt)

BATCHABLE_SCHEMES = (
    schemes.EDDSA_ED25519_SHA512,
    schemes.ECDSA_SECP256K1_SHA256,
    schemes.ECDSA_SECP256R1_SHA256,
)


class GeneratedLedger:
    """A random but VALID transaction graph over the Cash contract:
    issuances create value, moves shuffle ownership (conserving),
    exits destroy value — every transaction properly signed by keys
    drawn from all three batchable schemes. `transactions` is in
    topological (generation) order; `store` resolves by id."""

    def __init__(self, seed: int = 0, n_parties: int = 6, notary_scheme=None):
        self.rng = random.Random(seed)
        self.parties: list[tuple[Party, schemes.KeyPair]] = []
        for i in range(n_parties):
            scheme = BATCHABLE_SCHEMES[i % len(BATCHABLE_SCHEMES)]
            kp = schemes.generate_keypair(
                scheme, seed=self.rng.getrandbits(128)
            )
            self.parties.append((Party(f"P{i}", kp.public), kp))
        nkp = schemes.generate_keypair(
            notary_scheme or schemes.EDDSA_ED25519_SHA512,
            seed=self.rng.getrandbits(128),
        )
        self.notary = Party("GenNotary", nkp.public)
        self.notary_kp = nkp
        self.transactions: list[SignedTransaction] = []
        self.store: dict = {}
        # unspent: StateAndRef list (all CashState)
        self.unspent: list[StateAndRef] = []

    # -- steps ---------------------------------------------------------------

    def _keypair_of(self, key) -> schemes.KeyPair:
        for p, kp in self.parties:
            if p.owning_key == key:
                return kp
        raise KeyError("unknown owner key")

    def _record(self, stx: SignedTransaction) -> SignedTransaction:
        self.transactions.append(stx)
        self.store[stx.id] = stx
        for ref in stx.wtx.inputs:
            self.unspent = [s for s in self.unspent if s.ref != ref]
        for i, ts in enumerate(stx.wtx.outputs):
            if isinstance(ts.data, CashState):
                self.unspent.append(StateAndRef(ts, StateRef(stx.id, i)))
        return stx

    def issue(self) -> SignedTransaction:
        issuer, issuer_kp = self.parties[
            self.rng.randrange(len(self.parties))
        ]
        owner, _ = self.parties[self.rng.randrange(len(self.parties))]
        token = Issued(
            PartyAndReference(issuer, bytes([self.rng.randrange(1, 4)])),
            self.rng.choice(["USD", "EUR", "GBP"]),
        )
        qty = self.rng.randint(1, 10_000)
        b = TransactionBuilder(self.notary)
        b.add_output_state(
            CashState(Amount(qty, token), owner.owning_key), CASH_CONTRACT
        )
        b.add_command(CashIssue(self.rng.getrandbits(32)), issuer.owning_key)
        wtx = b.to_wire_transaction()
        sig = _sign(issuer_kp, wtx.id)
        return self._record(SignedTransaction(wtx, (sig,)))

    def move(self) -> Optional[SignedTransaction]:
        if not self.unspent:
            return None
        k = self.rng.randint(1, min(3, len(self.unspent)))
        picked = self.rng.sample(self.unspent, k)
        b = TransactionBuilder(self.notary)
        signers = []
        by_token: dict = {}
        for sar in picked:
            b.add_input_state(sar)
            data = sar.state.data
            by_token[data.amount.token] = (
                by_token.get(data.amount.token, 0) + data.amount.quantity
            )
            signers.append(data.owner)
        for token, total in sorted(
            by_token.items(), key=lambda kv: str(kv[0])
        ):
            # split into 1-2 outputs to random owners, conserving
            split = (
                [total]
                if total < 2 or self.rng.random() < 0.5
                else [total // 2, total - total // 2]
            )
            for part in split:
                owner, _ = self.parties[self.rng.randrange(len(self.parties))]
                b.add_output_state(
                    CashState(Amount(part, token), owner.owning_key),
                    CASH_CONTRACT,
                )
        b.add_command(CashMove(), *dict.fromkeys(signers))
        wtx = b.to_wire_transaction()
        sigs = tuple(
            _sign(self._keypair_of(key), wtx.id)
            for key in dict.fromkeys(signers)
        )
        return self._record(SignedTransaction(wtx, sigs))

    def exit(self) -> Optional[SignedTransaction]:
        # exits need issuer signature AND owner signature; pick a state
        # and have both sign (issuer may differ from owner)
        if not self.unspent:
            return None
        sar = self.rng.choice(self.unspent)
        data = sar.state.data
        b = TransactionBuilder(self.notary)
        b.add_input_state(sar)
        exit_qty = self.rng.randint(1, data.amount.quantity)
        change = data.amount.quantity - exit_qty
        if change:
            b.add_output_state(
                CashState(Amount(change, data.amount.token), data.owner),
                CASH_CONTRACT,
            )
        issuer_key = data.issuer.owning_key
        b.add_command(
            CashExit(Amount(exit_qty, data.amount.token)),
            issuer_key,
            data.owner,
        )
        wtx = b.to_wire_transaction()
        keys = list(dict.fromkeys([issuer_key, data.owner]))
        sigs = tuple(_sign(self._keypair_of(k), wtx.id) for k in keys)
        return self._record(SignedTransaction(wtx, sigs))

    def grow(self, n: int) -> "GeneratedLedger":
        """Generate n transactions (issuance-weighted early, like the
        reference's 100-tx ledgers)."""
        while len(self.transactions) < n:
            if not self.unspent or self.rng.random() < 0.35:
                self.issue()
            elif self.rng.random() < 0.85:
                self.move()
            else:
                self.exit()
        return self

    # -- resolution (what the verifier needs) --------------------------------

    def resolve(self, wtx) -> "Any":
        """WireTransaction -> LedgerTransaction against this ledger."""
        from ..core.contracts import CommandWithParties, StateAndRef
        from ..core.transactions import LedgerTransaction

        inputs = []
        for ref in wtx.inputs:
            stx = self.store[ref.txhash]
            inputs.append(
                StateAndRef(stx.wtx.outputs[ref.index], ref)
            )
        commands = tuple(
            CommandWithParties(c.signers, (), c.value) for c in wtx.commands
        )
        return LedgerTransaction(
            tuple(inputs), wtx.outputs, commands, (), wtx.notary,
            wtx.time_window, wtx.id,
        )

    def all_signatures(self):
        """[(pubkey, signature, signed-payload-bytes)] for every sig in
        the ledger — the batch-verifier fuzz corpus."""
        out = []
        for stx in self.transactions:
            for sig in stx.sigs:
                out.append(
                    (sig.by, sig.signature, sig.signable_payload(stx.id))
                )
        return out


def _sign(kp: schemes.KeyPair, tx_id):
    from ..crypto.tx_signature import sign_tx_id

    return sign_tx_id(kp.private, tx_id)
