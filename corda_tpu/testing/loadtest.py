"""Load test harness: command generators, injectors, disruptions, reconciliation.

Reference: `tools/loadtest` (LoadTest.kt:40-70 — generate random
commands from a seeded Generator, apply via RPC, gather node state,
reconcile against the expected model) with `Disruption`s
(Disruption.kt:17-73 — SIGSTOP hangs, restarts, kills interleaved with
traffic) and the fixed-rate/tight-loop injectors of
testing/performance/{Injectors,Rate}.kt (NodePerformanceTests.kt uses
them for the empty-flow and self-pay rates).

The harness drives real node processes through the Driver DSL; the
model is the expected per-node cash position, reconciled via vault
queries at the end (CrossCashTest.kt's invariant)."""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..finance.cash import CashIssueFlow, CashPaymentFlow
from ..node.vault_query import FungibleAssetQueryCriteria, PageSpecification
from .driver import Driver, NodeHandle


@dataclass
class LoadResult:
    submitted: int
    succeeded: int
    failed: int
    elapsed_s: float
    reconciled: bool
    expected: dict
    actual: dict

    @property
    def throughput(self) -> float:
        return self.succeeded / self.elapsed_s if self.elapsed_s else 0.0


@dataclass
class Disruption:
    """One fault injected mid-run (Disruption.kt). `action(d, handle)`
    runs at `at_fraction` of the way through the command stream.

    `target` pins the victim (e.g. the notary — Disruption.kt's
    `isNetworkMap`/notary-targeted variants pick specific nodes); None
    picks a random traffic node."""

    name: str
    at_fraction: float
    action: Callable[[Driver, NodeHandle], Optional[NodeHandle]]
    target: Optional[NodeHandle] = None


def kill_and_restart(d: Driver, handle: NodeHandle) -> NodeHandle:
    """SIGKILL, then boot a replacement over the same state dir
    (Disruption.kt 'restart' + StabilityTest crash-restart). The spawn
    timeout matches the slow-boot budget soak targets use (a notary
    child with a cold XLA compile cache needs minutes, not the default
    120 s)."""
    handle.kill()
    return d.restart_node(handle, timeout=600.0)


def sigstop_for(seconds: float):
    def action(d: Driver, handle: NodeHandle) -> None:
        handle.sigstop()
        time.sleep(seconds)
        handle.sigcont()
        return None

    return action


class CrossCashLoadTest:
    """Self-issue + cross-pay traffic over a driver network, with an
    expected-balance model (CrossCashTest.kt):

      - issue: node mints `amount` of its own currency to itself
      - pay: node pays a random peer from its balance

    Reconciliation: every node's vault total per (issuer, currency)
    must equal the model's once traffic quiesces."""

    def __init__(
        self,
        d: Driver,
        nodes: list[NodeHandle],
        notary_party,
        seed: int = 0,
        currency: str = "USD",
    ):
        self.d = d
        self.nodes = nodes
        self.notary = notary_party
        self.rng = random.Random(seed)
        self.currency = currency
        self.identities = {n.name: d.identity_of(n) for n in nodes}
        # model: node name -> expected total balance (its own view)
        self.expected: dict[str, int] = {n.name: 0 for n in nodes}

    # -- command stream ------------------------------------------------------

    def _commands(self, count: int):
        for _ in range(count):
            node = self.rng.choice(self.nodes)
            balance = self.expected[node.name]
            if balance < 100 or self.rng.random() < 0.4:
                amount = self.rng.randint(500, 2_000)
                yield ("issue", node, amount, None)
            else:
                peer = self.rng.choice(
                    [n for n in self.nodes if n.name != node.name]
                )
                amount = self.rng.randint(1, balance)
                yield ("pay", node, amount, peer)

    def run(
        self,
        count: int = 30,
        rate_per_s: Optional[float] = None,
        disruptions: tuple[Disruption, ...] = (),
        timeout_per_flow: float = 120.0,
    ) -> LoadResult:
        """Apply `count` commands (optionally rate-limited — the
        FixedRateInjector; None = tight loop, the TightLoopInjector),
        interleaving disruptions, then reconcile."""
        submitted = succeeded = failed = 0
        pending_disruptions = sorted(
            disruptions, key=lambda di: di.at_fraction
        )
        t0 = time.monotonic()
        for i, (kind, node, amount, peer) in enumerate(
            self._commands(count)
        ):
            while (
                pending_disruptions
                and i >= pending_disruptions[0].at_fraction * count
            ):
                di = pending_disruptions.pop(0)
                target = di.target or self.rng.choice(self.nodes)
                replacement = di.action(self.d, target)
                if replacement is not None:
                    self.nodes = [
                        replacement if n.name == target.name else n
                        for n in self.nodes
                    ]
            if rate_per_s is not None:
                target_t = t0 + i / rate_per_s
                now = time.monotonic()
                if now < target_t:
                    time.sleep(target_t - now)
            submitted += 1
            try:
                self._apply(kind, node, amount, peer, timeout_per_flow)
                succeeded += 1
            except Exception:
                failed += 1
        elapsed = time.monotonic() - t0
        actual = self.gather()
        return LoadResult(
            submitted, succeeded, failed, elapsed,
            actual == self.expected, dict(self.expected), actual,
        )

    def _apply(self, kind, node, amount, peer, timeout) -> None:
        cli = self.d.rpc(node)
        me = self.identities[node.name]
        if kind == "issue":
            handle = self.d.wait(
                cli.start_flow(
                    CashIssueFlow(amount, self.currency, me, self.notary)
                ),
                timeout,
            )
            self.d.wait(handle.result, timeout)
            self.expected[node.name] += amount
        else:
            handle = self.d.wait(
                cli.start_flow(
                    CashPaymentFlow(
                        amount, self.currency, self.identities[peer.name]
                    )
                ),
                timeout,
            )
            self.d.wait(handle.result, timeout)
            self.expected[node.name] -= amount
            self.expected[peer.name] += amount

    # -- reconciliation ------------------------------------------------------

    def gather(self) -> dict[str, int]:
        """Each node's actual unconsumed total (CrossCashTest's state
        gathering via RPC vault queries)."""
        out = {}
        for node in self.nodes:
            cli = self.d.rpc(node)
            fut = cli.vault_query_by(
                FungibleAssetQueryCriteria(product=self.currency),
                PageSpecification(page_size=10_000),
            )
            page = self.d.wait(fut)
            out[node.name] = sum(
                s.state.data.amount.quantity for s in page.states
            )
        return out


class EmptyFlowLoadTest:
    """The NodePerformanceTests 'empty flow' rate measurement
    (NodePerformanceTests.kt:59-87): round-trip N no-op flows and
    report throughput + average latency."""

    def __init__(self, d: Driver, node: NodeHandle):
        self.d = d
        self.node = node

    def run(self, count: int = 50) -> dict:
        from .flows import NoOpFlow

        cli = self.d.rpc(self.node)
        latencies = []
        t0 = time.monotonic()
        for _ in range(count):
            s = time.monotonic()
            handle = self.d.wait(cli.start_flow(NoOpFlow()))
            self.d.wait(handle.result)
            latencies.append(time.monotonic() - s)
        elapsed = time.monotonic() - t0
        return {
            "count": count,
            "elapsed_s": elapsed,
            "flows_per_s": count / elapsed,
            "avg_latency_ms": 1000 * sum(latencies) / len(latencies),
        }
