"""MockNetwork: N in-process nodes over the manually-pumped fabric.

Reference: test-utils/.../testing/node/MockNode.kt:58 — N AbstractNode
instances in one JVM over an InMemoryMessagingNetwork with deterministic
manual delivery, deterministic identities from seeds
(TestConstants.kt entropyToKeyPair), in-memory persistence, and an
InMemoryTransactionVerifierService. `run()` loops until quiescent
(MockNode runNetwork).

Signature verification uses the CPU reference verifier by default so
Ring-3 tests stay fast; pass a TpuBatchVerifier to exercise the jitted
kernels end-to-end (done once in tests/test_e2e_tpu.py).
"""

from __future__ import annotations

import random
from typing import Optional

from ..core.identity import Party
from ..crypto import schemes
from ..crypto.batch_verifier import BatchSignatureVerifier, CpuBatchVerifier
from ..flows.api import FlowLogic
from ..flows.statemachine import FlowStateMachine, StateMachineManager
from ..node import messaging as msglib
from ..node.notary import (
    InMemoryUniquenessProvider,
    BatchingNotaryService,
    SimpleNotaryService,
    ValidatingNotaryService,
)
from ..node.scheduler import NodeSchedulerService
from ..node.services import (
    IdentityService,
    KeyManagementService,
    NodeInfo,
    NetworkMapCache,
    SERVICE_NOTARY,
    SERVICE_NOTARY_VALIDATING,
    ServiceHub,
    TestClock,
)


class MockNode:
    """One in-process node: ServiceHub + SMM + fabric endpoint."""

    def __init__(
        self,
        network: "MockNetwork",
        name: str,
        *,
        notary: Optional[str] = None,     # None | "simple" | "validating"
        scheme_id: int = schemes.DEFAULT_SCHEME,
        keypair: Optional[schemes.KeyPair] = None,
        notary_shards: int = 1,           # batching: sharded commit plane
    ):
        self.network = network
        self.name = name
        self.scheme_id = scheme_id
        seed = network.rng.getrandbits(256)
        self.keypair = keypair or schemes.generate_keypair(scheme_id, seed=seed)
        self.party = Party(name, self.keypair.public)
        self.notary_kind = notary
        advertised: tuple[str, ...] = ()
        if notary == "simple":
            advertised = (SERVICE_NOTARY,)
        elif notary in ("validating", "batching"):
            advertised = (SERVICE_NOTARY_VALIDATING,)
        elif notary is not None:
            raise ValueError(f"unknown notary type {notary!r}")
        self.info = NodeInfo(name, self.party, advertised)
        kms_rng = random.Random(network.rng.getrandbits(64))
        if network.db_dir is not None:
            from ..node.persistence import (
                PersistentServiceHub,
                PersistentUniquenessProvider,
            )

            self.services = PersistentServiceHub.open(
                f"{network.db_dir}/{name}.db",
                self.info,
                IdentityService(self.party),
                self.keypair,
                network_map_cache=NetworkMapCache(),
                clock=network.clock,
                batch_verifier=network.batch_verifier,
                rng=kms_rng,
            )
            uniqueness = lambda: PersistentUniquenessProvider(  # noqa: E731
                self.services.db
            )
        else:
            self.services = ServiceHub(
                my_info=self.info,
                key_management=KeyManagementService(self.keypair, rng=kms_rng),
                identity=IdentityService(self.party),
                network_map_cache=NetworkMapCache(),
                clock=network.clock,
                batch_verifier=network.batch_verifier,
            )
            uniqueness = InMemoryUniquenessProvider
        from ..node.cordapp import install_cordapp_services

        install_cordapp_services(self.services)
        self.messaging = network.fabric.endpoint(name)
        self.smm = StateMachineManager(
            self.services,
            self.messaging,
            rng=random.Random(network.rng.getrandbits(64)),
        )
        if notary == "simple":
            self.services.notary_service = SimpleNotaryService(
                self.services, uniqueness()
            )
        elif notary == "validating":
            self.services.notary_service = ValidatingNotaryService(
                self.services, uniqueness()
            )
        elif notary == "batching":
            if notary_shards > 1:
                from ..node.notary import ShardedUniquenessProvider

                self.services.notary_service = BatchingNotaryService(
                    self.services,
                    ShardedUniquenessProvider(notary_shards),
                    shards=notary_shards,
                )
            else:
                self.services.notary_service = BatchingNotaryService(
                    self.services, uniqueness()
                )
        self.scheduler = NodeSchedulerService(
            self.services, self.smm.start_flow
        )
        # extra per-pump tick hooks (raft timers etc.); each returns a
        # count of actions so run() can detect quiescence
        self.ticks: list = []
        if notary == "batching":
            # the pump tick IS the batch deadline: requests that arrived
            # during one delivery round share one SPI dispatch
            self.ticks.append(self.services.notary_service.tick)

    # -- conveniences -------------------------------------------------------

    def start_flow(self, logic: FlowLogic) -> FlowStateMachine:
        return self.smm.start_flow(logic)

    def run_flow(self, logic: FlowLogic):
        """start + pump the whole network + return the result."""
        fsm = self.start_flow(logic)
        self.network.run()
        return fsm.result_or_throw()

    @property
    def vault(self):
        return self.services.vault

    def __repr__(self) -> str:
        return f"<MockNode {self.name}>"


class MockNetwork:
    """Deterministic multi-node harness (MockNode.kt:58)."""

    def __init__(
        self,
        seed: int = 42,
        batch_verifier: Optional[BatchSignatureVerifier] = None,
        shuffle_delivery: bool = False,
        db_dir: Optional[str] = None,
        faults: Optional[msglib.FabricFaults] = None,
    ):
        """`faults`: an optional FabricFaults plane (messaging.py) —
        the chaos-injection seam the fleet simulator drives. It shares
        this network's TestClock so slow-link delays advance in
        simulated time; run() then treats blocked/delayed frames as
        quiescent instead of livelocking on them."""
        self.db_dir = db_dir
        self.rng = random.Random(seed)
        self.clock = TestClock()
        if faults is not None and faults._clock is None:
            faults._clock = self.clock
        self.faults = faults
        self.fabric = msglib.InMemoryMessagingNetwork(
            clock=self.clock, faults=faults
        )
        self.batch_verifier = batch_verifier or CpuBatchVerifier()
        self.nodes: list[MockNode] = []
        self._shuffle_seed = (
            self.rng.getrandbits(32) if shuffle_delivery else None
        )

    def create_node(self, name: Optional[str] = None, **kw) -> MockNode:
        node = MockNode(
            self, name or f"Node{len(self.nodes)}", **kw
        )
        self.nodes.append(node)
        self._sync_directories()
        return node

    def create_notary(
        self,
        name: str = "Notary",
        validating: bool = False,
        batching: bool = False,
        shards: int = 1,
    ):
        """`shards` > 1 (batching only) builds the sharded commit
        plane: per-shard flush pipelines over a partitioned in-memory
        uniqueness provider (node/notary.py round 6)."""
        kind = (
            "batching" if batching
            else "validating" if validating
            else "simple"
        )
        return self.create_node(name, notary=kind, notary_shards=shards)

    def create_raft_notary_cluster(
        self,
        n: int = 3,
        name: str = "RaftNotary",
        validating: bool = False,
        scheme_id: int = schemes.DEFAULT_SCHEME,
        tracer_factory=None,
        metrics_factory=None,
    ):
        """n MockNodes forming one Raft notary cluster behind a shared
        service identity (reference: notary-demo Raft cluster,
        RaftUniquenessProvider.kt). Returns (service_party, members).
        Elect a leader before notarising: run() + advance_clock loops
        (see tests/test_raft_notary.py drive helper). `scheme_id` picks
        the member/service signature scheme — fleet soaks use secp256r1
        (cheap pure-python keygen/sign) so thousand-request runs fit in
        CI seconds. `tracer_factory(member_name)` / `metrics_factory(
        member_name)` optionally hand each member its OWN tracer /
        metric registry — consensus-phase spans and Raft.Phase.* timers
        land per member, the shape cross-node trace assembly tests
        against (None keeps the bare protocol)."""
        import random as _random

        from ..core.identity import Party
        from ..node.notary import SimpleNotaryService, ValidatingNotaryService
        from ..node.raft import RaftNode, RaftUniquenessProvider

        shared_kp = schemes.generate_keypair(
            scheme_id, seed=self.rng.getrandbits(256)
        )
        service_party = Party(name, shared_kp.public)
        member_names = [f"{name}-{i}" for i in range(n)]
        members = []
        for mname in member_names:
            node = self.create_node(mname, scheme_id=scheme_id)
            node.services.key_management.register_keypair(shared_kp)
            node.info = NodeInfo(
                mname,
                node.party,
                (SERVICE_NOTARY_VALIDATING,) if validating else (SERVICE_NOTARY,),
                cluster_identity=service_party,
            )
            node.services.my_info = node.info

            def factory(apply_fn, _node=node, _mname=mname, **raft_kw):
                raft = RaftNode(
                    _mname,
                    member_names,
                    _node.messaging,
                    apply_fn,
                    self.clock,
                    db=getattr(_node.services, "db", None),
                    rng=_random.Random(self.rng.getrandbits(32)),
                    tracer=(
                        tracer_factory(_mname) if tracer_factory else None
                    ),
                    metrics=(
                        metrics_factory(_mname) if metrics_factory else None
                    ),
                    **raft_kw,
                )
                _node.raft = raft
                _node.ticks.append(raft.tick)
                return raft

            def rebuild(_node=node, _factory=factory):
                """Kill/restart seam (testing/fleet.py): discard the
                member's raft state machine and provider, build fresh
                ones over the SAME fabric endpoint (dedupe set and
                journal survive, like a real node restarting over its
                database), and let the cluster's own state transfer —
                AppendEntries replay / InstallSnapshot — restore the
                committed map. The previous raft instance must be
                stop()ped first (handler removal)."""
                provider = RaftUniquenessProvider(_factory)
                cls = (
                    ValidatingNotaryService if validating
                    else SimpleNotaryService
                )
                _node.services.notary_service = cls(
                    _node.services, provider, service_identity=service_party
                )
                return _node.services.notary_service

            node.rebuild_cluster_member = rebuild
            rebuild()
            members.append(node)
        self._sync_directories()
        return service_party, members

    def create_bft_notary_cluster(
        self,
        n: int = 4,
        name: str = "BFTNotary",
        scheme_id: int = schemes.DEFAULT_SCHEME,
        tracer_factory=None,
        metrics_factory=None,
    ):
        """3f+1 MockNodes forming a BFT notary cluster. The service
        identity is a CompositeKey(threshold=f+1) over the member keys
        (reference: BFTNonValidatingNotaryService.kt:29 + the cluster
        composite identity in BFTSMaRt.kt). Returns (party, members).
        `scheme_id` picks the member scheme (fleet soaks: secp256r1,
        the cheap pure-python path)."""
        import random as _random

        from ..core.identity import Party
        from ..crypto.composite import CompositeKey
        from ..node.bft import BftReplica, BFTNotaryService

        member_names = [f"{name}-{i}" for i in range(n)]
        members = [
            self.create_node(m, scheme_id=scheme_id) for m in member_names
        ]
        f = (n - 1) // 3
        composite = CompositeKey.build(
            [m.party.owning_key for m in members], threshold=f + 1
        )
        service_party = Party(name, composite)
        member_keys = {m.name: m.party.owning_key for m in members}
        for node in members:
            node.info = NodeInfo(
                node.name,
                node.party,
                (SERVICE_NOTARY,),
                cluster_identity=service_party,
            )
            node.services.my_info = node.info

            def rebuild(_node=node):
                """Kill/restart seam (testing/fleet.py): a FRESH replica
                over the same endpoint — empty uniqueness map, exec_seq
                1 — restored by the cluster's own catch-up/state-
                transfer machinery (CatchUpRequest -> _restore). The
                previous replica must be stop()ped first."""
                replica = BftReplica(
                    _node.name,
                    member_names,
                    _node.messaging,
                    lambda cmd, ts: (None, None),   # rewired by the service
                    self.clock,
                    cluster=name,
                    rng=_random.Random(self.rng.getrandbits(32)),
                    tracer=(
                        tracer_factory(_node.name)
                        if tracer_factory else None
                    ),
                    metrics=(
                        metrics_factory(_node.name)
                        if metrics_factory else None
                    ),
                )
                _node.bft = replica
                _node.ticks.append(replica.tick)
                _node.services.notary_service = BFTNotaryService(
                    _node.services,
                    replica,
                    service_party,
                    member_keys=member_keys,
                )
                return _node.services.notary_service

            node.rebuild_cluster_member = rebuild
            rebuild()
        self._sync_directories()
        return service_party, members

    def elect(self, members, max_rounds: int = 300):
        """Advance time until the cluster settles on a leader."""
        from ..node.raft import LEADER

        for _ in range(max_rounds):
            self.clock.advance(20_000)
            self.run()
            leaders = [m for m in members if m.raft.role == LEADER]
            if len(leaders) == 1 and all(
                m.raft.leader == leaders[0].raft.name
                for m in members
                if m is not leaders[0]
            ):
                return leaders[0]
        raise AssertionError("raft notary cluster failed to elect")

    def restart_node(self, node: MockNode) -> MockNode:
        """Kill a node and boot a replacement from its database — the
        reference's crash-recovery test move (StateMachineManager
        restoreFibersFromCheckpoints, MockNode restart). Requires
        db_dir. The new node re-registers, restores checkpoints, and
        resumes flows on the next pump. The replacement reuses the same
        fabric endpoint object, so the receiver-side dedupe set and id
        counter survive — the in-memory stand-in for the durable
        fabric's persisted dedupe table."""
        if self.db_dir is None:
            raise RuntimeError("restart_node requires MockNetwork(db_dir=...)")
        node.scheduler.stop()
        node.smm.stop()
        node.services.db.close()
        node.messaging.running = False
        self.nodes.remove(node)
        replacement = MockNode(
            self,
            node.name,
            notary=node.notary_kind,
            scheme_id=node.scheme_id,
            keypair=node.keypair,
        )
        self.nodes.append(replacement)
        self._sync_directories()
        replacement.messaging.running = True
        replacement.smm.restore_checkpoints()
        return replacement

    def _sync_directories(self) -> None:
        """Every node learns every node (the reference's network-map
        registration round, instant here)."""
        for node in self.nodes:
            for other in self.nodes:
                node.services.network_map_cache.add_node(other.info)
                node.services.identity.register(other.party)

    def run(self, pump_limit: int = 100_000) -> int:
        """Deliver messages until quiescent; returns count delivered."""
        rng = (
            random.Random(self._shuffle_seed)
            if self._shuffle_seed is not None
            else None
        )
        total = 0
        rounds = 0
        while True:
            while True:
                got = self.fabric.pump(1, rng)
                if not got:
                    break   # drained, or frames blocked/delayed by faults
                total += got
                if total > pump_limit:
                    raise RuntimeError("network did not quiesce (livelock?)")
            # quiescent on messages: fire any due scheduled activities
            # (the reference's scheduler thread wakes on its own; in
            # Ring 3 the pump is the only driver, so ticks interleave
            # deterministically with delivery)
            actions = sum(n.scheduler.tick() for n in self.nodes)
            actions += sum(t() for n in self.nodes for t in n.ticks)
            actions += sum(n.smm.tick() for n in self.nodes)
            if not actions and not self.fabric.deliverable:
                return total
            rounds += 1
            if rounds > pump_limit:
                # scheduled flows that keep producing immediately-due
                # activities without any messaging never quiesce either
                raise RuntimeError("scheduler did not quiesce (livelock?)")
