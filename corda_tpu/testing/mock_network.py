"""MockNetwork: N in-process nodes over the manually-pumped fabric.

Reference: test-utils/.../testing/node/MockNode.kt:58 — N AbstractNode
instances in one JVM over an InMemoryMessagingNetwork with deterministic
manual delivery, deterministic identities from seeds
(TestConstants.kt entropyToKeyPair), in-memory persistence, and an
InMemoryTransactionVerifierService. `run()` loops until quiescent
(MockNode runNetwork).

Signature verification uses the CPU reference verifier by default so
Ring-3 tests stay fast; pass a TpuBatchVerifier to exercise the jitted
kernels end-to-end (done once in tests/test_e2e_tpu.py).
"""

from __future__ import annotations

import random
from typing import Optional

from ..core.identity import Party
from ..crypto import schemes
from ..crypto.batch_verifier import BatchSignatureVerifier, CpuBatchVerifier
from ..flows.api import FlowLogic
from ..flows.statemachine import FlowStateMachine, StateMachineManager
from ..node import messaging as msglib
from ..node.notary import (
    InMemoryUniquenessProvider,
    SimpleNotaryService,
    ValidatingNotaryService,
)
from ..node.services import (
    IdentityService,
    KeyManagementService,
    NodeInfo,
    NetworkMapCache,
    SERVICE_NOTARY,
    SERVICE_NOTARY_VALIDATING,
    ServiceHub,
    TestClock,
)


class MockNode:
    """One in-process node: ServiceHub + SMM + fabric endpoint."""

    def __init__(
        self,
        network: "MockNetwork",
        name: str,
        *,
        notary: Optional[str] = None,     # None | "simple" | "validating"
        scheme_id: int = schemes.DEFAULT_SCHEME,
    ):
        self.network = network
        self.name = name
        seed = network.rng.getrandbits(256)
        self.keypair = schemes.generate_keypair(scheme_id, seed=seed)
        self.party = Party(name, self.keypair.public)
        advertised: tuple[str, ...] = ()
        if notary == "simple":
            advertised = (SERVICE_NOTARY,)
        elif notary == "validating":
            advertised = (SERVICE_NOTARY_VALIDATING,)
        elif notary is not None:
            raise ValueError(f"unknown notary type {notary!r}")
        self.info = NodeInfo(name, self.party, advertised)
        self.services = ServiceHub(
            my_info=self.info,
            key_management=KeyManagementService(
                self.keypair, rng=random.Random(network.rng.getrandbits(64))
            ),
            identity=IdentityService(self.party),
            network_map_cache=NetworkMapCache(),
            clock=network.clock,
            batch_verifier=network.batch_verifier,
        )
        self.messaging = network.fabric.endpoint(name)
        self.smm = StateMachineManager(
            self.services,
            self.messaging,
            rng=random.Random(network.rng.getrandbits(64)),
        )
        if notary == "simple":
            self.services.notary_service = SimpleNotaryService(
                self.services, InMemoryUniquenessProvider()
            )
        elif notary == "validating":
            self.services.notary_service = ValidatingNotaryService(
                self.services, InMemoryUniquenessProvider()
            )

    # -- conveniences -------------------------------------------------------

    def start_flow(self, logic: FlowLogic) -> FlowStateMachine:
        return self.smm.start_flow(logic)

    def run_flow(self, logic: FlowLogic):
        """start + pump the whole network + return the result."""
        fsm = self.start_flow(logic)
        self.network.run()
        return fsm.result_or_throw()

    @property
    def vault(self):
        return self.services.vault

    def __repr__(self) -> str:
        return f"<MockNode {self.name}>"


class MockNetwork:
    """Deterministic multi-node harness (MockNode.kt:58)."""

    def __init__(
        self,
        seed: int = 42,
        batch_verifier: Optional[BatchSignatureVerifier] = None,
        shuffle_delivery: bool = False,
    ):
        self.rng = random.Random(seed)
        self.fabric = msglib.InMemoryMessagingNetwork()
        self.clock = TestClock()
        self.batch_verifier = batch_verifier or CpuBatchVerifier()
        self.nodes: list[MockNode] = []
        self._shuffle_seed = (
            self.rng.getrandbits(32) if shuffle_delivery else None
        )

    def create_node(self, name: Optional[str] = None, **kw) -> MockNode:
        node = MockNode(
            self, name or f"Node{len(self.nodes)}", **kw
        )
        self.nodes.append(node)
        self._sync_directories()
        return node

    def create_notary(self, name: str = "Notary", validating: bool = False):
        return self.create_node(
            name, notary="validating" if validating else "simple"
        )

    def _sync_directories(self) -> None:
        """Every node learns every node (the reference's network-map
        registration round, instant here)."""
        for node in self.nodes:
            for other in self.nodes:
                node.services.network_map_cache.add_node(other.info)
                node.services.identity.register(other.party)

    def run(self, pump_limit: int = 100_000) -> int:
        """Deliver messages until quiescent; returns count delivered."""
        rng = (
            random.Random(self._shuffle_seed)
            if self._shuffle_seed is not None
            else None
        )
        total = 0
        while self.fabric.pending:
            total += self.fabric.pump(1, rng)
            if total > pump_limit:
                raise RuntimeError("network did not quiesce (livelock?)")
        return total
